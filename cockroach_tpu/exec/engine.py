"""The query engine: sessions, statement dispatch, result materialization.

The analogue of the reference's connExecutor (pkg/sql/conn_executor.go:
1835: run/execCmd -> dispatchToExecutionEngine) minus the wire protocol
(server/ speaks that). Each statement: parse -> bind/plan -> compiled
XLA program (cached) -> device run -> host decode.

Executable caching: keyed by (sql, table generations) — the reference
caches optimized memos per query fingerprint similarly (plan cache).
Table data is uploaded to device HBM once per (table, generation) and
reused across queries (the HBM analogue of the block cache); chunks are
padded to power-of-two row counts so XLA recompiles only on bucket
growth, not every ingest.
"""

from __future__ import annotations

import datetime
import threading
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..kv.concurrency import (Span, TxnAbortedError, TxnRetryError)
from ..kv.txn import DB as KVDB
from ..kv.txn import KVStore, Txn
from ..ops.batch import ColumnBatch
from ..parallel import mesh as meshmod
from ..parallel.distagg import analyze as dist_analyze
from ..parallel.distagg import make_distributed_fn
from ..parallel.mesh import SHARD_AXIS
from ..sql import ast, parser
from ..sql import plan as P
from ..sql.binder import Binder, ColumnBinding, Scope
from ..sql.bound import BConst
from ..sql.planner import CatalogView, Planner
from ..sql.rowenc import ROWID
from ..sql.types import ColumnSchema, Family, TableSchema
from ..storage import keys as K
from ..storage.columnstore import MAX_TS_INT, Chunk, ColumnStore
from ..storage.hlc import Clock, Timestamp
from ..utils.metric import MetricRegistry
from ..utils.mon import BytesMonitor, MemoryQuotaError
from ..utils.settings import SessionVars, Settings
from .compile import (ExecParams, RunContext, can_stream, compile_plan,
                      compile_streaming)
from .expr import ExprContext, compile_expr

EPOCH_DATE = datetime.date(1970, 1, 1)
EPOCH_DT = datetime.datetime(1970, 1, 1)


class EngineError(Exception):
    pass


class HashCapacityExceeded(EngineError):
    """GROUP BY distinct-key count exceeded the device hash table.
    Prepared.run catches this and falls back to hash-partitioned
    re-execution (the spill path)."""


@dataclass
class Result:
    """Decoded query result."""
    names: list[str] = field(default_factory=list)
    rows: list[tuple] = field(default_factory=list)
    row_count: int = 0  # for DML
    tag: str = "SELECT"
    types: list = field(default_factory=list)  # SQLTypes (SELECT only)

    def column(self, name: str) -> list:
        i = self.names.index(name)
        return [r[i] for r in self.rows]

    def __len__(self):
        return len(self.rows)


@dataclass
class Session:
    """Session state (the connExecutor's session data,
    sessiondatapb/session_data.go). An open explicit transaction holds
    a real kv.Txn: DML writes intents through it and buffers its
    scan-plane effects; COMMIT publishes them at the commit timestamp,
    ROLLBACK discards them (the reference's connExecutor txn state
    machine, conn_executor.go:1835)."""
    vars: SessionVars = field(default_factory=SessionVars)
    txn: Optional[Txn] = None
    # ordered (table, op) effects: ("put", key, row) | ("del", key)
    effects: list = field(default_factory=list)
    # a failed statement aborts the whole txn (postgres semantics:
    # "current transaction is aborted" until ROLLBACK) — this keeps
    # statements atomic without kv-level savepoints
    txn_aborted: bool = False
    # SET tracing = on: span recordings per statement, rendered by
    # SHOW TRACE FOR SESSION (the reference's session tracing)
    trace: list = field(default_factory=list)
    # currval() state: sequence name -> last nextval in this session
    seq_currval: dict = field(default_factory=dict)

    @property
    def in_txn(self) -> bool:
        return self.txn is not None

    @property
    def txn_read_ts(self) -> Optional[Timestamp]:
        return self.txn.meta.read_ts if self.txn is not None else None


@dataclass
class Prepared:
    """A planned+compiled SELECT bound to device-resident tables.

    ``dispatch()`` is asynchronous (returns the device-side output
    batch immediately, XLA-style); ``run()`` dispatches and
    materializes. The read timestamp is taken per execution and the
    bound device tables are re-resolved if any scanned table's
    generation moved (DML re-uploads), so a prepared statement sees
    current data under the session's isolation rules, like a pgwire
    portal re-executed after Bind."""

    engine: "Engine"
    session: "Session"
    stmt: "ast.Select"
    sql_text: str
    jfn: object
    scans: dict
    meta: object
    gens: tuple  # ((table, generation), ...) captured at prepare time
    # beyond-HBM paging: (alias, page_rows) of the streamed fact table
    stream: Optional[tuple] = None
    stream_cols: Optional[frozenset] = None
    # AS OF SYSTEM TIME: fixed historical read timestamp
    as_of: Optional[Timestamp] = None

    def _refresh(self) -> "Prepared":
        cur = tuple((t, self.engine.store.table(t).generation)
                    for t, _ in self.gens)
        if cur == self.gens:
            return self
        return self.engine._prepare_select(self.stmt, self.session,
                                           self.sql_text)

    def dispatch(self, read_ts: Optional[Timestamp] = None,
                 nparts: int = 1, pid: int = 0) -> ColumnBatch:
        p = self._refresh()
        if p is not self:
            self.jfn, self.scans, self.meta, self.gens = \
                p.jfn, p.scans, p.meta, p.gens
            self.stream, self.stream_cols = p.stream, p.stream_cols
            self.as_of = p.as_of  # keep guard + execution timestamps
            # consistent (interval forms re-resolve on refresh)
        ts = read_ts or self.as_of or \
            self.engine._read_ts(self.session)
        # np scalar: a jnp.int64() upload would cost a blocking
        # host->device round trip before the query even dispatches.
        tsv = np.int64(ts.to_int())
        if self.stream is None:
            return self.jfn(self.scans, tsv, np.int32(nparts),
                            np.int32(pid))
        # paged execution: every page's upload+compute dispatches
        # asynchronously, so page i+1's host-side assembly overlaps
        # page i's device work (the double-buffering of the
        # reference's byte-limited KV paging, kv_batch_fetcher.go:191)
        _alias, tname, page_rows = self.stream
        fns: _StreamFns = self.jfn
        state = None
        scans = dict(self.scans)
        for page in self.engine._iter_pages(tname, self.stream_cols,
                                            page_rows):
            scans[_alias] = page
            s = fns.page(scans, tsv)
            state = s if state is None else fns.combine(state, s)
        return fns.final(state)

    def run(self, read_ts: Optional[Timestamp] = None) -> "Result":
        tracer = self.engine.tracer
        try:
            with tracer.span("dispatch"):
                out = self.dispatch(read_ts)
            with tracer.span("materialize"):
                return self.engine._materialize(out, self.meta)
        except HashCapacityExceeded:
            # partition-and-recurse (the reference's disk spiller,
            # colexecdisk/disk_spiller.go:75, over HBM re-reads)
            return self.engine._run_partitioned(self, read_ts)


class Engine:
    def __init__(self, store: ColumnStore | None = None,
                 clock: Clock | None = None,
                 settings: Settings | None = None,
                 mesh=None):
        self.store = store or ColumnStore()
        self.clock = clock or Clock()
        # the transactional row plane: DML writes intents here via
        # kv.Txn (latches, tscache, pushes — kv/txn.py) and publishes
        # committed effects into the columnstore scan plane
        self.kv = KVDB(KVStore(clock=self.clock))
        self.settings = settings or Settings()
        # catalog: versioned descriptors in KV + leases (pkg/sql/catalog);
        # the columnstore's TableData.schema is the runtime cache of the
        # PUBLIC schema, kept in sync by the DDL/schema-change paths
        from ..catalog import Catalog, LeaseManager
        self.catalog = Catalog(self.kv)
        self.leases = LeaseManager(self.catalog, holder=f"sql-{id(self)}",
                                   now_ns=lambda: self.clock.now().wall)
        # changefeed event taps (cdc/changefeed.py TableFeed)
        self.cdc_feeds: list = []
        self._cdc_threads: dict[int, threading.Thread] = {}
        # observability: span tracing (util/tracing) + per-statement
        # fingerprint stats (pkg/sql/sqlstats)
        from ..utils.sqlstats import StatsRegistry
        from ..utils.tracing import Tracer
        self.tracer = Tracer()
        self.sqlstats = StatsRegistry()
        # admission control in front of execution (pkg/util/admission):
        # bounded priority queue so overload rejects cleanly instead of
        # stacking unbounded latency behind the statement lock
        from ..utils.admission import AdmissionController
        self.admission = AdmissionController(slots=4, max_queue=64)
        if mesh is None and len(jax.devices()) > 1:
            mesh = meshmod.make_mesh()
        self.mesh = mesh
        self._device_tables: dict[tuple, ColumnBatch] = {}
        self._exec_cache: dict[tuple, tuple] = {}
        # per-table secondary-index descriptors, cached off the catalog
        # (invalidated by index DDL; a fresh engine lazily reloads)
        self._index_defs: dict[str, list] = {}
        # per-table (checks, fks) cache + reverse fk map, same policy
        self._constraint_defs: dict[str, tuple] = {}
        self._fk_children: dict | None = None
        # statement execution is serialized per engine: pgwire serves
        # each connection on its own thread, and the plan/device caches
        # plus columnstore publish are not safe under concurrent
        # mutation (the reference runs a connExecutor per conn against
        # thread-safe subsystems; finer-grained locking is later work)
        self._stmt_lock = threading.RLock()
        self.metrics = MetricRegistry()
        # device-memory accounting: resident table uploads reserve
        # against the HBM budget BEFORE device_put, so an over-budget
        # upload fails with a quota error naming the knob instead of
        # an XLA OOM (pkg/util/mon/bytes_usage.go:173 analogue)
        self.hbm = BytesMonitor(
            "hbm", lambda: int(self.settings.get(
                "sql.exec.hbm_budget_bytes")),
            on_change=lambda used: self.metrics.gauge(
                "sql.mem.device.current",
                "bytes of HBM reserved by resident tables").set(used))

    # -- public API ----------------------------------------------------------
    def session(self) -> Session:
        return Session()

    def execute(self, sql: str, session: Session | None = None) -> Result:
        session = session or self.session()
        try:
            stmt = parser.parse(sql)
        except Exception:
            # a syntax error inside an explicit txn block aborts it,
            # same as any other statement failure (pg semantics)
            if session.txn is not None:
                session.txn_aborted = True
            raise
        return self.execute_stmt(stmt, session, sql_text=sql)

    def execute_stmt(self, stmt: ast.Statement, session: Session,
                     sql_text: str = "") -> Result:
        if session.txn_aborted and not isinstance(
                stmt, (ast.CommitTxn, ast.RollbackTxn)):
            raise EngineError(
                "current transaction is aborted, commands ignored "
                "until end of transaction block")
        import time as _time
        t0 = _time.monotonic()
        prio = session.vars.get("admission_priority", "normal")
        self.admission.acquire(priority=prio)
        tracing = session.vars.get("tracing", "off") == "on" \
            and not isinstance(stmt, ast.ShowTrace)
        try:
            if tracing:
                with self.tracer.capture(sql_text or
                                         type(stmt).__name__) as rec:
                    with self._stmt_lock:
                        res = self._dispatch_stmt(stmt, session,
                                                  sql_text)
                session.trace.append(rec)
            else:
                with self.tracer.span(
                        f"stmt:{type(stmt).__name__.lower()}"):
                    with self._stmt_lock:
                        res = self._dispatch_stmt(stmt, session,
                                                  sql_text)
            self.metrics.counter(
                f"sql.{type(stmt).__name__.lower()}.count",
                "statements executed, by type").inc()
            dt = _time.monotonic() - t0
            self.metrics.histogram(
                "sql.exec.latency",
                "statement execution latency (s)").observe(dt)
            if sql_text:
                self.sqlstats.record(sql_text, dt,
                                     max(len(res.rows), res.row_count))
            return res
        except Exception:
            # any error inside an explicit txn block aborts it until
            # ROLLBACK (postgres semantics; the connExecutor state
            # machine's stateAborted) — not just DML failures
            self.metrics.counter("sql.failure.count",
                                 "statements that errored").inc()
            if sql_text:
                self.sqlstats.record(sql_text,
                                     _time.monotonic() - t0, 0,
                                     failed=True)
            if session.txn is not None and not isinstance(
                    stmt, ast.BeginTxn):
                session.txn_aborted = True
            raise
        finally:
            self.admission.release()

    def _dispatch_stmt(self, stmt: ast.Statement, session: Session,
                       sql_text: str = "") -> Result:
        if isinstance(stmt, (ast.Select, ast.SetOp)):
            return self._exec_select(stmt, session, sql_text)
        if isinstance(stmt, ast.CreateTable):
            return self._exec_create(stmt)
        if isinstance(stmt, ast.DropTable):
            return self._exec_drop(stmt)
        if isinstance(stmt, ast.AlterTable):
            return self._exec_alter(stmt, session)
        if isinstance(stmt, ast.ConfigureZone):
            import json as _json
            if stmt.table not in self.store.tables:
                raise EngineError(
                    f"table {stmt.table!r} does not exist")
            allowed = {"gc.ttl_seconds", "range_max_bytes"}
            bad = set(stmt.options) - allowed
            if bad:
                raise EngineError(
                    f"unknown zone option(s) {sorted(bad)}; "
                    f"supported: {sorted(allowed)}")
            cur = self.zone_config(stmt.table)
            cur.update(stmt.options)
            self.kv.txn(lambda t: t.put(
                b"/zone/" + stmt.table.encode(),
                _json.dumps(cur, sort_keys=True).encode()))
            return Result(tag="CONFIGURE ZONE")
        if isinstance(stmt, ast.ShowZone):
            z = self.zone_config(stmt.table)
            if not z:
                z = {"gc.ttl_seconds":
                     self.settings.get("kv.gc.ttl_seconds"),
                     "range_max_bytes":
                     self.settings.get("kv.range.max_bytes")}
            return Result(names=["option", "value"],
                          rows=sorted((k, str(v))
                                      for k, v in z.items()),
                          tag="SHOW ZONE CONFIGURATION")
        if isinstance(stmt, (ast.Insert, ast.Update, ast.Delete,
                             ast.Truncate, ast.AlterTable)):
            tbl = getattr(stmt, "table", None)
            if tbl in self._view_map():
                raise EngineError(
                    f"{tbl!r} is a view; views are not modifiable")
        if isinstance(stmt, ast.CreateView):
            return self._exec_create_view(stmt, session)
        if isinstance(stmt, ast.DropView):
            return self._exec_drop_view(stmt)
        if isinstance(stmt, ast.CreateSequence):
            return self._exec_create_sequence(stmt)
        if isinstance(stmt, ast.DropSequence):
            return self._exec_drop_sequence(stmt)
        if isinstance(stmt, ast.ShowSequences):
            import json as _json
            rows = []
            for k, v in self.kv.scan(self.SEQ_PREFIX,
                                     K.prefix_end(self.SEQ_PREFIX)):
                d = _json.loads(v.decode())
                rows.append((k[len(self.SEQ_PREFIX):].decode(),
                             d["start"], d["increment"],
                             d.get("value")))
            return Result(
                names=["sequence_name", "start", "increment",
                       "last_value"],
                rows=sorted(rows), tag="SHOW SEQUENCES")
        if isinstance(stmt, ast.Truncate):
            return self._exec_truncate(stmt)
        if isinstance(stmt, ast.CreateIndex):
            return self._exec_create_index(stmt, session)
        if isinstance(stmt, ast.DropIndex):
            return self._exec_drop_index(stmt, session)
        if isinstance(stmt, ast.ShowColumns):
            d = self.catalog.get_by_name(stmt.table)
            if d is None:
                raise EngineError(
                    f"table {stmt.table!r} does not exist")
            idx_cols = {cn for i in d.indexes for cn in i.columns} \
                | set(d.primary_key)
            return Result(
                names=["column_name", "data_type", "is_nullable",
                       "indexed"],
                rows=[(c.name, str(c.type), c.nullable,
                       c.name in idx_cols)
                      for c in d.columns if c.state == "public"],
                tag="SHOW COLUMNS")
        if isinstance(stmt, ast.ShowIndexes):
            d = self.catalog.get_by_name(stmt.table)
            if d is None:
                raise EngineError(
                    f"table {stmt.table!r} does not exist")
            rows = [(stmt.table, "primary",
                     ", ".join(d.primary_key) or ROWID, True, "public")]
            rows += [(stmt.table, i.name, ", ".join(i.columns),
                      i.unique, i.state) for i in d.indexes]
            return Result(
                names=["table_name", "index_name", "columns",
                       "unique", "state"],
                rows=rows, tag="SHOW INDEXES")
        if isinstance(stmt, ast.Insert):
            return self._exec_insert(stmt, session)
        if isinstance(stmt, ast.Update):
            return self._exec_update(stmt, session)
        if isinstance(stmt, ast.Delete):
            return self._exec_delete(stmt, session)
        if isinstance(stmt, ast.SetVar):
            if stmt.cluster:
                self.settings.set(stmt.name, stmt.value)
            else:
                session.vars.set(stmt.name, stmt.value)
            return Result(tag="SET")
        if isinstance(stmt, ast.Backup):
            from ..jobs.backup import BACKUP_JOB
            for t in stmt.tables:
                if t not in self.store.tables:
                    raise EngineError(f"table {t!r} does not exist")
            jid = self.jobs.create(BACKUP_JOB, {
                "tables": stmt.tables, "dest": stmt.dest})
            rec = self.jobs.run_job(jid)
            if rec.status != "succeeded":
                raise EngineError(f"BACKUP failed: {rec.error}")
            return Result(names=["job_id"], rows=[(jid,)], tag="BACKUP")
        if isinstance(stmt, ast.Restore):
            from ..jobs.backup import RESTORE_JOB
            jid = self.jobs.create(RESTORE_JOB, {
                "tables": stmt.tables, "src": stmt.src})
            rec = self.jobs.run_job(jid)
            if rec.status != "succeeded":
                raise EngineError(f"RESTORE failed: {rec.error}")
            return Result(names=["job_id"], rows=[(jid,)],
                          tag="RESTORE")
        if isinstance(stmt, ast.CreateChangefeed):
            jid = self.create_changefeed(stmt.table, stmt.sink)
            return Result(names=["job_id"], rows=[(jid,)],
                          tag="CREATE CHANGEFEED")
        if isinstance(stmt, ast.ShowJobs):
            recs = sorted(self.jobs.jobs(), key=lambda r: r.id)
            return Result(
                names=["job_id", "job_type", "status",
                       "fraction_completed"],
                rows=[(r.id, r.type, r.status,
                       round(r.fraction_completed, 3)) for r in recs],
                tag="SHOW JOBS")
        if isinstance(stmt, ast.CancelJob):
            # async cancel (the statement lock is held here and the
            # changefeed thread may be waiting on it — joining would
            # self-deadlock); the job observes the request at its next
            # check_cancel and exits
            self.jobs.cancel(stmt.job_id)
            self._cdc_threads.pop(stmt.job_id, None)
            return Result(tag="CANCEL JOB")
        if isinstance(stmt, ast.ShowTables):
            descs = sorted(self.catalog.list_tables(),
                           key=lambda d: d.name)
            return Result(
                names=["table_name", "version"],
                rows=[(d.name, d.version) for d in descs
                      if not d.name.startswith("__")],
                tag="SHOW TABLES")
        if isinstance(stmt, ast.ShowVar):
            v = session.vars.get(stmt.name, None)
            if v is None:
                v = self.settings.get(stmt.name)
            return Result(names=[stmt.name], rows=[(v,)], tag="SHOW")
        if isinstance(stmt, ast.Explain):
            from ..sql.stats import estimate
            if stmt.analyze:
                return self._explain_analyze(stmt.stmt, session,
                                             sql_text)
            target = stmt.stmt
            if isinstance(target, ast.Select):
                target = self._expand_views(target)
            if isinstance(target, ast.Select) and (
                    target.ctes or self._has_derived(target)):
                # composite shapes (CTEs / derived / views): explain
                # each sub-plan; the main stage re-plans over the
                # materialized temps at execution time
                return Result(
                    names=["plan"],
                    rows=[(ln,) for ln in
                          self._explain_composite(target, session)],
                    tag="EXPLAIN")
            node, emeta = self._plan(target, session,
                                     for_explain=True)
            costs = estimate(node, self.catalog_view().stats)
            tree = P.plan_tree_repr(node, costs=costs)
            rows = []
            if emeta.memo is not None:
                m_ = emeta.memo
                rows.append((
                    f"memo: {m_.groups} groups, {m_.considered} "
                    f"plans costed; best order "
                    f"{[m_.root] + m_.order} cost≈{m_.cost:.0f}",))
            if isinstance(target, ast.Select):
                m = self._index_fastpath_match(target, session)
                if m is not None:
                    label, cols, vals, _residual = m
                    # mirror the runtime selectivity guard when a warm
                    # locator exists; never BUILD one here — EXPLAIN
                    # must stay metadata-only (no O(table) work)
                    tname = target.table.name
                    td = self.store.table(tname)
                    lim = int(session.vars.get(
                        "index_lookup_limit", 4096))
                    cached = td.sec_index_cache.get(cols)
                    declined = (
                        cached is not None
                        and cached[0] == td.generation
                        and len(cached[1].get(vals, [])) > lim)
                    if not declined:
                        rows.append((
                            f"index scan {tname}@{label} "
                            f"({', '.join(cols)}) = {vals!r}",))
            rows += [(line,) for line in tree.rstrip().split("\n")]
            return Result(names=["plan"], rows=rows, tag="EXPLAIN")
        if isinstance(stmt, ast.ShowCreateTable):
            d = self.catalog.get_by_name(stmt.table)
            if d is None:
                raise EngineError(
                    f"table {stmt.table!r} does not exist")
            if d.view_sql:
                cols = (f" ({', '.join(d.view_columns)})"
                        if d.view_columns else "")
                ddl = f"CREATE VIEW {d.name}{cols} AS {d.view_sql}"
            else:
                ddl = _render_create(d)
            return Result(names=["table_name", "create_statement"],
                          rows=[(d.name, ddl)],
                          tag="SHOW CREATE TABLE")
        if isinstance(stmt, ast.ShowAll):
            return Result(
                names=["variable", "value"],
                rows=sorted((k, str(v))
                            for k, v in session.vars.values.items()),
                tag="SHOW ALL")
        if isinstance(stmt, ast.ShowTrace):
            rows = []
            for rec in session.trace:
                for line in rec.tree_lines():
                    rows.append((line,))
            return Result(names=["span"], rows=rows,
                          tag="SHOW TRACE")
        if isinstance(stmt, ast.ShowStatements):
            return Result(
                names=["fingerprint", "count", "mean_latency_ms",
                       "max_latency_ms", "rows", "failures"],
                rows=[(s.fingerprint, s.count,
                       round(s.mean_latency_s * 1e3, 3),
                       round(s.max_latency_s * 1e3, 3),
                       s.total_rows, s.failures)
                      for s in self.sqlstats.all()],
                tag="SHOW STATEMENTS")
        if isinstance(stmt, ast.Analyze):
            self.store.analyze(stmt.table)
            self.metrics.counter("sql.stats.analyze",
                                 "ANALYZE statements run").inc()
            return Result(tag="ANALYZE")
        if isinstance(stmt, ast.BeginTxn):
            if session.txn is not None:
                raise EngineError("transaction already open")
            session.txn = Txn(self.kv.store)
            session.effects = []
            session.txn_aborted = False
            return Result(tag="BEGIN")
        if isinstance(stmt, ast.CommitTxn):
            t = session.txn
            if t is None:
                return Result(tag="COMMIT")
            effects = session.effects
            aborted = session.txn_aborted
            session.txn, session.effects = None, []
            session.txn_aborted = False
            if aborted:
                # COMMIT of an aborted txn is a rollback (pg semantics)
                t.rollback()
                return Result(tag="ROLLBACK")
            try:
                commit_ts = t.commit()
            except (TxnRetryError, TxnAbortedError) as e:
                t.rollback()
                # the pg "restart transaction" error class (40001):
                # client must retry the whole txn
                raise EngineError(f"restart transaction: {e}") from e
            self._publish(effects, commit_ts)
            return Result(tag="COMMIT")
        if isinstance(stmt, ast.RollbackTxn):
            if session.txn is not None:
                session.txn.rollback()
            session.txn, session.effects = None, []
            session.txn_aborted = False
            return Result(tag="ROLLBACK")
        raise EngineError(f"unsupported statement {type(stmt).__name__}")

    def _explain_composite(self, sel: ast.Select,
                           session: Session) -> list[str]:
        """EXPLAIN for CTE / derived-table / view shapes: one plan
        block per sub-select (the reference similarly renders each
        WithExpr's bound plan); the main stage is re-planned over the
        materialized temps at execution."""
        from ..sql.stats import estimate
        lines: list[str] = []

        def emit(label: str, sub):
            if isinstance(sub, ast.Select):
                sub = self._expand_views(sub)
            lines.append(f"{label}:")
            if isinstance(sub, ast.Select) and (
                    sub.ctes or self._has_derived(sub)):
                lines.extend("  " + ln for ln in
                             self._explain_composite(sub, session))
            elif isinstance(sub, ast.Select) and sub.table is not None:
                node, _ = self._plan(sub, session, for_explain=True)
                costs = estimate(node, self.catalog_view().stats)
                lines.extend(
                    "  " + ln for ln in P.plan_tree_repr(
                        node, costs=costs).rstrip().split("\n"))
            else:
                lines.append(
                    "  (table-free or set-op; planned at execution)")

        for name, _cols, s in sel.ctes:
            emit(f"cte {name}", s)
        refs = ([sel.table] if sel.table is not None else []) \
            + [j.table for j in sel.joins]
        for r in refs:
            if r.subquery is not None:
                emit(f"derived {r.alias or r.name}", r.subquery)
        lines.append(
            "main: re-planned over the materialized temps at "
            "execution")
        return lines

    def _explain_analyze(self, sel, session: Session,
                         sql_text: str) -> Result:
        """EXPLAIN ANALYZE: run the statement under a trace recording
        and render the plan with measured phase timings + row counts
        (the reference's instrumented statement diagnostics,
        sql/instrumentation.go)."""
        if not isinstance(sel, ast.Select):
            raise EngineError("can only EXPLAIN ANALYZE SELECT")
        import time as _time
        with self.tracer.capture("explain-analyze") as rec:
            t0 = _time.monotonic()
            res = self._exec_select(sel, session, sql_text)
            total_ms = (_time.monotonic() - t0) * 1e3
        node, _ = self._plan(sel, session)
        from ..sql.stats import estimate
        costs = estimate(node, self.catalog_view().stats)
        lines = ["planning/execution:"]
        for name in ("plan", "compile", "upload", "dispatch",
                     "materialize"):
            s = rec.find(name)
            if s is not None:
                tag_s = "".join(f" {k}={v}" for k, v in s.tags.items())
                lines.append(f"  {name}: {s.duration_ms:.2f}ms{tag_s}")
        lines.append(f"  total: {total_ms:.2f}ms, "
                     f"rows returned: {len(res.rows)}")
        lines.append("plan:")
        lines.extend("  " + ln for ln in P.plan_tree_repr(
            node, costs=costs).rstrip().split("\n"))
        return Result(names=["info"], rows=[(ln,) for ln in lines],
                      tag="EXPLAIN ANALYZE")

    # -- catalog -------------------------------------------------------------
    def catalog_view(self) -> CatalogView:
        from ..sql.stats import TableStats
        # planners see the PUBLIC schema: columns mid-add (WRITE_ONLY
        # descriptor state, schemachange.py) are physically present but
        # hidden until published
        schemas = {}
        for n, td in self.store.tables.items():
            if any(c.hidden for c in td.schema.columns):
                s = TableSchema(
                    name=td.schema.name,
                    columns=[c for c in td.schema.columns
                             if not c.hidden],
                    primary_key=list(td.schema.primary_key),
                    table_id=td.schema.table_id)
                schemas[n] = s
            else:
                schemas[n] = td.schema
        dicts = {n: dict(td.dictionaries)
                 for n, td in self.store.tables.items()}
        stats = {}
        for n, td in self.store.tables.items():
            if td.stats is not None:
                # stale ANALYZE output (mutations since) still informs
                # estimates but no longer counts as authoritative
                st = TableStats(
                    row_count=td.row_count,
                    distinct=dict(td.stats.distinct),
                    null_frac=dict(td.stats.null_frac),
                    analyzed=td.stats_generation == td.generation)
            else:
                st = TableStats(row_count=td.row_count)
            stats[n] = st
        return CatalogView(schemas, dicts, stats,
                           key_distinct_fn=self.store.key_distinct)

    def _read_ts(self, session: Session) -> Timestamp:
        return session.txn_read_ts or self.clock.now()

    def _as_of_ts(self, sel, session: Session):
        """Resolve AS OF SYSTEM TIME to a Timestamp, or None when the
        statement has no AS OF clause. Accepted forms (a subset of
        the reference's, sql/as_of.go): a negative interval string
        ('-10s', '-2m', '-1h'), a timestamp string, or a decimal HLC
        wall-nanos value."""
        aso = getattr(sel, "as_of", None)
        if aso is None:
            return None
        if session.txn is not None:
            raise EngineError(
                "AS OF SYSTEM TIME is not allowed inside a "
                "transaction")
        if not isinstance(aso, ast.Literal):
            raise EngineError(
                "AS OF SYSTEM TIME requires a constant")
        v = aso.value
        if isinstance(v, str):
            import re as _re
            m = _re.fullmatch(r"-(\d+(?:\.\d+)?)([smh])", v.strip())
            if m:
                mult = {"s": 1e9, "m": 60e9, "h": 3600e9}[m.group(2)]
                wall = self.clock.now().wall - int(
                    float(m.group(1)) * mult)
            else:
                from ..sql.binder import parse_timestamp
                try:
                    wall = parse_timestamp(v) * 1000  # micros -> ns
                except Exception:
                    raise EngineError(
                        f"cannot parse AS OF SYSTEM TIME {v!r}")
        elif isinstance(v, (int, float)):
            wall = int(v)
        else:
            raise EngineError(
                f"cannot parse AS OF SYSTEM TIME {v!r}")
        if wall <= 0 or wall > self.clock.now().wall:
            raise EngineError(
                "AS OF SYSTEM TIME must be in the past")
        return Timestamp(int(wall), 0)

    # -- SELECT --------------------------------------------------------------
    def _plan(self, stmt, session, for_explain: bool = False,
              no_memo: bool = False):
        if not isinstance(stmt, ast.Select):
            raise EngineError("can only EXPLAIN SELECT")
        # AS OF pins the whole statement: now() and plan-time
        # subquery evaluation read at the historical timestamp too
        # (the reference pins the txn's read ts, sql/as_of.go)
        read_ts = self._as_of_ts(stmt, session) or \
            self._read_ts(session)
        # EXPLAIN must not execute volatile functions: sequences bind
        # to a placeholder instead of allocating (pg EXPLAIN semantics)
        seq_ops = ((lambda fn, name, arg: 0) if for_explain
                   else self._sequence_ops(session))
        planner = Planner(
            self.catalog_view(),
            subquery_eval=lambda sel, lim: self._eval_subquery(
                _propagate_as_of(sel, stmt), session, lim),
            now_micros=read_ts.wall // 1000,
            sequence_ops=seq_ops,
            use_memo=(not no_memo
                      and session.vars.get("optimizer", "on")
                      != "off"))
        return planner.plan_select(stmt)

    # -- sequences ------------------------------------------------------------
    SEQ_PREFIX = b"/seq/"

    def _sequence_ops(self, session: Session):
        return lambda fn, name, arg: self._sequence_op(
            session, fn, name, arg)

    def _seq_desc(self, name: str) -> dict:
        import json as _json
        raw = self.kv.txn(
            lambda t: t.get(self.SEQ_PREFIX + name.encode()))
        if raw is None:
            raise EngineError(f"sequence {name!r} does not exist")
        return _json.loads(raw.decode())

    def _sequence_op(self, session: Session, fn: str, name: str,
                     arg) -> int:
        """nextval/currval/setval. nextval allocates in its OWN KV
        txn — sequence values are never rolled back (pg semantics;
        the reference likewise increments outside the user txn,
        pkg/sql/sequence.go)."""
        import json as _json
        key = self.SEQ_PREFIX + name.encode()
        if fn == "currval":
            if name not in session.seq_currval:
                raise EngineError(
                    f"currval of sequence {name!r} is not yet "
                    f"defined in this session")
            return session.seq_currval[name]
        if fn == "nextval":
            def bump(t):
                raw = t.get(key)
                if raw is None:
                    raise EngineError(
                        f"sequence {name!r} does not exist")
                d = _json.loads(raw.decode())
                if d.get("value") is None:
                    d["value"] = d["start"]
                else:
                    d["value"] += d["increment"]
                t.put(key, _json.dumps(d).encode())
                return d["value"]
            v = self.kv.txn(bump)
        else:  # setval
            desc = self._seq_desc(name)
            desc["value"] = int(arg)
            self.kv.txn(lambda t: t.put(
                key, _json.dumps(desc).encode()))
            v = int(arg)
        session.seq_currval[name] = v
        return v

    # -- subqueries / CTEs ---------------------------------------------------
    def _eval_subquery(self, sel: ast.Select, session: Session,
                       limit_one: bool = False):
        """Execute an expression subquery before the main statement
        (the reference's planTop.subqueryPlans, sql/subquery.go) and
        hand (rows, types) back to the binder for constant inlining."""
        import copy
        if limit_one and sel.limit is None:
            sel = copy.copy(sel)
            sel.limit = 1  # EXISTS needs one row, not the result set
        res = self._exec_select(sel, session, f"(subquery {sel!r})")
        return res.rows, res.types

    @staticmethod
    def _has_derived(sel: ast.Select) -> bool:
        refs = ([sel.table] if sel.table is not None else []) + \
            [j.table for j in sel.joins]
        return any(r.subquery is not None for r in refs)

    def _exec_with_temps(self, sel: ast.Select, session: Session,
                         sql_text: str) -> Result:
        """WITH ctes / FROM (SELECT...): materialize each into a temp
        columnstore table, rewrite references, run the main query, drop
        the temps. The reference plans CTEs as once-materialized
        buffers (sql/opt: WithExpr / spool); here the natural TPU form
        is a temp scan-plane table the main program reads like any
        other."""
        import copy
        sel = copy.copy(sel)
        temps: list[str] = []
        mapping: dict[str, str] = {}
        try:
            for name, cols, sub in sel.ctes:
                sub = _propagate_as_of(
                    _rewrite_table_names(sub, mapping), sel)
                res = self._exec_select(sub, session, f"(cte {sub!r})")
                tname = f"__cte{self._temp_seq()}_{name}"
                self._materialize_temp(tname, res, cols)
                mapping[name] = tname
                temps.append(tname)
            sel.ctes = []
            refs = ([("table", sel.table)] if sel.table is not None
                    else []) + [("join", j) for j in sel.joins]
            for kind, obj in refs:
                ref = obj if kind == "table" else obj.table
                if ref.subquery is None:
                    continue
                sub = _propagate_as_of(
                    _rewrite_table_names(ref.subquery, mapping), sel)
                res = self._exec_select(sub, session,
                                        f"(derived {sub!r})")
                tname = f"__cte{self._temp_seq()}_{ref.alias}"
                self._materialize_temp(tname, res, None)
                temps.append(tname)
                newref = ast.TableRef(tname, ref.alias)
                if kind == "table":
                    sel.table = newref
                else:
                    obj.table = newref
            sel = _rewrite_table_names(sel, mapping)
            return self._exec_select(sel, session, sql_text)
        finally:
            for t in temps:
                if t in self.store.tables:
                    self.store.drop_table(t)
                    for k in [k for k in self._device_tables
                              if k[0] == t]:
                        self._evict_device(k)

    _temp_counter = [0]

    def _temp_seq(self) -> int:
        self._temp_counter[0] += 1
        return self._temp_counter[0]

    def _materialize_temp(self, tname: str, res: Result,
                          rename: list | None) -> None:
        """Create a columnstore table from a decoded Result."""
        names = list(res.names)
        if rename is not None:
            if len(rename) != len(names):
                raise EngineError(
                    "CTE column list length does not match query")
            names = list(rename)
        if len(set(names)) != len(names):
            raise EngineError(f"duplicate column names in {tname}")
        types = res.types
        if not types:
            raise EngineError("subquery produced no column types")
        schema = TableSchema(
            name=tname,
            columns=[ColumnSchema(n, t, True)
                     for n, t in zip(names, types)],
            primary_key=[],
            table_id=self.store.alloc_table_id())
        self.store.create_table(schema)
        if not res.rows:
            return
        n = len(res.rows)
        cols: dict[str, np.ndarray] = {}
        valid: dict[str, np.ndarray] = {}
        for i, (cname, ty) in enumerate(zip(names, types)):
            vals = [r[i] for r in res.rows]
            v = np.array([x is not None for x in vals], dtype=bool)
            f = ty.family
            if f == Family.STRING:
                arr = np.array([x if x is not None else "" for x in vals],
                               dtype=object)
            elif f == Family.DATE:
                arr = np.array(
                    [(x - EPOCH_DATE).days if isinstance(x, datetime.date)
                     else (x or 0) for x in vals], dtype=np.int64)
            elif f == Family.TIMESTAMP:
                arr = np.array(
                    [int((x - EPOCH_DT).total_seconds() * 1e6)
                     if isinstance(x, datetime.datetime) else (x or 0)
                     for x in vals], dtype=np.int64)
            else:
                # DECIMAL floats are rescaled by insert_columns
                arr = np.array([x if x is not None else 0 for x in vals],
                               dtype=ty.np_dtype
                               if f != Family.DECIMAL else np.float64)
            cols[cname] = arr
            valid[cname] = v
        # temps ingest at wall=1 so they are visible at ANY read
        # timestamp — including a txn's pinned one from before the
        # materialization happened
        self.store.insert_columns(tname, cols, Timestamp(1, 0),
                                  valid=valid)

    def _prepare_select(self, sel: ast.Select, session: Session,
                        sql_text: str,
                        no_memo: bool = False) -> "Prepared":
        for td in self.store.tables.values():
            if td.open_ts:
                self.store.seal(td.schema.name)
        with self.tracer.span("plan"):
            node, meta = self._plan(sel, session, no_memo=no_memo)

        scan_aliases = _collect_scans(node)
        scan_cols = _collect_scan_columns(node)
        # read-your-own-writes: tables this txn has written get an
        # overlay snapshot (committed + buffered effects), not the
        # shared device cache; overlay scans stay single-device
        overlay = set()
        if session.txn is not None and session.effects:
            touched = {tb for tb, _ in session.effects}
            overlay = touched & set(scan_aliases.values())
        decision = None if overlay else self._dist_decision(node, session)
        stream = (None if (overlay or decision is not None)
                  else self._stream_decision(node, scan_aliases, scan_cols,
                                             session))
        read_ts = self._read_ts(session)
        # the join-build uniqueness guard is snapshot-aware: it must
        # judge the rows visible at THIS query's read timestamp — and
        # know about txn-buffered build rows the store can't see
        as_of = self._as_of_ts(sel, session)
        if as_of is not None:
            read_ts = as_of
        overlay_puts = {
            t: sum(1 for tb, op in session.effects
                   if tb == t and op[0] == "put")
            for t in overlay}
        try:
            self._check_join_builds(node, read_ts, overlay_puts)
        except EngineError:
            if meta.memo is not None and not no_memo:
                # the memo's stats-estimated build order violated the
                # engine's EXACT multiplicity cap (avg vs max skew):
                # replan with the greedy orderer, which consults the
                # store's exact probes (the reference's optimizer
                # likewise falls back when exploration yields no
                # executable plan)
                return self._prepare_select(sel, session, sql_text,
                                            no_memo=True)
            raise

        scans = {}
        gens = []
        shapes = []
        for alias, tname in scan_aliases.items():
            self._register_table_read(session.txn, tname, read_ts)
            cols = scan_cols.get(alias)
            if stream is not None and alias == stream[0]:
                # the streamed fact table never uploads whole; its
                # shape contribution is the (static) page size — but
                # dictionary sizes still fingerprint the compiled plan
                # (group codes are baked into the XLA program)
                gens.append((tname, self.store.table(tname).generation))
                dictlens = tuple(
                    sorted((cn, len(d)) for cn, d in
                           self.store.table(tname).dictionaries.items()))
                shapes.append((tname, stream[2], dictlens))
                continue
            if tname in overlay:
                b = self._overlay_batch(tname, session.effects, read_ts)
                gens.append((tname, -1))
            elif decision is not None:
                sharded = alias in decision.sharded
                b = self._device_table(tname, "sharded" if sharded
                                       else "replicated", cols)
                gens.append((tname, self.store.table(tname).generation))
            else:
                b = self._device_table(tname, cols=cols)
                gens.append((tname, self.store.table(tname).generation))
            scans[alias] = b
            dictlens = tuple(
                sorted((cn, len(d)) for cn, d in
                       self.store.table(tname).dictionaries.items()))
            shapes.append((tname, b.n, dictlens))

        cap = int(session.vars.get("hash_group_capacity", 1 << 17))
        pallas = session.vars.get("pallas_groupagg", "off") == "on"
        # keyed by shape (padded row-count bucket) + dictionary sizes,
        # NOT data generation: the compiled XLA program depends only on
        # shapes and on literal dictionary codes (append-only, so any
        # growth shows up in dictlens) — the plan-cache fingerprint idea
        # of the reference (sql/plan_opt.go), adapted to XLA's
        # shape-specialized compilation model
        # plan fingerprint: subquery results are inlined into the plan
        # as constants, so two preparations of the SAME sql_text can
        # compile DIFFERENT programs when underlying data moved —
        # sql_text alone would hand back a stale compiled constant
        plan_fp = hash(repr(node))
        key = (sql_text, tuple(sorted(shapes)), decision is not None,
               stream, cap, pallas, plan_fp)
        cached = self._exec_cache.get(key)
        self.tracer.tag(plan_cache="hit" if cached else "miss")
        if cached is None:
            params = ExecParams(
                hash_group_capacity=cap,
                axis_name=SHARD_AXIS if decision is not None else None,
                pallas_groupagg=pallas,
                pallas_interpret=jax.default_backend() != "tpu")
            if stream is not None:
                splan = compile_streaming(node, params, meta)

                def page_fn(scans_in, ts_in, _f=splan.page_fn):
                    return _f(RunContext(scans_in, ts_in))
                jfn = _StreamFns(jax.jit(page_fn),
                                 jax.jit(splan.combine),
                                 jax.jit(splan.final_fn))
            elif decision is not None:
                runf = compile_plan(node, params, meta)
                jfn = jax.jit(make_distributed_fn(
                    runf, self.mesh, scan_aliases, decision))
            else:
                runf = compile_plan(node, params, meta)

                def fn(scans_in, ts_in, nparts, pid):
                    return runf(RunContext(scans_in, ts_in, nparts, pid))
                jfn = jax.jit(fn)
            self._exec_cache[key] = (jfn, meta)
        else:
            jfn, meta = cached
        gens = tuple(sorted(gens))
        return Prepared(self, session, sel, sql_text, jfn, scans, meta,
                        gens, stream=stream,
                        stream_cols=(scan_cols.get(stream[0])
                                     if stream else None),
                        as_of=as_of)

    def prepare(self, sql: str, session: Session | None = None) -> "Prepared":
        """Prepare a SELECT for repeated execution (the pgwire
        prepared-statement/portal path, pkg/sql/pgwire/conn.go Describe/
        Bind/Execute). ``Prepared.dispatch()`` launches the compiled
        program without blocking on the result, so a stream of
        executions pipelines on-device instead of paying a full
        host<->device round trip per query."""
        session = session or self.session()
        stmt = parser.parse(sql)
        if isinstance(stmt, ast.Select):
            stmt = self._expand_views(stmt)
        if isinstance(stmt, ast.SetOp) or (
                isinstance(stmt, ast.Select)
                and (stmt.ctes or self._has_derived(stmt))):
            # CTE/set-op/derived statements materialize temps per
            # execution: prepare degrades to a re-execute handle (the
            # reference's portals likewise re-plan non-cacheable
            # statements)
            return _RerunPrepared(self, session, stmt, sql)
        if not isinstance(stmt, ast.Select) or stmt.table is None:
            raise EngineError("can only prepare table-reading SELECTs")
        return self._prepare_select(stmt, session, sql_text=sql)

    def _exec_select(self, sel, session: Session,
                     sql_text: str) -> Result:
        if isinstance(sel, ast.SetOp):
            return self._exec_setop(sel, session, sql_text)
        sel = self._expand_views(sel)
        if sel.ctes or self._has_derived(sel):
            return self._exec_with_temps(sel, session, sql_text)
        if sel.table is None:
            return self._exec_table_free(sel, session)
        match = self._index_fastpath_match(sel, session)
        if match is not None:
            res = self._exec_index_fastpath(sel, session, match)
            if res is not None:
                self.metrics.counter(
                    "sql.select.index_fastpath",
                    "SELECTs served by the index point-read path").inc()
                return res
        rmatch = self._range_fastpath_match(sel, session)
        if rmatch is not None:
            res = self._exec_range_fastpath(sel, session, rmatch)
            if res is not None:
                self.metrics.counter(
                    "sql.select.range_fastpath",
                    "SELECTs served by the ordered index-range "
                    "path").inc()
                return res
        return self._prepare_select(sel, session, sql_text).run()

    def _dml_index_candidates(self, table: str, where,
                              session: Session):
        """Chunk indexes that can hold rows matching `where`'s
        equality conjuncts, per an available index — so a point
        UPDATE/DELETE evaluates its predicate over one chunk instead
        of the whole table. None = no usable index, scan every chunk.
        The candidate set covers ALL row versions, so pruned chunks
        provably contain no match at any timestamp."""
        if where is None:
            return None
        probe = ast.Select(
            items=[ast.SelectItem(None, star=True)],
            table=ast.TableRef(table), where=where)
        match = self._index_fastpath_match(probe, session)
        if match is None:
            return None
        _label, cols, vals, _residual = match
        sec = self.store.ensure_secondary_index(table, cols)
        return {ci for ci, _ri in sec.get(vals, [])}

    # -- index point-read fast path ------------------------------------------
    # The OLTP read path: a selective equality lookup is served from
    # the host-side index locator + per-row extraction instead of
    # compiling and dispatching a full device scan — the analogue of
    # the reference's constrained index scan (opt/idxconstraint +
    # colfetcher point lookups through DistSender), where a point read
    # touches one range instead of streaming the table.

    def _fastpath_shape(self, sel: ast.Select, session: Session):
        """Common structural gate for host-side index fastpaths:
        single stored table, projection-only items. Returns
        (tname, schema, visible, projected) or None."""
        if (sel.table is None or sel.joins or sel.group_by
                or sel.having or sel.distinct or sel.ctes):
            return None
        if session.vars.get("index_scan", "on") == "off":
            return None
        tname = sel.table.name
        if sel.table.alias not in (None, tname):
            return None
        if tname not in self.store.tables:
            return None
        schema = self.store.table(tname).schema
        visible = {c.name for c in schema.columns
                   if not getattr(c, "hidden", False)}
        projected = set()
        for item in sel.items:
            if item.star:
                projected |= visible
                continue
            e = item.expr
            if not (isinstance(e, ast.ColumnRef)
                    and e.table in (None, tname)
                    and e.name in visible):
                return None
            projected.add(item.alias or e.name)
        return (tname, schema, visible, projected)

    def _index_fastpath_match(self, sel: ast.Select, session: Session):
        """Return (label, cols, vals) when this SELECT is an equality
        lookup covering all columns of a usable index: single table,
        projection-only items, conjunctive WHERE with constant
        equalities. None = use the compiled scan path."""
        shape = self._fastpath_shape(sel, session)
        if shape is None:
            return None
        tname, schema, visible, projected = shape
        for ob in sel.order_by:
            if not (isinstance(ob.expr, ast.ColumnRef)
                    and ob.expr.name in projected):
                return None
        if sel.where is None:
            return None
        eq: dict[str, object] = {}
        eq_conjs: dict[str, object] = {}
        conjs = split_conjuncts_ast(sel.where)
        for c in conjs:
            if not (isinstance(c, ast.BinOp) and c.op == "="):
                continue
            lhs, rhs = c.left, c.right
            if isinstance(rhs, ast.ColumnRef) and isinstance(
                    lhs, ast.Literal):
                lhs, rhs = rhs, lhs
            if (isinstance(lhs, ast.ColumnRef)
                    and lhs.table in (None, tname)
                    and lhs.name in visible
                    and isinstance(rhs, ast.Literal)
                    and rhs.value is not None
                    and lhs.name not in eq):
                eq[lhs.name] = rhs
                eq_conjs[lhs.name] = c
        if not eq:
            return None
        # candidate indexes, best first: primary, unique, non-unique
        cands = []
        if schema.primary_key:
            cands.append(("primary", tuple(schema.primary_key), 0))
        for idx in self._table_indexes(tname):
            if idx.state != "public":
                continue
            cands.append((idx.name, tuple(idx.columns),
                          1 if idx.unique else 2))
        cands.sort(key=lambda c: c[2])
        for label, cols, _rank in cands:
            if not all(cn in eq for cn in cols):
                continue
            vals = []
            ok = True
            for cn in cols:
                v = self._coerce_index_literal(schema.column(cn),
                                               eq[cn])
                if v is None:
                    ok = False
                    break
                vals.append(v)
            if ok:
                consumed = {id(eq_conjs[cn]) for cn in cols}
                residual = any(id(c) not in consumed for c in conjs)
                return (label, cols, tuple(vals), residual)
        return None

    def _exec_index_fastpath(self, sel: ast.Select, session: Session,
                             match) -> Optional[Result]:
        label, cols, vals, residual = match
        tname = sel.table.name
        td = self.store.table(tname)
        read_ts = self._as_of_ts(sel, session) or \
            self._read_ts(session)
        rts = read_ts.to_int()
        sec = self.store.ensure_secondary_index(tname, cols)
        positions = sec.get(vals, [])
        limit = int(session.vars.get("index_lookup_limit", 4096))
        if len(positions) > limit:
            # low selectivity: the compiled device scan wins
            return None
        self._register_table_read(session.txn, tname, read_ts)
        pending = (self._txn_key_state(session.effects, tname)
                   if session.txn is not None else {})
        rows = []
        for ci, ri in positions:
            c = td.chunks[ci]
            if not (c.mvcc_ts[ri] <= rts < c.mvcc_del[ri]):
                continue
            row = self.store.extract_row(td, c, ri)
            if pending and td.codec.key(row) in pending:
                continue  # superseded by this txn's buffered effects
            rows.append(row)
        for _key, r in pending.items():
            if r is None:
                continue
            r = dict(r)
            if td.codec.synthetic_pk and ROWID not in r:
                r[ROWID] = 0
            if tuple(r.get(cn) for cn in cols) == vals:
                rows.append(r)
        return self._fastpath_project(sel, session, td, rows, rts,
                                      apply_where=residual)

    _FLIP_OP = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}

    def _coerce_index_literal(self, col, lit):
        """Bind + coerce a literal to `col`'s storage form for index
        probing; None when the conversion fails OR is inexact — a
        rounded probe value (0.5 -> 1 on an INT column) would answer
        a DIFFERENT predicate, so those shapes must fall back to the
        compiled path, which evaluates the original comparison."""
        binder = Binder(Scope())
        try:
            b = binder.bind(lit)
            v = binder._const_to(b, col.type).value
        except Exception:
            return None
        if v is None:
            return None
        if isinstance(b.value, (int, float)) \
                and not isinstance(b.value, bool):
            orig = (b.value / 10 ** b.type.scale
                    if b.type.family == Family.DECIMAL else b.value)
            f = col.type.family
            if f == Family.INT and float(v) != float(orig):
                return None
            if f == Family.DECIMAL and \
                    float(v) / 10 ** col.type.scale != float(orig):
                return None
        return v

    def _range_fastpath_match(self, sel: ast.Select,
                              session: Session):
        """Match an index-ordered range scan: equality on a prefix of
        an index plus optional bounds on the next column — the
        analogue of a constrained ordered index scan
        (opt/idxconstraint + pebbleMVCCScanner over an index span).
        Serves `WHERE k >= x ORDER BY k LIMIT n` (YCSB-E's scan shape)
        host-side with early termination instead of compiling a
        per-literal XLA program."""
        shape = self._fastpath_shape(sel, session)
        if shape is None or sel.where is None:
            return None
        tname, schema, visible, projected = shape
        # normalize comparisons to (conj, col, op, literal)
        comps = []
        for c in split_conjuncts_ast(sel.where):
            if isinstance(c, ast.BinOp) and c.op in (
                    "=", "<", "<=", ">", ">="):
                lhs, rhs, op = c.left, c.right, c.op
                if isinstance(lhs, ast.Literal) and \
                        isinstance(rhs, ast.ColumnRef):
                    lhs, rhs = rhs, lhs
                    op = self._FLIP_OP.get(op, op)
                if (isinstance(lhs, ast.ColumnRef)
                        and lhs.table in (None, tname)
                        and lhs.name in visible
                        and isinstance(rhs, ast.Literal)
                        and rhs.value is not None):
                    comps.append((c, lhs.name, op, rhs))
                    continue
            comps.append((c, None, None, None))
        cands = []
        if schema.primary_key:
            cands.append(("primary", tuple(schema.primary_key)))
        for idx in self._table_indexes(tname):
            if idx.state == "public":
                cands.append((idx.name, tuple(idx.columns)))
        for label, cols in cands:
            consumed = []
            eq_vals = []
            p = 0
            for cn in cols:
                hit = next((t for t in comps
                            if t[1] == cn and t[2] == "="), None)
                if hit is None:
                    break
                v = self._coerce_index_literal(schema.column(cn),
                                               hit[3])
                if v is None:
                    break  # NOT consumed: stays in the residual
                consumed.append(hit[0])
                eq_vals.append(v)
                p += 1
            lo = hi = None
            lo_strict = hi_strict = False
            if p < len(cols):
                rng_col = cols[p]
                for t in comps:
                    if t[1] != rng_col or t[2] in ("=", None):
                        continue
                    v = self._coerce_index_literal(
                        schema.column(rng_col), t[3])
                    if v is None:
                        continue  # inexact bound: leave as residual
                    strict = t[2] in (">", "<")
                    if t[2] in (">", ">="):
                        # tighter lower bound: higher value wins;
                        # at a tie, strict (>) excludes more
                        if lo is None or v > lo or \
                                (v == lo and strict and not lo_strict):
                            lo, lo_strict = v, strict
                    else:
                        # tighter upper bound: lower value wins;
                        # at a tie, strict (<) excludes more
                        if hi is None or v < hi or \
                                (v == hi and strict and not hi_strict):
                            hi, hi_strict = v, strict
                    consumed.append(t[0])
            if p == len(cols) or (p == 0 and lo is None
                                  and hi is None):
                continue  # full-eq (eq path) or unconstrained
            residual = any(t[0] not in consumed for t in comps)
            # index order serves: no ORDER BY, or ascending on the
            # range column (eq-prefix columns are constants)
            order_ok = not sel.order_by or (
                p < len(cols)
                and len(sel.order_by) == 1
                and isinstance(sel.order_by[0].expr, ast.ColumnRef)
                and sel.order_by[0].expr.name == cols[p]
                and not sel.order_by[0].desc
                and cols[p] in projected)
            if sel.order_by and not order_ok:
                if not all(isinstance(ob.expr, ast.ColumnRef)
                           and ob.expr.name in projected
                           for ob in sel.order_by):
                    continue  # cannot even host-sort the output
            return {"label": label, "cols": cols, "p": p,
                    "eq_vals": tuple(eq_vals), "lo": lo,
                    "lo_strict": lo_strict, "hi": hi,
                    "hi_strict": hi_strict, "residual": residual,
                    "order_ok": order_ok}
        return None

    def _exec_range_fastpath(self, sel: ast.Select, session: Session,
                             m: dict) -> Optional[Result]:
        import bisect
        tname = sel.table.name
        td = self.store.table(tname)
        read_ts = self._as_of_ts(sel, session) or \
            self._read_ts(session)
        rts = read_ts.to_int()
        entries = self.store.ensure_sorted_index(tname, m["cols"])
        p, eq_vals = m["p"], m["eq_vals"]
        lo_key = eq_vals + ((m["lo"],) if m["lo"] is not None else ())
        kl = len(lo_key)
        if kl:
            fn = (bisect.bisect_right if m["lo_strict"]
                  else bisect.bisect_left)
            start = fn(entries, lo_key, key=lambda e: e[0][:kl])
        else:
            start = 0
        if m["hi"] is not None:
            hi_key = eq_vals + (m["hi"],)
            kh = len(hi_key)
            fn = (bisect.bisect_left if m["hi_strict"]
                  else bisect.bisect_right)
            end = fn(entries, hi_key, key=lambda e: e[0][:kh])
        elif p:
            end = bisect.bisect_right(entries, eq_vals,
                                      key=lambda e: e[0][:p])
        else:
            end = len(entries)
        self._register_table_read(session.txn, tname, read_ts)
        pending = (self._txn_key_state(session.effects, tname)
                   if session.txn is not None else {})
        limit = int(session.vars.get("index_lookup_limit", 4096))
        # early termination is sound only when the index order is the
        # output order, nothing further filters rows, and no txn
        # overlay could add rows that sort earlier
        want = None
        if m["order_ok"] and not m["residual"] and not pending \
                and sel.limit is not None:
            want = sel.limit + (sel.offset or 0)
        rows = []
        for i in range(start, end):
            _vals, ci, ri = entries[i]
            c = td.chunks[ci]
            if not (c.mvcc_ts[ri] <= rts < c.mvcc_del[ri]):
                continue
            row = self.store.extract_row(td, c, ri)
            if pending and td.codec.key(row) in pending:
                continue
            rows.append(row)
            if want is not None and len(rows) >= want:
                break
            if len(rows) > limit:
                return None  # low selectivity: compiled scan wins
        for _key, r in pending.items():
            if r is None:
                continue
            r = dict(r)
            if td.codec.synthetic_pk and ROWID not in r:
                r[ROWID] = 0
            vals = tuple(r.get(cn) for cn in m["cols"])
            if any(v is None for v in vals):
                continue
            if vals[:p] != eq_vals:
                continue
            if p < len(m["cols"]):
                v = vals[p]
                if m["lo"] is not None and (
                        v < m["lo"] or (m["lo_strict"]
                                        and v == m["lo"])):
                    continue
                if m["hi"] is not None and (
                        v > m["hi"] or (m["hi_strict"]
                                        and v == m["hi"])):
                    continue
            rows.append(r)
        return self._fastpath_project(sel, session, td, rows, rts,
                                      apply_where=m["residual"])

    def _fastpath_project(self, sel: ast.Select, session: Session,
                          td, rows: list, rts: int,
                          apply_where: bool = True) -> Result:
        """Shared fastpath tail: residual WHERE over a mini chunk
        (skipped when the index consumed every conjunct — the mini
        chunk costs an eager device round trip), projection,
        ORDER BY / OFFSET / LIMIT, client decode."""
        tname = sel.table.name
        if apply_where and rows and sel.where is not None:
            scope, _ = self._dml_scope(tname)
            predf = self._chunk_pred(tname, sel.where, scope, session)
            mini = self._delta_chunk(td, rows, rts)
            mask = np.asarray(predf(mini))
            rows = [r for r, m in zip(rows, mask) if m]
        schema = td.schema
        out: list[tuple[str, object]] = []  # (output name, column)
        for item in sel.items:
            if item.star:
                for c in schema.columns:
                    if not getattr(c, "hidden", False):
                        out.append((c.name, c))
            else:
                col = schema.column(item.expr.name)
                out.append((item.alias or item.expr.name, col))
        names = [n for n, _ in out]
        types = [c.type for _, c in out]
        res_rows = [tuple(_decode_storage_value(r.get(c.name), c.type)
                          for _, c in out) for r in rows]
        if sel.order_by:
            res_rows = self._sort_decoded(res_rows, names, sel.order_by)
        if sel.offset:
            res_rows = res_rows[sel.offset:]
        if sel.limit is not None:
            res_rows = res_rows[:sel.limit]
        return Result(names=names, rows=res_rows, types=types)

    def _exec_setop(self, so: ast.SetOp, session: Session,
                    sql_text: str) -> Result:
        """UNION / INTERSECT / EXCEPT [ALL]: both branches execute as
        ordinary statements (each fully device-compiled); the combine
        is a host multiset merge over decoded rows — matching the
        reference's setOpNode, which likewise merges above the
        vectorized inputs (sql/union.go)."""
        import copy
        if so.ctes:
            # WITH over a set op: materialize temps then recurse with
            # names rewritten in both branches
            temps: list[str] = []
            mapping: dict[str, str] = {}
            so = copy.copy(so)
            try:
                for name, cols, sub in so.ctes:
                    sub = _rewrite_table_names(sub, mapping)
                    res = self._exec_select(sub, session,
                                            f"(cte {sub!r})")
                    tname = f"__cte{self._temp_seq()}_{name}"
                    self._materialize_temp(tname, res, cols)
                    mapping[name] = tname
                    temps.append(tname)
                so.ctes = []
                so = _rewrite_table_names(so, mapping)
                return self._exec_setop(so, session, sql_text)
            finally:
                for t in temps:
                    if t in self.store.tables:
                        self.store.drop_table(t)
                        for k in [k for k in self._device_tables
                                  if k[0] == t]:
                            self._evict_device(k)
        left = self._exec_select(so.left, session,
                                 f"(setop-l {so.left!r})")
        right = self._exec_select(so.right, session,
                                  f"(setop-r {so.right!r})")
        if len(left.names) != len(right.names):
            raise EngineError(
                f"each {so.op.upper()} branch must have the same "
                f"number of columns ({len(left.names)} vs "
                f"{len(right.names)})")
        for lt, rt in zip(left.types, right.types):
            if lt.family != rt.family and \
                    "unknown" not in (lt.family.value, rt.family.value):
                raise EngineError(
                    f"{so.op.upper()} branch column types do not "
                    f"match: {lt} vs {rt}")
        lrows, rrows = list(left.rows), list(right.rows)
        if so.op == "union":
            rows = lrows + rrows
            if not so.all:
                rows = list(dict.fromkeys(rows))
        elif so.op == "intersect":
            from collections import Counter
            rc = Counter(rrows)
            if so.all:
                rows = []
                for r in lrows:
                    if rc[r] > 0:
                        rc[r] -= 1
                        rows.append(r)
            else:
                rset = set(rrows)
                rows = list(dict.fromkeys(
                    r for r in lrows if r in rset))
        else:  # except
            from collections import Counter
            rc = Counter(rrows)
            if so.all:
                rows = []
                for r in lrows:
                    if rc[r] > 0:
                        rc[r] -= 1
                    else:
                        rows.append(r)
            else:
                rset = set(rrows)
                rows = list(dict.fromkeys(
                    r for r in lrows if r not in rset))
        if so.order_by:
            rows = self._sort_decoded(rows, left.names, so.order_by)
        if so.offset:
            rows = rows[so.offset:]
        if so.limit is not None:
            rows = rows[:so.limit]
        return Result(names=list(left.names), rows=rows,
                      types=list(left.types))

    @staticmethod
    def _sort_decoded(rows: list, names: list, order_by) -> list:
        """Host sort of decoded rows by output columns/positions; pg
        NULL ordering (last for asc, first for desc)."""
        out = list(rows)
        for ob in reversed(order_by):
            if isinstance(ob.expr, ast.Literal) \
                    and isinstance(ob.expr.value, int):
                i = ob.expr.value - 1
            elif isinstance(ob.expr, ast.ColumnRef) \
                    and ob.expr.name in names:
                i = names.index(ob.expr.name)
            else:
                raise EngineError(
                    "set-op ORDER BY must reference output columns")

            def key(r, i=i):
                v = r[i]
                return (v is None, v)
            out.sort(key=key, reverse=ob.desc)
        return out

    def _check_join_builds(self, node, read_ts: Timestamp,
                           overlay: set = frozenset()) -> None:
        """The device hash join gathers ONE build row per probe key
        (ops/join.py: exact for unique build keys). Verify build-side
        key uniqueness on the host over the rows VISIBLE at the query's
        read timestamp before running — a duplicate-keyed build must be
        a clean error, never a silently-dropped match. The reference's
        hash join handles duplicates by row expansion (colexecjoin/
        hashjoiner.go:870); that emission strategy is future work."""

        def walk(n):
            if isinstance(n, P.HashJoin):
                if n.join_type in ("inner", "left"):
                    self._check_one_build(n, read_ts, overlay)
                walk(n.left)
                walk(n.right)
                return
            for attr in ("child",):
                c = getattr(n, attr, None)
                if c is not None:
                    walk(c)

        walk(node)

    def _check_one_build(self, join, read_ts: Timestamp,
                         overlay: set) -> None:
        from ..sql.stats import _underlying_col
        b = join.right
        if not isinstance(b, P.Scan):
            return
        stored = []
        all_plain = True  # every key is a stored column, not computed
        computed = dict(b.computed)
        for rk in join.right_keys:
            sname = b.columns.get(rk)
            if sname is None:
                all_plain = False
                # computed key: a dictionary-code remap of a column is
                # injective, so check the underlying column instead
                inner = _underlying_col(computed.get(rk))
                if inner is not None:
                    sname = b.columns.get(inner.name)
            if sname is None:
                return  # cannot map back to storage; accept
            stored.append(sname)
        # direct addressing needs the RUNTIME key values' range, so
        # only plain stored keys qualify (a remapped key's codes live
        # in the other dictionary's space)
        if all_plain:
            self._maybe_direct_join(join, b, stored, read_ts, overlay)
        # txn-buffered writes to the build table are invisible to the
        # store's committed-rows measurements: each buffered put can
        # add one more row per key, so it widens the bound — and
        # forfeits the uniqueness fast path
        buffered_puts = self._overlay_put_count(b.table, overlay)
        if buffered_puts == 0 and self.store.keys_unique_for_read(
                b.table, tuple(stored), read_ts.to_int()):
            join.expand = 1
            return
        # duplicate-keyed build: measure the max multiplicity among
        # visible rows and bake it in as the STATIC expansion factor
        # (ops/join.py expansion path). NB: measured at TABLE
        # granularity — a pushed build filter can only reduce the true
        # multiplicity, so K is a safe upper bound.
        k = self.store.key_max_multiplicity(b.table, tuple(stored),
                                            read_ts.to_int()) \
            + buffered_puts
        if k > self.MAX_JOIN_EXPANSION:
            raise EngineError(
                f"hash join build side {b.table!r} has up to {k} "
                f"duplicate rows per key {stored} (limit "
                f"{self.MAX_JOIN_EXPANSION}); make the lower-"
                "multiplicity table the build side")
        join.expand = max(k, 1)

    @staticmethod
    def _overlay_put_count(table: str, overlay) -> int:
        """Buffered put-ops on `table` in the current txn (0 when the
        caller passed a plain membership set)."""
        if isinstance(overlay, dict):
            return overlay.get(table, 0)
        return 0

    MAX_DIRECT_JOIN_SLOTS = 1 << 22

    def _maybe_direct_join(self, join, b, stored, read_ts,
                           overlay: set) -> None:
        """Direct-address the join when the single build key is
        int-family with a dense live-value range (dimension pks, dict
        codes): one scatter + one gather instead of hash-table
        while_loops, which TPUs execute ~100x slower. Skipped for
        txn-overlay builds — uncommitted rows could fall outside the
        measured range and steal slots from committed matches."""
        join.direct = None
        if len(stored) != 1 or b.table in overlay:
            return
        col = self.store.table(b.table).schema.column(stored[0])
        if col.type.family == Family.FLOAT:
            return
        r = self.store.key_int_range(b.table, stored[0])
        if r is None:
            return
        lo, hi, n_all = r
        span = hi - lo + 1
        if span <= max(4 * n_all, 1024) \
                and span + 1 <= self.MAX_DIRECT_JOIN_SLOTS:
            join.direct = (lo, span + 1)

    def _dist_decision(self, node, session: Session):
        """Choose distributed (SPMD over the mesh) vs single-device —
        the analogue of the DistSQL distribution decision
        (sql/distsql_physical_planner.go shouldDistributePlan)."""
        if session.vars.get("distsql", "auto") == "off":
            return None
        if self.mesh is None or self.mesh.size <= 1:
            return None
        if self.mesh.size & (self.mesh.size - 1):
            return None  # table padding is pow2; shards must divide it
        if not self.settings.get("sql.distsql.mesh_partitioning.enabled"):
            return None
        d = dist_analyze(node)
        return d if d.ok else None

    def _maybe_generate_series(self, sel: ast.Select, binder: Binder):
        """SELECT generate_series(a, b [, step]) — the one supported
        set-returning function (pg SRF in the select list), table-free
        context only; args must fold to constants."""
        if len(sel.items) != 1 or sel.items[0].star:
            return None
        e = sel.items[0].expr
        if not (isinstance(e, ast.FuncCall)
                and e.name == "generate_series"):
            return None
        if sel.where is not None or sel.distinct or sel.group_by \
                or sel.having:
            raise EngineError(
                "generate_series supports only ORDER BY/LIMIT/OFFSET "
                "(materialize it in a CTE for WHERE/GROUP BY)")
        if len(e.args) not in (2, 3):
            raise EngineError("generate_series(start, stop [, step])")
        vals = []
        for a in e.args:
            b = binder.bind(a)
            if not isinstance(b, BConst) or b.value is None:
                raise EngineError(
                    "generate_series arguments must be constants")
            vals.append(int(b.value))
        start, stop = vals[0], vals[1]
        step = vals[2] if len(vals) == 3 else 1
        if step == 0:
            raise EngineError("generate_series step cannot be 0")
        series = range(start, stop + (1 if step > 0 else -1), step)
        name = sel.items[0].alias or "generate_series"
        rows = [(int(v),) for v in series]
        if sel.order_by:
            rows = self._sort_decoded(rows, [name], sel.order_by)
        if sel.offset:
            rows = rows[sel.offset:]
        if sel.limit is not None:
            rows = rows[:sel.limit]
        from ..sql.types import INT8
        return Result(names=[name], rows=rows, types=[INT8])

    def _exec_table_free(self, sel: ast.Select,
                         session: Session | None = None) -> Result:
        """SELECT <exprs> with no FROM."""
        session = session or self.session()
        read_ts = self._read_ts(session)
        binder = Binder(
            Scope(),
            subquery_eval=lambda s, lim: self._eval_subquery(
                s, session, lim),
            now_micros=read_ts.wall // 1000,
            sequence_ops=self._sequence_ops(session))
        srf = self._maybe_generate_series(sel, binder)
        if srf is not None:
            return srf
        names, exprs = [], []
        for it in sel.items:
            if it.star:
                raise EngineError("SELECT * requires FROM")
            b = binder.bind(it.expr)
            names.append(it.alias or "column")
            exprs.append(b)
        ctx = ExprContext({}, 1)
        row = []
        types = []
        for b in exprs:
            if isinstance(b, BConst):
                # constants (incl. folded string builtins) skip the
                # device: strings have no resident dictionary here
                v = b.value
                if b.type.family == Family.DECIMAL and v is not None:
                    v = v / 10 ** b.type.scale
                elif b.type.family == Family.DATE and v is not None:
                    v = EPOCH_DATE + datetime.timedelta(days=int(v))
                elif b.type.family == Family.TIMESTAMP and v is not None:
                    v = EPOCH_DT + datetime.timedelta(microseconds=int(v))
                row.append(v)
                types.append(b.type)
                continue
            d, v = compile_expr(b)(ctx)
            row.append(_decode_scalar(np.asarray(d)[0], bool(np.asarray(v)[0]),
                                      b.type, None))
            types.append(b.type)
        return Result(names=names, rows=[tuple(row)], types=types)

    # -- hash-partitioned spill ---------------------------------------------
    MAX_SPILL_PARTITIONS = 256
    # duplicate-key join expansion cap: output rows = probe.n * K
    MAX_JOIN_EXPANSION = 32

    def _run_partitioned(self, prep: "Prepared",
                         read_ts: Optional[Timestamp]) -> Result:
        """Partition-and-recurse fallback for hash GROUP BY overflow.

        The compiled program already takes (nparts, pid) scalars and
        keeps only rows whose salted key-hash lands in partition pid
        (ops/hashtable.py partition_mask), so spilling is: rerun the
        SAME program once per partition, concatenate the per-partition
        group rows on the host, then apply any Sort/Limit there
        (device sort/limit would have been per-partition). Doubling
        the partition count until every partition fits mirrors the
        reference's recursive hash_based_partitioner; re-reads hit the
        resident HBM table instead of disk.
        """
        node, meta = self._plan(prep.stmt, prep.session)
        limit_node = sort_node = None
        if isinstance(node, P.Limit):
            limit_node, node = node, node.child
        if isinstance(node, P.Sort):
            sort_node, node = node, node.child
        if not isinstance(node, P.Aggregate) or node.max_groups > 0:
            raise HashCapacityExceeded(
                "GROUP BY overflow in a non-spillable plan shape; "
                "SET hash_group_capacity to a larger power of two")

        # compile the STRIPPED plan (no device Sort/Limit — a per-
        # partition limit would truncate wrongly); reuse prep's device
        # scans, which already match the distribution decision
        cap = int(prep.session.vars.get("hash_group_capacity", 1 << 17))
        decision = self._dist_decision(node, prep.session)
        shapes = tuple(sorted((a, b.n) for a, b in prep.scans.items()))
        dictlens = tuple(
            sorted((t, tuple(sorted((cn, len(d)) for cn, d in
                                    self.store.table(t).dictionaries
                                    .items())))
                   for t, _ in prep.gens))
        key = ("spill", prep.sql_text, shapes, dictlens, cap,
               decision is not None, hash(repr(node)))
        cached = self._exec_cache.get(key)
        if cached is None:
            params = ExecParams(
                hash_group_capacity=cap,
                axis_name=SHARD_AXIS if decision is not None else None)
            runf = compile_plan(node, params, meta)
            if decision is not None:
                jfn = jax.jit(make_distributed_fn(
                    runf, self.mesh, _collect_scans(node), decision))
            else:
                def fn(scans_in, ts_in, np_, pid_):
                    return runf(RunContext(scans_in, ts_in, np_, pid_))
                jfn = jax.jit(fn)
            self._exec_cache[key] = (jfn, meta)
        else:
            jfn, meta = cached

        ts = read_ts or self._read_ts(prep.session)
        tsv = np.int64(ts.to_int())
        nparts = 2
        while nparts <= self.MAX_SPILL_PARTITIONS:
            try:
                all_rows: list[tuple] = []
                for pid in range(nparts):
                    out = jfn(prep.scans, tsv, np.int32(nparts),
                              np.int32(pid))
                    part = self._materialize(out, meta)
                    all_rows.extend(part.rows)
                break
            except HashCapacityExceeded:
                nparts *= 2
        else:
            raise HashCapacityExceeded(
                f"GROUP BY did not fit hash_group_capacity even at "
                f"{self.MAX_SPILL_PARTITIONS} spill partitions")

        rows = all_rows
        if sort_node is not None:
            rows = _host_sort(rows, meta, sort_node.keys)
        if limit_node is not None:
            off = limit_node.offset or 0
            end = (off + limit_node.limit
                   if limit_node.limit is not None else None)
            rows = rows[off:end]
        return Result(names=list(meta.names), rows=rows)

    # -- beyond-HBM streaming ------------------------------------------------
    def _stream_decision(self, node, scan_aliases: dict, scan_cols: dict,
                         session: Session):
        """Page the fact table through HBM when its pruned upload would
        not fit the device budget. Eligibility mirrors the mesh
        distribution analysis (the plan must reduce to mergeable
        aggregate partials); only the probe-spine scan streams.
        Returns (alias, table, page_rows) or None."""
        if session.vars.get("streaming", "auto") == "off":
            return None
        budget = int(self.settings.get("sql.exec.hbm_budget_bytes"))
        if budget <= 0:
            return None
        if not can_stream(node):
            # dist_analyze accepts more shapes (e.g. hash GROUP BY)
            # than paging can compile; never pick those
            return None
        d = dist_analyze(node)
        if not d.ok or len(d.sharded) != 1:
            return None
        alias = next(iter(d.sharded))
        tname = scan_aliases[alias]
        td = self.store.table(tname)
        if td.row_count == 0:
            return None
        # working set = pruned upload + aggregation temporaries. XLA's
        # segment reductions materialize ~2 n-length temps per
        # aggregate concurrently (measured: TPC-H Q1 at 2^27 rows
        # compiles to ~12GB of HLO temps), so a table that "fits" can
        # still OOM at compile time without this term.
        n_aggs = _count_aggs(node)
        padded = max(_next_pow2(max(td.row_count, 1)), 1024)
        temp_bytes = 16 * n_aggs * padded
        if (self._table_device_bytes(td, scan_cols.get(alias))
                + temp_bytes <= budget):
            return None
        # Build-side tables still upload whole: streaming the probe is
        # strictly better than not, and an over-budget build fails
        # upstream with a clean quota error rather than silently here.
        page_rows = max(1024,
                        int(session.vars.get("streaming_page_rows",
                                             1 << 21)))
        return (alias, tname, page_rows)

    def _table_device_bytes(self, td, cols) -> int:
        """Device bytes a pruned upload of this table would take."""
        n = td.row_count
        padded = max(_next_pow2(max(n, 1)), 1024)
        total = 16 * padded  # the two MVCC int64 columns
        for col in td.schema.columns:
            if cols is not None and col.name not in cols:
                continue
            total += (np.dtype(col.type.np_dtype).itemsize + 1) * padded
        return total

    def _iter_pages(self, tname: str, cols, page_rows: int):
        """Yield fixed-shape device pages of a table's chunks. Each
        page is padded to page_rows with never-visible rows so one XLA
        program serves every page."""
        td = self.store.table(tname)
        if td.open_ts:
            self.store.seal(tname)
        chunks = list(td.chunks)
        total = sum(c.n for c in chunks)
        names = [c.name for c in td.schema.columns
                 if cols is None or c.name in cols]
        start = 0
        while start < total:
            end = min(start + page_rows, total)
            data = {cn: _slice_chunks(chunks, lambda c, cn=cn: c.data[cn],
                                      start, end)
                    for cn in names}
            valid = {cn: _slice_chunks(chunks, lambda c, cn=cn: c.valid[cn],
                                       start, end)
                     for cn in names}
            mts = _slice_chunks(chunks, lambda c: c.mvcc_ts, start, end)
            mdl = _slice_chunks(chunks, lambda c: c.mvcc_del, start, end)
            page = {cn: _pad(a, page_rows) for cn, a in data.items()}
            page["_mvcc_ts"] = _pad(mts, page_rows, fill=np.int64(2**62))
            page["_mvcc_del"] = _pad(mdl, page_rows, fill=np.int64(0))
            vmap = {cn: _pad(v, page_rows) for cn, v in valid.items()
                    if not v.all()}
            yield ColumnBatch.from_dict(
                {k: jnp.asarray(v) for k, v in page.items()},
                {k: jnp.asarray(v) for k, v in vmap.items()})
            start = end

    # -- device table cache --------------------------------------------------
    def _evict_device(self, key) -> None:
        self._device_tables.pop(key, None)
        self.hbm.release(key)

    def drop_device_cache(self) -> None:
        """Evict every resident table upload AND release its memory
        reservation (a raw _device_tables.clear() would leak the
        monitor's accounting)."""
        for k in list(self._device_tables):
            self._evict_device(k)

    def _device_table(self, name: str, placement: str = "single",
                      cols: frozenset | None = None) -> ColumnBatch:
        td = self.store.table(name)
        # a cached upload with a SUPERSET of the needed columns serves
        # this scan directly (scans read columns by name); this keeps
        # one resident copy per table instead of one per column set
        for k, v in self._device_tables.items():
            if (k[0] == name and k[1] == td.generation
                    and k[2] == placement
                    and (k[3] is None
                         or (cols is not None and cols <= k[3]))):
                return v
        # evict stale generations of this table
        for k in [k for k in self._device_tables if k[0] == name
                  and k[1] != td.generation]:
            self._evict_device(k)
        if td.open_ts:
            self.store.seal(name)
        key = (name, td.generation, placement, cols)
        # account BEFORE upload; replication costs a copy per device
        nbytes = self._table_device_bytes(td, cols)
        if placement == "replicated" and self.mesh is not None:
            nbytes *= self.mesh.size
        self.hbm.reserve(key, nbytes)
        try:
            b = self._batch_from_chunks(td, td.chunks, cols)
            if placement == "sharded":
                b = jax.device_put(b, meshmod.row_sharding(self.mesh))
            elif placement == "replicated":
                b = jax.device_put(b, meshmod.replicated(self.mesh))
        except BaseException:
            self.hbm.release(key)
            raise
        # drop now-redundant strict-subset uploads of the same table
        for k in [k for k in self._device_tables
                  if k[0] == name and k[1] == td.generation
                  and k[2] == placement and k[3] is not None
                  and (cols is None or k[3] < cols)]:
            self._evict_device(k)
        self._device_tables[key] = b
        self.metrics.counter("sql.device.table_uploads",
                             "resident table uploads to HBM").inc()
        return b

    def _batch_from_chunks(self, td, chunks: list,
                           prune: frozenset | None = None) -> ColumnBatch:
        """Concatenate chunks, pad to a power-of-two row bucket, and
        upload as a device-resident ColumnBatch with MVCC columns.
        With ``prune`` set, only those stored columns upload (the scan
        projection; HBM is the scarce resource the reference's
        needed-columns fetch logic protects, cfetcher.go:668)."""
        cols: dict[str, np.ndarray] = {}
        valid: dict[str, np.ndarray] = {}
        n = sum(c.n for c in chunks)
        padded = max(_next_pow2(max(n, 1)), 1024)
        for col in td.schema.columns:
            cn = col.name
            if prune is not None and cn not in prune:
                continue
            parts = [c.data[cn] for c in chunks]
            arr = (np.concatenate(parts) if parts
                   else np.zeros(0, dtype=col.type.np_dtype))
            vparts = [c.valid[cn] for c in chunks]
            va = np.concatenate(vparts) if vparts else np.zeros(0, bool)
            cols[cn] = _pad(arr, padded)
            if not va.all():
                # all-valid masks regenerate on device (ones) for free
                # instead of paying PCIe for a constant
                valid[cn] = _pad(va, padded)
        ts_parts = [c.mvcc_ts for c in chunks]
        del_parts = [c.mvcc_del for c in chunks]
        mts = np.concatenate(ts_parts) if ts_parts else np.zeros(0, np.int64)
        mdl = (np.concatenate(del_parts) if del_parts
               else np.zeros(0, np.int64))
        # padding rows are never visible: created at +inf
        cols["_mvcc_ts"] = _pad(mts, padded, fill=np.int64(2**62))
        cols["_mvcc_del"] = _pad(mdl, padded, fill=np.int64(0))
        return ColumnBatch.from_dict(
            {k: jnp.asarray(v) for k, v in cols.items()},
            {k: jnp.asarray(v) for k, v in valid.items()})

    def _overlay_batch(self, name: str, effects: list,
                       read_ts: Timestamp) -> ColumnBatch:
        """Uncached device snapshot of committed chunks + this txn's
        buffered effects (read-your-own-writes)."""
        td = self.store.table(name)
        chunks = self._overlay_chunks(name, effects, read_ts)
        return self._batch_from_chunks(td, chunks)

    # -- result materialization ---------------------------------------------
    def _materialize(self, out: ColumnBatch, meta: P.OutputMeta) -> Result:
        if out.has("__ht_overflow"):
            if bool(np.asarray(out.col("__ht_overflow"))[0]):
                raise HashCapacityExceeded(
                    "GROUP BY cardinality exceeded hash_group_capacity; "
                    "SET hash_group_capacity to a larger power of two")
        if out.has("__sum_overflow"):
            if bool(np.asarray(out.col("__sum_overflow"))[0]):
                raise EngineError(
                    "decimal SUM overflowed int64 accumulation; "
                    "CAST the argument to FLOAT to trade exactness for range")
        host = out.to_host()
        res = Result(names=list(meta.names), types=list(meta.types))
        cols = []
        for name, ty in zip(meta.names, meta.types):
            arr = host[name]
            d = meta.dictionaries.get(name)
            cols.append(_decode_column(arr, ty, d))
        res.rows = list(zip(*cols)) if cols else []
        return res

    # -- DDL -----------------------------------------------------------------
    def _exec_create(self, c: ast.CreateTable) -> Result:
        from ..catalog import (CatalogError, IndexDescriptor,
                               TableDescriptor)
        if c.name in self.store.tables:
            if c.if_not_exists:
                return Result(tag="CREATE TABLE")
            raise EngineError(f"table {c.name!r} already exists")
        schema = TableSchema(
            name=c.name,
            columns=[ColumnSchema(d.name, d.type, d.nullable)
                     for d in c.columns],
            primary_key=list(c.primary_key))
        colnames = {d.name for d in c.columns}
        # validate FK references now (the reference resolves them in
        # the descriptor builder): target must exist and the referenced
        # columns must be its primary key or a unique index
        # unique column / table constraints become unique indexes at
        # birth (the table is empty — no backfill, straight to PUBLIC)
        uniq_sets = [[d.name] for d in c.columns if d.unique] \
            + [list(u) for u in c.uniques]
        fk_records = []
        for fkname, lcols, rt, rcols in c.foreign_keys:
            for cn in lcols:
                if cn not in colnames:
                    raise EngineError(f"fk column {cn!r} not in table")
            if rt == c.name:
                # self-referential: validate against the in-flight
                # definition (the table does not exist yet)
                rcols = rcols or list(c.primary_key)
                unique_sets = [tuple(c.primary_key)] + \
                    [tuple(u) for u in uniq_sets]
            elif rt in self.store.tables:
                rschema = self.store.table(rt).schema
                rcols = rcols or list(rschema.primary_key)
                unique_sets = [tuple(rschema.primary_key)] + [
                    tuple(i.columns) for i in self._table_indexes(rt)
                    if i.unique]
            else:
                raise EngineError(
                    f"referenced table {rt!r} does not exist")
            if tuple(rcols) not in unique_sets:
                raise EngineError(
                    f"foreign key must reference a primary key or "
                    f"unique index of {rt!r} (got {rcols})")
            if len(rcols) != len(lcols):
                raise EngineError("foreign key column count mismatch")
            fk_records.append({"name": fkname, "columns": list(lcols),
                               "ref_table": rt,
                               "ref_columns": list(rcols)})
        for u in uniq_sets:
            for cn in u:
                if cn not in colnames:
                    raise EngineError(
                        f"unique column {cn!r} not in table")
        desc0 = TableDescriptor.from_schema(schema)
        desc0.checks = [{"name": n, "expr_sql": text}
                        for n, _e, text in c.checks]
        desc0.fks = fk_records
        desc0.indexes = [
            IndexDescriptor(f"{c.name}_{'_'.join(u)}_key", 2 + i,
                            list(u), True, "public")
            for i, u in enumerate(uniq_sets)]
        # the descriptor (catalog, system of record) is written first,
        # transactionally — two racing CREATEs conflict on the
        # namespace key; the columnstore table is the scan-plane
        # materialization keyed by the allocated descriptor id
        try:
            desc = self.catalog.create_table(desc0)
        except CatalogError as e:
            if c.if_not_exists:
                return Result(tag="CREATE TABLE")
            raise EngineError(str(e)) from e
        schema.table_id = desc.id
        self.store.create_table(schema)
        self._index_defs.pop(c.name, None)
        self._constraint_defs.pop(c.name, None)
        self._fk_children = None
        # CHECK expressions must bind against the new schema (catches
        # unknown columns / type errors at DDL time)
        try:
            scope, _ = self._dml_scope(c.name)
            for n, e, _text in c.checks:
                b = Binder(scope).bind(e)
                if b.type.family != Family.BOOL:
                    raise EngineError(
                        f"check constraint {n!r} must be boolean")
        except Exception:
            self.store.drop_table(c.name)
            self.catalog.drop_table(c.name)
            self._fk_children = None
            raise
        return Result(tag="CREATE TABLE")

    def _exec_drop(self, d: ast.DropTable) -> Result:
        from ..catalog import CatalogError
        if d.name in self._view_map():
            raise EngineError(
                f"{d.name!r} is a view; use DROP VIEW")
        deps = [v for v, vd in self._view_map().items()
                if d.name in _stmt_table_refs(
                    parser.parse(vd.view_sql))]
        if deps:
            raise EngineError(
                f"cannot drop table {d.name!r}: view(s) "
                f"{sorted(deps)} depend on it")
        fk_deps = sorted({child for child, _fk in
                          self._fk_children_of(d.name)
                          if child != d.name})
        if fk_deps:
            raise EngineError(
                f"cannot drop table {d.name!r}: foreign key(s) on "
                f"{fk_deps} reference it")
        if d.name not in self.store.tables:
            if d.if_exists:
                return Result(tag="DROP TABLE")
            raise EngineError(f"table {d.name!r} does not exist")
        try:
            self.catalog.drop_table(d.name)
        except CatalogError:
            pass  # store-only table (pre-catalog tests); still drop it
        self.store.drop_table(d.name)
        self._index_defs.pop(d.name, None)
        self._constraint_defs.pop(d.name, None)
        self._fk_children = None
        for k in [k for k in self._device_tables if k[0] == d.name]:
            self._evict_device(k)
        return Result(tag="DROP TABLE")

    # -- secondary indexes ----------------------------------------------------
    # Design (vs pkg/sql/rowenc + colfetcher/index_join.go): the scan
    # plane is columnar and the analytic path never decodes keys, so a
    # non-unique index is a *derived* host-side locator over the
    # columnstore (generation-cached, storage/columnstore.py
    # ensure_secondary_index) used for point-read/DML acceleration.
    # UNIQUE indexes additionally materialize KV entries at
    # /Table/<tid>/<index_id>/<vals> -> pk-key through the row-plane
    # txn, so two concurrent writers of the same value conflict
    # transactionally — the same guarantee the reference gets from
    # CPut on index keys (pkg/sql/row/writer.go).

    def _table_indexes(self, table: str) -> list:
        cached = self._index_defs.get(table)
        if cached is not None:
            return cached
        # a transient catalog error must fail the statement, NOT be
        # cached as "no indexes" (which would silently drop unique
        # enforcement); a missing descriptor (pre-catalog test table)
        # legitimately has none
        d = self.catalog.get_by_name(table)
        idxs = list(d.indexes) if d is not None else []
        self._index_defs[table] = idxs
        return idxs

    def _exec_create_index(self, c: ast.CreateIndex,
                           session: Session) -> Result:
        from ..catalog import IndexDescriptor
        from ..catalog.descriptor import WRITE_ONLY
        from ..jobs.schemachange import INDEX_BACKFILL_JOB
        if c.table not in self.store.tables:
            raise EngineError(f"table {c.table!r} does not exist")
        td = self.store.table(c.table)
        for cn in c.columns:
            try:
                td.schema.column(cn)
            except KeyError:
                raise EngineError(
                    f"column {cn!r} does not exist in {c.table!r}")
        desc = self.catalog.get_by_name(c.table)
        if desc is None:
            raise EngineError(
                f"table {c.table!r} has no descriptor (pre-catalog)")
        if c.name == "primary":
            raise EngineError(
                "index name 'primary' is reserved for the primary key")
        if any(i.name == c.name for i in desc.indexes):
            if c.if_not_exists:
                return Result(tag="CREATE INDEX")
            raise EngineError(
                f"index {c.name!r} already exists on {c.table!r}")
        next_id = 1 + max([i.index_id for i in desc.indexes],
                          default=1)  # primary index is 1
        # step 1: WRITE_ONLY — after the lease drain every writer
        # maintains the index, but readers don't use it yet
        desc.indexes.append(IndexDescriptor(
            c.name, next_id, list(c.columns), c.unique, WRITE_ONLY))
        desc = self.leases.publish(desc)
        self._index_defs.pop(c.table, None)
        # step 2: chunk-checkpointed backfill + validation + PUBLIC
        # publish as a durable job (resumable after a crash), like the
        # reference's index backfiller (pkg/sql/backfill via pkg/jobs)
        job_id = self.jobs.create(INDEX_BACKFILL_JOB,
                                  {"table": c.table, "index": c.name})
        rec = self.jobs.run_job(job_id)
        self._index_defs.pop(c.table, None)
        if rec.status != "succeeded":
            raise EngineError(
                f"CREATE INDEX failed: {rec.error or rec.status}")
        return Result(tag="CREATE INDEX")

    def _exec_drop_index(self, d_stmt: ast.DropIndex,
                         session: Session) -> Result:
        found = []
        for desc in self.catalog.list_tables():
            for i in desc.indexes:
                if i.name == d_stmt.name:
                    found.append((desc, i))
        if not found:
            if d_stmt.if_exists:
                return Result(tag="DROP INDEX")
            raise EngineError(f"index {d_stmt.name!r} does not exist")
        if len(found) > 1:
            tables = sorted(d.name for d, _ in found)
            raise EngineError(
                f"index name {d_stmt.name!r} is ambiguous (exists on "
                f"tables {tables}); drop and recreate with distinct "
                f"names")
        desc, idx = found[0]
        desc.indexes = [i for i in desc.indexes if i.name != idx.name]
        self.leases.publish(desc)
        self._index_defs.pop(desc.name, None)
        if idx.unique:
            # clear the index keyspace (the reference runs this as a
            # GC-TTL'd schema-change job; immediate here)
            p = K.table_prefix(desc.id, idx.index_id)
            self.kv.txn(lambda t: t.delete_range(p, K.prefix_end(p)))
        return Result(tag="DROP INDEX")

    # -- views ----------------------------------------------------------------
    # A view is a descriptor carrying SQL text; every use re-plans it
    # as a derived table (pkg/sql/create_view.go + opt view expansion).

    def _view_map(self) -> dict:
        if getattr(self, "_view_defs", None) is None:
            self._view_defs = {
                d.name: d for d in self.catalog.list_tables()
                if d.view_sql}
        return self._view_defs

    def _expand_views(self, sel: ast.Select,
                      depth: int = 0) -> ast.Select:
        views = self._view_map()
        # SQL scoping: a CTE binding shadows a same-named view
        cte_names = {name for name, _c, _s in sel.ctes}
        if cte_names:
            views = {k: v for k, v in views.items()
                     if k not in cte_names}
        if not views:
            return sel
        if depth > 16:
            raise EngineError("view nesting too deep (cycle?)")
        import copy
        refs = ([sel.table] if sel.table is not None else []) \
            + [j.table for j in sel.joins]
        if not any(r.subquery is None and r.name in views
                   for r in refs):
            return sel
        sel = copy.copy(sel)

        def expand_ref(ref: ast.TableRef) -> ast.TableRef:
            if ref.subquery is not None or ref.name not in views:
                return ref
            d = views[ref.name]
            body = parser.parse(d.view_sql)
            if not isinstance(body, ast.Select):
                raise EngineError(
                    f"view {d.name!r} body is not a plain SELECT")
            body = self._expand_views(body, depth + 1)
            if d.view_columns:
                body = copy.copy(body)
                body.items = [
                    ast.SelectItem(it.expr, alias=cn, star=False)
                    for it, cn in zip(body.items, d.view_columns)]
            return ast.TableRef(name=f"__view_{d.name}",
                                alias=ref.alias or ref.name,
                                subquery=body)

        if sel.table is not None:
            sel.table = expand_ref(sel.table)
        sel.joins = [ast.JoinClause(expand_ref(j.table), j.join_type,
                                    j.on) for j in sel.joins]
        return sel

    def _exec_create_view(self, c: ast.CreateView,
                          session: Session) -> Result:
        import copy
        from ..catalog import CatalogError, TableDescriptor
        if c.name in self.store.tables or c.name in self._view_map():
            if c.if_not_exists:
                return Result(tag="CREATE VIEW")
            raise EngineError(f"relation {c.name!r} already exists")
        if not isinstance(c.select, ast.Select):
            raise EngineError(
                "CREATE VIEW body must be a plain SELECT")
        if c.columns is not None and any(
                it.star for it in c.select.items):
            raise EngineError(
                "view column list requires explicit select items")
        # validate by executing the body with LIMIT 0 — catches
        # unknown tables/columns and type errors at DDL time, like the
        # reference's view dependency check
        probe = copy.deepcopy(c.select)
        probe.limit = 0
        res = self._exec_select(probe, session,
                                f"(create-view {c.name})")
        if c.columns is not None and len(c.columns) != len(res.names):
            raise EngineError(
                f"view column list has {len(c.columns)} names, "
                f"SELECT produces {len(res.names)}")
        try:
            self.catalog.create_table(TableDescriptor(
                id=0, name=c.name, view_sql=c.sql,
                view_columns=list(c.columns or [])))
        except CatalogError as e:
            if c.if_not_exists:
                return Result(tag="CREATE VIEW")
            raise EngineError(str(e)) from e
        self._view_defs = None
        return Result(tag="CREATE VIEW")

    def _exec_drop_view(self, d: ast.DropView) -> Result:
        if d.name not in self._view_map():
            if d.if_exists:
                return Result(tag="DROP VIEW")
            raise EngineError(f"view {d.name!r} does not exist")
        deps = [v for v, vd in self._view_map().items()
                if v != d.name and d.name in _stmt_table_refs(
                    parser.parse(vd.view_sql))]
        if deps:
            raise EngineError(
                f"cannot drop view {d.name!r}: view(s) "
                f"{sorted(deps)} depend on it")
        self.catalog.drop_table(d.name)
        self._view_defs = None
        return Result(tag="DROP VIEW")

    # -- sequences (DDL) ------------------------------------------------------
    def _exec_create_sequence(self, c: ast.CreateSequence) -> Result:
        import json as _json
        key = self.SEQ_PREFIX + c.name.encode()

        def fn(t):
            if t.get(key) is not None:
                if c.if_not_exists:
                    return
                raise EngineError(
                    f"sequence {c.name!r} already exists")
            t.put(key, _json.dumps({
                "start": c.start, "increment": c.increment,
                "value": None}).encode())
        self.kv.txn(fn)
        return Result(tag="CREATE SEQUENCE")

    def _exec_drop_sequence(self, d: ast.DropSequence) -> Result:
        key = self.SEQ_PREFIX + d.name.encode()

        def fn(t):
            if t.get(key) is None:
                if d.if_exists:
                    return
                raise EngineError(
                    f"sequence {d.name!r} does not exist")
            t.delete(key)
        self.kv.txn(fn)
        return Result(tag="DROP SEQUENCE")

    # -- TRUNCATE -------------------------------------------------------------
    def _exec_truncate(self, tr: ast.Truncate) -> Result:
        """Clear all rows + KV pairs + index entries, keep the schema
        (the reference swaps in fresh empty indexes and lets GC reap
        the old keyspace, pkg/sql/truncate.go)."""
        if tr.table not in self.store.tables:
            raise EngineError(f"table {tr.table!r} does not exist")
        fk_deps = sorted({child for child, _fk in
                          self._fk_children_of(tr.table)
                          if child != tr.table})
        if fk_deps:
            raise EngineError(
                f"cannot truncate {tr.table!r}: foreign key(s) on "
                f"{fk_deps} reference it")
        td = self.store.table(tr.table)
        schema = td.schema
        # the whole table keyspace: every index id under the table
        base = bytearray(K.TABLE_PREFIX)
        K.encode_int(base, schema.table_id)
        base = bytes(base)
        self.kv.txn(lambda t: t.delete_range(base, K.prefix_end(base)))
        self.store.drop_table(tr.table)
        self.store.create_table(schema)
        self._evict(tr.table)
        return Result(tag="TRUNCATE")

    # -- constraints (CHECK + FOREIGN KEY, restrict semantics) ---------------
    # The analogue of the reference's row-level constraint checks
    # (pkg/sql/row/fk_existence_*.go, check constraints in the
    # writer). FK existence probes run against the scan-plane index
    # locators plus this txn's buffered effects; concurrent-txn races
    # are serialized by the KV plane the same way unique indexes are.

    def _table_constraints(self, table: str) -> tuple:
        cached = self._constraint_defs.get(table)
        if cached is not None:
            return cached
        d = self.catalog.get_by_name(table)
        out = ((list(d.checks), list(d.fks)) if d is not None
               else ([], []))
        self._constraint_defs[table] = out
        return out

    def _fk_children_of(self, table: str) -> list:
        """[(child_table, fk_record)] of FKs referencing `table`."""
        if self._fk_children is None:
            m: dict[str, list] = {}
            for d in self.catalog.list_tables():
                for fk in d.fks:
                    m.setdefault(fk["ref_table"], []).append(
                        (d.name, fk))
            self._fk_children = m
        return self._fk_children.get(table, [])

    def _enforce_checks(self, table: str, td, rows: list,
                        rts: int) -> None:
        checks, _ = self._table_constraints(table)
        if not checks or not rows:
            return
        # the mini chunk must be built FIRST: encoding the new rows
        # can append fresh string values to the table dictionaries,
        # and the compiled predicate bakes dictionary lookup tables —
        # compiling before the growth would miss the new codes
        mini = self._delta_chunk(td, rows, rts)
        # compiled per (table, string-dictionary sizes): dictionary
        # growth recompiles — same fingerprint idea as the plan cache
        dictlens = tuple(sorted((cn, len(d)) for cn, d in
                                td.dictionaries.items()))
        key = (table, dictlens)
        fns = getattr(self, "_check_fn_cache", None)
        if fns is None:
            fns = self._check_fn_cache = {}
        compiled = fns.get(key)
        if compiled is None:
            scope, _s = self._dml_scope(table)
            compiled = []
            for ck in checks:
                e = parser.Parser(ck["expr_sql"]).parse_expr()
                b = Binder(scope).bind(e)
                compiled.append((ck, compile_expr(b)))
            # evict stale entries for THIS table (old dictlens), keep
            # other tables' hot entries
            for k in [k for k in fns if k[0] == table]:
                del fns[k]
            fns[key] = compiled
        ctx = ExprContext(
            {f"{table}.{k}": (mini.data[k], mini.valid[k])
             for k in mini.data}, mini.n)
        for ck, f in compiled:
            with self._host_eval():
                d, v = f(ctx)
                # SQL: CHECK fails only on FALSE (NULL passes)
                viol = np.asarray(jnp.logical_and(
                    jnp.logical_not(d), v))
            if viol.any():
                raise EngineError(
                    f"new row violates check constraint "
                    f"{ck['name']!r} ({ck['expr_sql']})")

    def _fk_parent_exists(self, fk: dict, vals: tuple, session,
                          rts: int) -> bool:
        rt = fk["ref_table"]
        rtd = self.store.table(rt)
        pending = (self._txn_key_state(session.effects, rt)
                   if session is not None and session.txn is not None
                   else {})
        sec = self.store.ensure_secondary_index(
            rt, tuple(fk["ref_columns"]))
        for ci, ri in sec.get(vals, []):
            ch = rtd.chunks[ci]
            if not (ch.mvcc_ts[ri] <= rts < ch.mvcc_del[ri]):
                continue
            if pending and self.store.row_key(rtd, ch, ri) in pending:
                continue  # deleted/superseded in this txn
            return True
        for _k, r in pending.items():
            if r is None:
                continue
            if tuple(r.get(c) for c in fk["ref_columns"]) == vals:
                return True
        return False

    def _enforce_fks(self, table: str, rows: list, session,
                     rts: int) -> None:
        """Child-side: every non-NULL FK value in `rows` must have a
        visible parent row."""
        _checks, fks = self._table_constraints(table)
        for fk in fks:
            # self-FKs may be satisfied by rows of this very statement
            self_vals = None
            if fk["ref_table"] == table:
                self_vals = {tuple(r.get(c) for c in fk["ref_columns"])
                             for r in rows}
            for r in rows:
                vals = tuple(r.get(c) for c in fk["columns"])
                if any(v is None for v in vals):
                    continue
                if self_vals is not None and vals in self_vals:
                    continue
                if not self._fk_parent_exists(fk, vals, session, rts):
                    raise EngineError(
                        f"insert on {table!r} violates foreign key "
                        f"{fk['name']!r}: no row in "
                        f"{fk['ref_table']!r} with "
                        f"{fk['ref_columns']} = {vals!r}")

    def _enforce_fk_restrict(self, table: str, removed_rows: list,
                             session, rts: int) -> None:
        """Parent-side RESTRICT: removing/changing a referenced key
        fails while child rows still point at it."""
        for child, fk in self._fk_children_of(table):
            if child not in self.store.tables:
                continue
            ctd = self.store.table(child)
            pending = (self._txn_key_state(session.effects, child)
                       if session is not None
                       and session.txn is not None else {})
            sec = self.store.ensure_secondary_index(
                child, tuple(fk["columns"]))
            for row in removed_rows:
                vals = tuple(row.get(c) for c in fk["ref_columns"])
                if any(v is None for v in vals):
                    continue
                for ci, ri in sec.get(vals, []):
                    ch = ctd.chunks[ci]
                    if not (ch.mvcc_ts[ri] <= rts < ch.mvcc_del[ri]):
                        continue
                    if pending and self.store.row_key(
                            ctd, ch, ri) in pending:
                        continue
                    raise EngineError(
                        f"delete/update on {table!r} violates "
                        f"foreign key {fk['name']!r} on {child!r}: "
                        f"row still references {vals!r}")
                for _k, r in pending.items():
                    if r is not None and tuple(
                            r.get(c) for c in fk["columns"]) == vals:
                        raise EngineError(
                            f"delete/update on {table!r} violates "
                            f"foreign key {fk['name']!r} on "
                            f"{child!r} (pending row)")

    def _maintain_indexes(self, table: str, td, t: Txn, pending: dict,
                          old_row, new_row, rts: int) -> None:
        """Per-row index maintenance inside a DML txn: drop stale
        unique-index KV entries for old_row, uniqueness-check and
        write entries for new_row. NULL in any indexed column exempts
        the row (SQL unique semantics)."""
        idxs = self._table_indexes(table)
        if not idxs:
            return
        tid = td.schema.table_id
        for idx in idxs:
            cols = tuple(idx.columns)
            old_vals = (tuple(old_row.get(cn) for cn in cols)
                        if old_row is not None else None)
            if old_vals is not None and any(v is None for v in old_vals):
                old_vals = None
            new_vals = (tuple(new_row.get(cn) for cn in cols)
                        if new_row is not None else None)
            if new_vals is not None and any(v is None for v in new_vals):
                new_vals = None
            if not idx.unique or old_vals == new_vals:
                continue
            if old_vals is not None:
                t.delete(K.table_key(tid, old_vals, idx.index_id))
            if new_vals is not None:
                self._check_unique(table, td, idx, new_vals, t,
                                   pending, new_row, rts)
                t.put(K.table_key(tid, new_vals, idx.index_id),
                      td.codec.key(new_row))

    def _check_unique(self, table: str, td, idx, vals: tuple, t: Txn,
                      pending: dict, new_row: dict, rts: int) -> None:
        tid = td.schema.table_id
        new_key = td.codec.key(new_row)
        # 1. the KV entry: covers committed rows written through the
        # row plane AND this txn's earlier writes (MVCC reads see own
        # intents); concurrent writers conflict on this same key
        raw = t.get(K.table_key(tid, vals, idx.index_id))
        if raw is not None and raw != new_key:
            raise EngineError(
                f"duplicate key value {vals!r} violates unique "
                f"index {idx.name!r} of {table!r}")
        # 2. the scan plane: covers bulk-ingested rows that never had
        # KV pairs (tpch.load-style ingest); visibility at our read ts
        sec = self.store.ensure_secondary_index(table, tuple(idx.columns))
        for ci, ri in sec.get(vals, []):
            c = td.chunks[ci]
            if not (c.mvcc_ts[ri] <= rts < c.mvcc_del[ri]):
                continue
            rk = self.store.row_key(td, c, ri)
            if rk == new_key or rk in pending:
                continue  # the row being replaced / superseded in-txn
            raise EngineError(
                f"duplicate key value {vals!r} violates unique "
                f"index {idx.name!r} of {table!r}")

    # -- schema changes -------------------------------------------------------
    @property
    def jobs(self):
        """Lazily-built jobs registry for engine-initiated work
        (schema changes); Nodes build their own adopting registry."""
        if getattr(self, "_jobs", None) is None:
            from ..cdc import CHANGEFEED_JOB, ChangefeedResumer
            from ..jobs import Registry
            from ..jobs.schemachange import (INDEX_BACKFILL_JOB,
                                             SCHEMA_CHANGE_JOB,
                                             IndexBackfillResumer,
                                             SchemaChangeResumer)
            self._jobs = Registry(self.kv,
                                  session_id=f"engine-{id(self)}")
            self._jobs.register(SCHEMA_CHANGE_JOB,
                                lambda: SchemaChangeResumer(self))
            self._jobs.register(INDEX_BACKFILL_JOB,
                                lambda: IndexBackfillResumer(self))
            self._jobs.register(CHANGEFEED_JOB,
                                lambda: ChangefeedResumer(self))
            from ..jobs.backup import (BACKUP_JOB, RESTORE_JOB,
                                       BackupResumer, RestoreResumer)
            self._jobs.register(BACKUP_JOB,
                                lambda: BackupResumer(self))
            self._jobs.register(RESTORE_JOB,
                                lambda: RestoreResumer(self))
            from ..jobs.ttl import TTL_JOB, TTLResumer
            self._jobs.register(TTL_JOB, lambda: TTLResumer(self))
        return self._jobs

    @property
    def protectedts(self):
        if getattr(self, "_pts", None) is None:
            from ..kv.protectedts import ProtectedTimestamps
            self._pts = ProtectedTimestamps(self.kv)
        return self._pts

    def zone_config(self, table: str) -> dict:
        """Per-table config overrides (the spanconfig analogue),
        stored at /zone/<table>; empty = cluster defaults apply."""
        import json as _json
        raw = self.kv.txn(
            lambda t: t.get(b"/zone/" + table.encode()))
        return _json.loads(raw.decode()) if raw else {}

    def run_gc(self, table: str) -> int:
        """One MVCC GC pass (mvcc_gc_queue analogue): drop versions
        deleted more than the gc ttl ago (zone override, else the
        cluster setting), clamped below the oldest protected timestamp
        covering the table."""
        zone = self.zone_config(table)
        ttl_s = zone.get("gc.ttl_seconds",
                         self.settings.get("kv.gc.ttl_seconds"))
        ttl_ns = int(ttl_s) * 10 ** 9
        threshold = self.clock.now().wall - ttl_ns
        prot = self.protectedts.min_protected(table)
        if prot is not None:
            threshold = min(threshold, prot - 1)
        if threshold <= 0:
            return 0
        # GC compacts td.chunks (positions shift); statements hold
        # locator (chunk, row) positions across store-lock sections, so
        # GC must serialize with statement execution — the maintenance
        # thread calls this directly (server/node.py)
        with self._stmt_lock:
            n = self.store.gc(table, Timestamp(threshold, 0))
            if n:
                self._evict(table)
        return n

    def run_ttl(self, table: str, ttl_col: str,
                ttl_seconds: int) -> int:
        """One row-TTL pass over `table` (pkg/ttl analogue): deletes
        rows whose ttl_col is older than ttl_seconds; returns the job
        id. Scheduling the pass is the caller's loop."""
        from ..jobs.ttl import TTL_JOB
        jid = self.jobs.create(TTL_JOB, {
            "table": table, "ttl_col": ttl_col,
            "ttl_seconds": ttl_seconds})
        rec = self.jobs.run_job(jid)
        if rec.status != "succeeded":
            raise EngineError(f"TTL job failed: {rec.error}")
        return jid

    def create_changefeed(self, table: str, sink: str,
                          cursor: int = 0,
                          resolved_every_s: float = 0.05) -> int:
        """Start a changefeed job tailing `table` into `sink`
        (mem://name or file://path); returns the job id. Runs on a
        background thread until canceled (jobs.cancel(id))."""
        from ..cdc import CHANGEFEED_JOB
        if table not in self.store.tables:
            raise EngineError(f"table {table!r} does not exist")
        job_id = self.jobs.create(CHANGEFEED_JOB, {
            "table": table, "sink": sink, "cursor": cursor,
            "resolved_every_s": resolved_every_s})
        th = threading.Thread(target=self._run_changefeed,
                              args=(job_id,), daemon=True)
        self._cdc_threads[job_id] = th
        th.start()
        return job_id

    def _run_changefeed(self, job_id: int) -> None:
        from ..jobs import JobsError
        try:
            self.jobs.run_job(job_id)
        except (JobsError, Exception):
            pass  # terminal state is in the job record

    def _exec_alter(self, a: ast.AlterTable, session: Session) -> Result:
        """Online schema change: the descriptor moves through
        WRITE_ONLY -> (backfill job) -> PUBLIC with a lease drain at
        each version bump (catalog/lease.py), like the reference's
        schema changer (pkg/sql/schemachanger via pkg/jobs)."""
        from ..catalog import CatalogError
        from ..catalog.descriptor import WRITE_ONLY, ColumnDescriptor
        from ..jobs.schemachange import SCHEMA_CHANGE_JOB
        if a.table not in self.store.tables:
            raise EngineError(f"table {a.table!r} does not exist")
        desc = self.catalog.get_by_name(a.table)
        if desc is None:
            raise EngineError(
                f"table {a.table!r} has no descriptor (pre-catalog)")
        if a.drop is not None:
            colname = a.drop
            if not any(c.name == colname for c in desc.columns):
                raise EngineError(f"column {colname!r} does not exist")
            if colname in desc.primary_key:
                raise EngineError(
                    f"cannot drop primary key column {colname!r}")
            refs = [i.name for i in desc.indexes
                    if colname in i.columns]
            if refs:
                raise EngineError(
                    f"cannot drop column {colname!r}: referenced by "
                    f"index(es) {sorted(refs)}; drop them first")
            # step 1: hide from readers, publish, drain leases
            desc.column(colname).state = WRITE_ONLY
            self.store.hide_column(a.table, colname)
            desc = self.leases.publish(desc)
            # step 2: physically remove, publish the final version
            desc.columns = [c for c in desc.columns
                            if c.name != colname]
            self.store.drop_column(a.table, colname)
            self.leases.publish(desc)
            for k in [k for k in self._device_tables
                      if k[0] == a.table]:
                self._evict_device(k)
            return Result(tag="ALTER TABLE")

        # ADD COLUMN
        cdef = a.add
        if any(c.name == cdef.name for c in desc.columns):
            raise EngineError(f"column {cdef.name!r} already exists")
        default_phys = None
        if a.default is not None:
            binder = Binder(Scope())
            b = binder.bind(a.default)
            if not isinstance(b, BConst):
                raise EngineError("DEFAULT must be a constant")
            if b.value is not None:
                default_phys = binder.coerce(b, cdef.type).value
        if not cdef.nullable and default_phys is None \
                and self.store.table(a.table).row_count > 0:
            raise EngineError(
                "adding NOT NULL column to non-empty table requires "
                "DEFAULT")
        # step 1: WRITE_ONLY descriptor + hidden physical column —
        # writes carry it, readers don't see it yet
        desc.columns.append(ColumnDescriptor(
            cdef.name, cdef.type, cdef.nullable, WRITE_ONLY,
            default_phys))
        desc = self.leases.publish(desc)
        self.store.add_column(
            a.table, ColumnSchema(cdef.name, cdef.type, cdef.nullable),
            default=default_phys, hidden=True)
        # step 2+3: chunk-checkpointed backfill + PUBLIC publish run as
        # a durable job (resumable after a crash)
        job_id = self.jobs.create(SCHEMA_CHANGE_JOB,
                                  {"table": a.table,
                                   "column": cdef.name})
        rec = self.jobs.run_job(job_id)
        if rec.status != "succeeded":
            raise EngineError(
                f"schema change failed: {rec.error or rec.status}")
        for k in [k for k in self._device_tables if k[0] == a.table]:
            self._evict_device(k)
        return Result(tag="ALTER TABLE")

    # -- DML (through the transactional KV plane) ----------------------------
    # Every DML statement writes row intents through kv.Txn (latches,
    # tscache floors, pushes, read refresh — the TxnCoordSender stack)
    # and records scan-plane effects that are published into the
    # columnstore only at the commit timestamp. Mirrors the reference's
    # write path: sql/row writers -> kv.Txn -> intents, resolved at
    # commit (pkg/kv/db.go:896, pkg/sql/row/writer.go).

    def _dml(self, session: Session, fn) -> Result:
        """Run fn(txn, effects)->Result in the session's open txn, or
        in a fresh auto-commit txn with the kv retry loop."""
        if session.txn is not None:
            # a failed statement aborts the whole explicit txn: its
            # partial intents are resolved away and nothing publishes.
            # This is how statement atomicity holds without kv-level
            # savepoints (pg's "aborted until end of txn block").
            try:
                return fn(session.txn, session.effects)
            except (TxnRetryError, TxnAbortedError) as e:
                session.txn_aborted = True
                session.txn.rollback()
                raise EngineError(f"restart transaction: {e}") from e
            except BaseException:
                session.txn_aborted = True
                session.txn.rollback()
                raise
        last: Exception | None = None
        for _ in range(KVDB.MAX_ATTEMPTS):
            t = Txn(self.kv.store)
            effects: list = []
            try:
                res = fn(t, effects)
                commit_ts = t.commit()
                self._publish(effects, commit_ts)
                return res
            except (TxnRetryError, TxnAbortedError) as e:
                t.rollback()
                last = e
            except BaseException:
                t.rollback()
                raise
        # still the retryable serialization class (pgwire maps the
        # "restart transaction" phrasing to SQLSTATE 40001)
        raise EngineError(f"restart transaction: DML exhausted "
                          f"retries: {last}")

    def _publish(self, effects: list, ts: Timestamp) -> None:
        if not effects:
            return
        by_table: dict[str, list] = {}
        order: list[str] = []
        for table, op in effects:
            if table not in by_table:
                by_table[table] = []
                order.append(table)
            by_table[table].append(op)
        for table in order:
            self.store.apply_committed(table, by_table[table], ts)
            self._evict(table)
            for feed in self.cdc_feeds:
                if feed.table == table:
                    feed.on_publish(by_table[table], ts)

    def _register_table_read(self, txn: Optional[Txn], table: str,
                             read_ts: Timestamp) -> None:
        """Record a scan-plane read in the KV concurrency plane: the
        table span goes into the txn's refresh set and the timestamp
        cache, so conflicting writers get pushed above our read — the
        contract of Replica.Send read path + span refresher."""
        codec = self.store.table(table).codec
        start, end = codec.span()
        span = Span(start, end)
        self.kv.store.tscache.add(span, read_ts,
                                  txn.meta.id if txn else None)
        if txn is not None:
            txn.read_spans.append(span)

    def _txn_key_state(self, effects: list, table: str) -> dict:
        """Net per-key state of buffered effects for one table:
        key -> row dict (pending put) or None (pending delete)."""
        state: dict[bytes, object] = {}
        for tb, op in effects:
            if tb != table:
                continue
            if op[0] == "put":
                state[op[1]] = op[2]
            else:
                state[op[1]] = None
        return state

    def _overlay_chunks(self, table: str, effects: list,
                        read_ts: Timestamp) -> list[Chunk]:
        """Committed chunks with this txn's buffered effects applied:
        pending deletes/overwrites tombstone the committed version
        (copy-on-write of the deletion column), pending puts appear as
        a delta chunk visible at the txn's read timestamp. This is the
        read-your-own-writes overlay; the reference gets the same from
        MVCC intents being visible to their own txn."""
        td = self.store.table(table)
        state = self._txn_key_state(effects, table)
        if not state:
            self.store.seal(table)
            return list(td.chunks)
        idx = self.store.ensure_pk_index(table)
        rts = read_ts.to_int()
        shadow: dict[int, np.ndarray] = {}   # chunk idx -> COW mvcc_del

        def _tombstone(ci: int, ri: int):
            if ci not in shadow:
                shadow[ci] = td.chunks[ci].mvcc_del.copy()
            shadow[ci][ri] = rts   # hidden from this txn's reads
        for key in state:
            pos = idx.get(key)
            if pos is None:
                continue
            ci, ri = pos
            if td.chunks[ci].mvcc_ts[ri] > rts:
                # live version is newer than our snapshot (a concurrent
                # txn superseded the key after our read_ts): it is
                # already invisible at rts; the version we must hide is
                # found by the superseded-after-rts sweep below
                continue
            _tombstone(ci, ri)
        # Versions visible at rts but superseded/deleted after it are
        # NOT in the live pk index, yet they are exactly what a pending
        # write must shadow (otherwise the old version + our delta row
        # would both surface). They satisfy rts < mvcc_del < MAX — a
        # small candidate set (recent MVCC garbage) we key-match.
        for ci, c in enumerate(td.chunks):
            cand = np.nonzero((c.mvcc_ts <= rts) & (rts < c.mvcc_del)
                              & (c.mvcc_del != MAX_TS_INT))[0]
            for ri in cand:
                if self.store.row_key(td, c, int(ri)) in state:
                    _tombstone(ci, int(ri))
        chunks = []
        for ci, c in enumerate(td.chunks):
            if ci in shadow:
                c = Chunk(data=c.data, valid=c.valid, mvcc_ts=c.mvcc_ts,
                          mvcc_del=shadow[ci], n=c.n, rowid=c.rowid)
            chunks.append(c)
        pending_rows = [r for r in state.values() if r is not None]
        if pending_rows:
            chunks.append(self._delta_chunk(td, pending_rows, rts))
        return chunks

    def _delta_chunk(self, td, rows: list[dict], ts_int: int) -> Chunk:
        n = len(rows)
        data, vmap = {}, {}
        for col in td.schema.columns:
            vals = [r.get(col.name) for r in rows]
            v = np.array([x is not None for x in vals], dtype=bool)
            if col.type.family == Family.STRING:
                d = td.dictionaries[col.name]
                arr = np.fromiter(
                    (d.encode(x) if x is not None else 0 for x in vals),
                    dtype=np.int32, count=n)
            else:
                arr = np.array([x if x is not None else 0 for x in vals],
                               dtype=col.type.np_dtype)
            data[col.name] = arr
            vmap[col.name] = v
        return Chunk(
            data=data, valid=vmap,
            mvcc_ts=np.full(n, ts_int, dtype=np.int64),
            mvcc_del=np.full(n, MAX_TS_INT, dtype=np.int64), n=n,
            rowid=np.asarray([int(r.get(ROWID, 0)) for r in rows],
                             dtype=np.int64))

    def _exec_insert(self, ins: ast.Insert, session: Session) -> Result:
        td = self.store.table(ins.table)
        schema = td.schema
        if ins.select is not None:
            for vol in ("nextval", "gen_random_uuid"):
                if _contains_func(ins.select, vol):
                    # the select binds the volatile fn ONCE, handing
                    # every produced row the same value (pg evaluates
                    # per row); reject instead of silently corrupting
                    # keys/uuids
                    raise EngineError(
                        f"{vol} inside INSERT ... SELECT is not "
                        "supported; insert explicit VALUES instead")
            # cache key must identify the inner select (repr is stable
            # and content-based for the AST dataclasses)
            src = self._exec_select(ins.select, session,
                                    sql_text="insert-select:" + repr(ins.select))
            cols = ins.columns or schema.column_names
            rows = [dict(zip(cols, r)) for r in src.rows]
            rows = [self._encode_row(schema, r) for r in rows]
        else:
            cols = ins.columns or schema.column_names
            binder = Binder(Scope(),
                            sequence_ops=self._sequence_ops(session))
            rows = []
            for row_exprs in ins.rows:
                if len(row_exprs) != len(cols):
                    raise EngineError("INSERT value count mismatch")
                row = {}
                for cname, e in zip(cols, row_exprs):
                    col = schema.column(cname)
                    b = binder.bind(e)
                    if not isinstance(b, BConst):
                        raise EngineError("INSERT values must be constants")
                    if b.value is None:
                        if not col.nullable:
                            raise EngineError(
                                f"null in non-null column {cname}")
                        row[cname] = None
                    else:
                        row[cname] = binder._const_to(b, col.type).value
                rows.append(row)
        for row in rows:
            for col in schema.columns:
                if not col.nullable and row.get(col.name) is None:
                    raise EngineError(f"null in non-null column {col.name}")
        codec = td.codec

        def fn(t: Txn, effects: list) -> Result:
            pending = self._txn_key_state(effects, ins.table)
            idx = self.store.ensure_pk_index(ins.table)
            rts = t.meta.read_ts.to_int()
            self._enforce_checks(ins.table, td, rows, rts)
            self._enforce_fks(ins.table, rows, session, rts)
            new_rows = []
            for row in rows:
                r = dict(row)
                if codec.synthetic_pk:
                    r[ROWID] = self.store.alloc_rowids(ins.table, 1)[0]
                key = codec.key(r)
                old_row = None
                if not codec.synthetic_pk and not ins.upsert:
                    # duplicate-key check = CPut semantics: a KV read
                    # (sees concurrent intents, registers the span)
                    # plus the scan-plane live index (covers
                    # bulk-ingested rows with no KV pair)
                    in_txn = pending.get(key, "absent")
                    committed = (t.get(key) is not None or key in idx)
                    if in_txn not in (None, "absent") or \
                            (committed and in_txn == "absent"):
                        pk = codec.pk_values(r)
                        raise EngineError(
                            f"duplicate key value {pk!r} violates "
                            f"primary key of {ins.table!r}")
                elif ins.upsert:
                    # the row being replaced (if any), for secondary-
                    # index entry cleanup and FK RESTRICT
                    in_txn = pending.get(key, "absent")
                    if in_txn not in (None, "absent"):
                        old_row = in_txn
                    elif key in idx:
                        ci, ri = idx[key]
                        old_row = self.store.extract_row(
                            td, td.chunks[ci], ri)
                    if old_row is not None:
                        ref_cols = set()
                        for _ch, fk in self._fk_children_of(
                                ins.table):
                            ref_cols |= set(fk["ref_columns"])
                        if ref_cols and any(
                                old_row.get(cn) != r.get(cn)
                                for cn in ref_cols):
                            self._enforce_fk_restrict(
                                ins.table, [old_row], session, rts)
                self._maintain_indexes(ins.table, td, t, pending,
                                       old_row, r, rts)
                t.put(key, codec.encode_value(r))
                pending[key] = r
                new_rows.append((key, r))
            for key, r in new_rows:
                effects.append((ins.table, ("put", key, r)))
            return Result(row_count=len(rows),
                          tag="UPSERT" if ins.upsert else "INSERT")

        return self._dml(session, fn)

    def _encode_row(self, schema: TableSchema, row: dict) -> dict:
        out = {}
        for cname, v in row.items():
            col = schema.column(cname)
            if v is None:
                out[cname] = None
            elif col.type.family == Family.DECIMAL:
                out[cname] = int(round(float(v) * 10 ** col.type.scale))
            elif col.type.family == Family.DATE:
                out[cname] = ((v - EPOCH_DATE).days
                              if isinstance(v, datetime.date) else int(v))
            elif col.type.family == Family.TIMESTAMP:
                out[cname] = (int((v - EPOCH_DT).total_seconds() * 1e6)
                              if isinstance(v, datetime.datetime) else int(v))
            else:
                out[cname] = v
        return out

    def _dml_scope(self, table: str) -> tuple[Scope, TableSchema]:
        td = self.store.table(table)
        scope = Scope()
        cols = {}
        for c in td.schema.columns:
            cols[c.name] = ColumnBinding(
                f"{table}.{c.name}", c.type, td.dictionaries.get(c.name))
        scope.add_table(table, cols)
        return scope, td.schema

    def _host_eval(self):
        """Eager host-side expression evaluation context: pin to the
        CPU backend so point-op predicates/assignments never pay a
        device round trip (on a tunnel-attached TPU one eager sync
        costs ~50-150ms — it would dominate every OLTP statement)."""
        return jax.default_device(jax.devices("cpu")[0])

    def _chunk_pred(self, table: str, where, scope: Scope,
                    session: Session | None = None):
        if where is None:
            return lambda chunk: np.ones(chunk.n, dtype=bool)
        session = session or self.session()
        binder = Binder(
            scope,
            subquery_eval=lambda s, lim: self._eval_subquery(
                s, session, lim),
            now_micros=self._read_ts(session).wall // 1000,
            sequence_ops=self._sequence_ops(session))
        pred = binder.bind(where)
        predf = compile_expr(pred)

        def f(chunk):
            with self._host_eval():
                ctx = ExprContext(
                    {f"{table}.{k}": (chunk.data[k], chunk.valid[k])
                     for k in chunk.data}, chunk.n)
                d, v = predf(ctx)
                return np.asarray(jnp.logical_and(d, v))
        return f

    def _exec_delete(self, d: ast.Delete, session: Session) -> Result:
        scope, _ = self._dml_scope(d.table)
        td = self.store.table(d.table)
        codec = td.codec
        predf = self._chunk_pred(d.table, d.where, scope, session)

        def fn(t: Txn, effects: list) -> Result:
            read_ts = t.meta.read_ts
            self._register_table_read(t, d.table, read_ts)
            rts = read_ts.to_int()
            n = 0
            pending = self._txn_key_state(effects, d.table)
            cand = self._dml_index_candidates(d.table, d.where, session)
            n_committed = len(td.chunks)
            victims: list[tuple[bytes, dict]] = []
            for ci, chunk in enumerate(
                    self._overlay_chunks(d.table, effects, read_ts)):
                if cand is not None and ci < n_committed \
                        and ci not in cand:
                    continue
                mask = chunk.live_mask(rts) & predf(chunk)
                for ri in np.nonzero(mask)[0]:
                    row = self.store.extract_row(td, chunk, int(ri))
                    victims.append((codec.key(row), row))
            # one batched RESTRICT probe for the whole statement (the
            # txn aborts wholly on violation, so ordering vs the
            # deletes below is immaterial)
            self._enforce_fk_restrict(d.table,
                                      [r for _k, r in victims],
                                      session, rts)
            for key, row in victims:
                self._maintain_indexes(d.table, td, t, pending,
                                       row, None, rts)
                t.delete(key)
                effects.append((d.table, ("del", key)))
                n += 1
            return Result(row_count=n, tag="DELETE")

        return self._dml(session, fn)

    def _exec_update(self, u: ast.Update, session: Session) -> Result:
        scope, schema = self._dml_scope(u.table)
        td = self.store.table(u.table)
        binder = Binder(scope,
                        sequence_ops=self._sequence_ops(session))
        assigned = {}
        for cname, e in u.assignments:
            col = schema.column(cname)
            # nextval is volatile and must allocate PER ROW (pg
            # semantics): a bare nextval('s') assignment allocates in
            # the row loop below; nextval nested inside a larger
            # expression would fold to one shared value — reject it
            if isinstance(e, ast.FuncCall) and e.name == "nextval" \
                    and len(e.args) == 1 \
                    and isinstance(e.args[0], ast.Literal):
                self._seq_desc(e.args[0].value)  # must exist
                assigned[cname] = ("seq", e.args[0].value)
                continue
            if _contains_func(e, "nextval"):
                raise EngineError(
                    "nextval may only be the entire SET expression "
                    "(per-row allocation); fold it into a bare "
                    "nextval('seq') assignment")
            if _contains_func(e, "gen_random_uuid"):
                raise EngineError(
                    "gen_random_uuid in UPDATE SET would give every "
                    "row the same uuid (bound once per statement); "
                    "not supported")
            b = binder.bind(e)
            if isinstance(b, BConst) and isinstance(b.value, str) \
                    and col.type.family == Family.STRING:
                code = td.dictionaries[cname].encode(b.value)
                assigned[cname] = ("const", code)
            elif isinstance(b, BConst):
                phys = binder._const_to(b, col.type).value if b.value is not None else None
                assigned[cname] = ("const", phys)
            else:
                b2 = binder.coerce(b, col.type) if b.type.family != col.type.family else b
                assigned[cname] = ("expr", compile_expr(b2))

        def assign(chunk, mask, _he=self._host_eval):
            idx = np.nonzero(mask)[0]
            data, valid = {}, {}
            ctx = ExprContext(
                {f"{u.table}.{k}": (chunk.data[k], chunk.valid[k])
                 for k in chunk.data}, chunk.n)
            for c in schema.columns:
                cn = c.name
                if cn in assigned:
                    kind, v = assigned[cn]
                    if kind == "seq":
                        # placeholder; allocated per row in the todo
                        # loop (volatile, must not fold per chunk)
                        data[cn] = np.zeros(len(idx),
                                            dtype=c.type.np_dtype)
                        valid[cn] = np.ones(len(idx), dtype=bool)
                    elif kind == "const":
                        if v is None:
                            data[cn] = np.zeros(len(idx), dtype=c.type.np_dtype)
                            valid[cn] = np.zeros(len(idx), dtype=bool)
                        else:
                            data[cn] = np.full(len(idx), v,
                                               dtype=c.type.np_dtype)
                            valid[cn] = np.ones(len(idx), dtype=bool)
                    else:
                        with _he():
                            dd, vv = v(ctx)
                            dd, vv = np.asarray(dd), np.asarray(vv)
                        data[cn] = dd[idx].astype(c.type.np_dtype)
                        valid[cn] = vv[idx]
                else:
                    data[cn] = chunk.data[cn][idx]
                    valid[cn] = chunk.valid[cn][idx]
            return data, valid

        codec = td.codec
        predf = self._chunk_pred(u.table, u.where, scope, session)

        def fn(t: Txn, effects: list) -> Result:
            read_ts = t.meta.read_ts
            self._register_table_read(t, u.table, read_ts)
            rts = read_ts.to_int()
            idx = self.store.ensure_pk_index(u.table)
            n = 0
            todo = []
            cand = self._dml_index_candidates(u.table, u.where, session)
            n_committed = len(td.chunks)
            for ci, chunk in enumerate(
                    self._overlay_chunks(u.table, effects, read_ts)):
                if cand is not None and ci < n_committed \
                        and ci not in cand:
                    continue
                mask = chunk.live_mask(rts) & predf(chunk)
                if not mask.any():
                    continue
                data, valid = assign(chunk, mask)
                for j, ri in enumerate(np.nonzero(mask)[0]):
                    old = self.store.extract_row(td, chunk, int(ri))
                    new = dict(old)
                    for c in schema.columns:
                        cn = c.name
                        if not valid[cn][j]:
                            new[cn] = None
                        elif c.type.family == Family.STRING:
                            new[cn] = td.dictionaries[cn].values[
                                int(data[cn][j])]
                        else:
                            new[cn] = data[cn][j].item()
                    for cn, kv in assigned.items():
                        if kv[0] == "seq":
                            new[cn] = self._sequence_op(
                                session, "nextval", kv[1], None)
                    todo.append((old, new))
            pending = self._txn_key_state(effects, u.table)
            self._enforce_checks(u.table, td,
                                 [new for _o, new in todo], rts)
            self._enforce_fks(u.table, [new for _o, new in todo],
                              session, rts)
            ref_cols_changed = set()
            for child, fk in self._fk_children_of(u.table):
                ref_cols_changed |= set(fk["ref_columns"])
            for old, new in todo:
                if ref_cols_changed and any(
                        old.get(c) != new.get(c)
                        for c in ref_cols_changed):
                    self._enforce_fk_restrict(u.table, [old],
                                              session, rts)
            for old, new in todo:
                okey = codec.key(old)
                nkey = codec.key(new)
                if nkey != okey:
                    # pk change: delete old kv, insert new (dup-checked)
                    in_txn = pending.get(nkey, "absent")
                    committed = (t.get(nkey) is not None or nkey in idx)
                    if in_txn not in (None, "absent") or \
                            (committed and in_txn == "absent"):
                        raise EngineError(
                            f"duplicate key {codec.pk_values(new)!r} on "
                            f"UPDATE of {u.table!r}")
                    t.delete(okey)
                    effects.append((u.table, ("del", okey)))
                    pending[okey] = None
                self._maintain_indexes(u.table, td, t, pending,
                                       old, new, rts)
                t.put(nkey, codec.encode_value(new))
                effects.append((u.table, ("put", nkey, new)))
                pending[nkey] = new
                n += 1
            return Result(row_count=n, tag="UPDATE")

        return self._dml(session, fn)

    def _evict(self, name: str):
        for k in [k for k in self._device_tables if k[0] == name]:
            self._evict_device(k)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

@dataclass
class _StreamFns:
    """The three jitted pieces of a paged plan (compile_streaming)."""
    page: object
    combine: object
    final: object


def _host_sort(rows: list, meta: P.OutputMeta, keys) -> list:
    """Host-side ORDER BY over decoded result rows (spill path only).
    Matches device semantics: ascending puts NULLs last, descending
    puts NULLs first; strings compare lexicographically."""
    out = list(rows)
    for name, desc in reversed(list(keys)):
        try:
            i = meta.names.index(name)
        except ValueError:
            raise EngineError(
                f"cannot host-sort spilled result by {name!r}") from None
        out = sorted(out,
                     key=lambda r, i=i: (r[i] is None,
                                         0 if r[i] is None else r[i]),
                     reverse=desc)
    return out


def _count_aggs(node: P.PlanNode) -> int:
    """Aggregate-function count of the plan's root aggregate (for the
    streaming working-set estimate)."""
    n = node
    if isinstance(n, P.Limit):
        n = n.child
    if isinstance(n, P.Sort):
        n = n.child
    if isinstance(n, P.Aggregate):
        return max(len(n.aggs), 1)
    return 1


def _collect_scan_columns(node: P.PlanNode) -> dict[str, frozenset]:
    """alias -> stored columns the plan's scans actually read (the
    pruned upload set; cf. the reference's neededColumns in
    colfetcher/cfetcher.go)."""
    out: dict[str, set] = {}
    if isinstance(node, P.Scan):
        out.setdefault(node.alias, set()).update(node.columns.values())
    for attr in ("child", "left", "right"):
        c = getattr(node, attr, None)
        if c is not None:
            for a, s in _collect_scan_columns(c).items():
                out.setdefault(a, set()).update(s)
    return {a: frozenset(s) for a, s in out.items()}


def _slice_chunks(chunks: list, getter, start: int, end: int) -> np.ndarray:
    """Materialize rows [start, end) of a chunked column as one array."""
    parts = []
    off = 0
    for c in chunks:
        lo, hi = max(start - off, 0), min(end - off, c.n)
        if lo < hi:
            parts.append(getter(c)[lo:hi])
        off += c.n
        if off >= end:
            break
    if len(parts) == 1:
        return parts[0]
    return np.concatenate(parts) if parts else np.zeros(0)


def _collect_scans(node: P.PlanNode) -> dict[str, str]:
    out = {}
    if isinstance(node, P.Scan):
        out[node.alias] = node.table
    for attr in ("child", "left", "right"):
        c = getattr(node, attr, None)
        if c is not None:
            out.update(_collect_scans(c))
    return out


def _next_pow2(x: int) -> int:
    n = 1
    while n < x:
        n <<= 1
    return n


def _pad(a: np.ndarray, n: int, fill=0) -> np.ndarray:
    if a.shape[0] == n:
        return a
    out = np.full(n, fill, dtype=a.dtype)
    out[: a.shape[0]] = a
    return out


@dataclass
class _RerunPrepared:
    """Prepared handle for statements that cannot pin one compiled
    program (CTEs materialize fresh temps per run; set ops merge on
    the host): each run() re-executes through the engine."""
    engine: "Engine"
    session: "Session"
    stmt: object
    sql_text: str

    def run(self, read_ts=None) -> "Result":
        return self.engine._exec_select(self.stmt, self.session,
                                        self.sql_text)

    def dispatch(self, *a, **kw):
        raise EngineError(
            "this statement shape cannot dispatch asynchronously")


def _render_create(desc) -> str:
    """Reconstruct CREATE TABLE DDL from a descriptor (SHOW CREATE)."""
    def ty(t):
        f = t.family.value
        names = {"int": "INT8", "float": "FLOAT8", "bool": "BOOL",
                 "string": "STRING", "date": "DATE",
                 "timestamp": "TIMESTAMP", "interval": "INTERVAL"}
        if f == "decimal":
            return f"DECIMAL({t.precision},{t.scale})"
        return names.get(f, f.upper())

    parts = []
    for c in desc.columns:
        if c.state != "public":
            continue
        s = f"{c.name} {ty(c.type)}"
        if not c.nullable:
            s += " NOT NULL"
        parts.append(s)
    if desc.primary_key:
        parts.append(f"PRIMARY KEY ({', '.join(desc.primary_key)})")
    for i in desc.indexes:
        if i.state != "public":
            continue
        kw = "UNIQUE INDEX" if i.unique else "INDEX"
        parts.append(f"{kw} {i.name} ({', '.join(i.columns)})")
    for ck in desc.checks:
        parts.append(f"CONSTRAINT {ck['name']} CHECK "
                     f"({ck['expr_sql']})")
    for fk in desc.fks:
        parts.append(
            f"CONSTRAINT {fk['name']} FOREIGN KEY "
            f"({', '.join(fk['columns'])}) REFERENCES "
            f"{fk['ref_table']} ({', '.join(fk['ref_columns'])})")
    cols = ",\n  ".join(parts)
    return f"CREATE TABLE {desc.name} (\n  {cols}\n)"


def _rewrite_table_names(sel, mapping: dict):
    """Deep-copy a Select/SetOp with CTE names replaced by their
    materialized temp-table names — in FROM/JOIN refs and inside
    expression subqueries (which execute while the temps are live)."""
    import copy
    if not mapping:
        return sel
    if isinstance(sel, ast.SetOp):
        sel = copy.copy(sel)
        shadowed = {name for name, _, _ in sel.ctes}
        inner = {k: v for k, v in mapping.items() if k not in shadowed}
        sel.left = _rewrite_table_names(sel.left, inner)
        sel.right = _rewrite_table_names(sel.right, inner)
        return sel
    sel = copy.deepcopy(sel)

    def fix_ref(ref: ast.TableRef):
        if ref is None or ref.subquery is not None:
            if ref is not None and ref.subquery is not None:
                fix_select(ref.subquery)
            return
        if ref.name in mapping:
            ref.alias = ref.alias or ref.name
            ref.name = mapping[ref.name]

    def fix_expr(e):
        if e is None:
            return
        if isinstance(e, (ast.Subquery, ast.Exists)):
            fix_select(e.select)
            return
        if isinstance(e, ast.InSubquery):
            fix_expr(e.expr)
            fix_select(e.select)
            return
        for attr in ("left", "right", "operand", "expr", "lo", "hi",
                     "start", "length", "else_"):
            fix_expr(getattr(e, attr, None))
        for a in getattr(e, "args", None) or []:
            fix_expr(a)
        for a in getattr(e, "items", None) or []:
            fix_expr(a)
        for c, v in getattr(e, "whens", None) or []:
            fix_expr(c)
            fix_expr(v)

    def fix_select(s):
        if isinstance(s, ast.SetOp):
            fix_select(s.left)
            fix_select(s.right)
            return
        # a CTE of the same name in an inner scope shadows the outer
        shadowed = {name for name, _, _ in s.ctes}
        inner = {k: v for k, v in mapping.items() if k not in shadowed}
        if s is not sel and inner != mapping:
            rewritten = _rewrite_table_names(s, inner)
            s.__dict__.update(rewritten.__dict__)
            return
        fix_ref(s.table)
        for j in s.joins:
            fix_ref(j.table)
            fix_expr(j.on)
        fix_expr(s.where)
        fix_expr(s.having)
        for it in s.items:
            fix_expr(it.expr)
        for g in s.group_by:
            fix_expr(g)
        for ob in s.order_by:
            fix_expr(ob.expr)
        for _, _, sub in s.ctes:
            fix_select(sub)

    fix_select(sel)
    return sel


def _propagate_as_of(inner, outer):
    """AS OF SYSTEM TIME covers the whole statement: sub-selects
    (expression subqueries, CTEs, derived tables) inherit the outer
    clause unless they carry their own."""
    if not isinstance(inner, ast.Select) \
            or not isinstance(outer, ast.Select):
        return inner
    if outer.as_of is None or inner.as_of is not None:
        return inner
    import copy
    inner = copy.copy(inner)
    inner.as_of = outer.as_of
    return inner


def _contains_func(node, fname: str) -> bool:
    """Does any expression under `node` call function `fname`?
    Generic dataclass walk (volatile-function detection)."""
    import dataclasses
    found = [False]

    def walk(x):
        if found[0]:
            return
        if isinstance(x, (list, tuple)):
            for v in x:
                walk(v)
            return
        if not dataclasses.is_dataclass(x) or isinstance(x, type):
            return
        if isinstance(x, ast.FuncCall) and x.name == fname:
            found[0] = True
            return
        for f in dataclasses.fields(x):
            walk(getattr(x, f.name))

    walk(node)
    return found[0]


def _stmt_table_refs(node) -> set:
    """All table names a statement references (FROM/JOIN refs plus
    expression subqueries and CTE bodies), via a generic dataclass
    walk — used for view dependency checks at DROP TABLE."""
    import dataclasses
    out: set = set()
    seen: set = set()

    def walk(x):
        if id(x) in seen:
            return
        if isinstance(x, (list, tuple)):
            for v in x:
                walk(v)
            return
        if not dataclasses.is_dataclass(x) or isinstance(x, type):
            return
        seen.add(id(x))
        if isinstance(x, ast.TableRef) and x.subquery is None:
            out.add(x.name)
        for f in dataclasses.fields(x):
            walk(getattr(x, f.name))

    walk(node)
    return out


def split_conjuncts_ast(e: ast.Expr) -> list:
    """Flatten a WHERE tree into its AND-conjuncts (AST level; the
    planner's split_conjuncts does the same over bound exprs)."""
    out: list = []

    def walk(x):
        if isinstance(x, ast.BinOp) and x.op == "and":
            walk(x.left)
            walk(x.right)
        else:
            out.append(x)

    walk(e)
    return out


def _decode_storage_value(v, ty):
    """Storage-logical value (extract_row form: strings pre-decoded,
    numerics physical) -> client value. Delegates to _decode_scalar so
    the fastpath and the compiled path share one decoding."""
    if v is None:
        return None
    if isinstance(v, str):
        return v
    return _decode_scalar(v, True, ty, None)


def _decode_scalar(v, valid: bool, ty, dictionary):
    if not valid:
        return None
    f = ty.family
    if f == Family.DECIMAL:
        return float(v) / 10 ** ty.scale
    if f == Family.DATE:
        return EPOCH_DATE + datetime.timedelta(days=int(v))
    if f == Family.TIMESTAMP:
        return EPOCH_DT + datetime.timedelta(microseconds=int(v))
    if f == Family.STRING:
        if dictionary is not None:
            return dictionary.values[int(v)]
        return int(v)
    if f == Family.BOOL:
        return bool(v)
    if f == Family.INT:
        return int(v)
    if f == Family.FLOAT:
        return float(v)
    if isinstance(v, str):
        return v
    return v.item() if hasattr(v, "item") else v


def _decode_column(arr: np.ma.MaskedArray, ty, dictionary) -> list:
    data = np.asarray(arr.data)
    mask = np.asarray(arr.mask) if arr.mask is not np.ma.nomask \
        else np.zeros(len(data), bool)
    return [_decode_scalar(d, not m, ty, dictionary)
            for d, m in zip(data, mask)]
