"""Cold-start elimination: persistent compile cache + shape bucketing.

A restarted node used to recompile every plan from scratch: the
executable cache (`Engine._exec_cache`) is in-process, and XLA keeps
its compiled programs in memory only. This module wires three pieces
of cross-process warm-start state (ROADMAP item 5, the Tailwind-style
accelerator-management frame in PAPERS.md):

1. **Persistent XLA compile cache** — `init_compile_cache` points
   `jax.experimental.compilation_cache` at an on-disk directory
   (cluster setting `sql.exec.compile_cache.dir`), under a
   per-backend / per-jax-version / per-schema subdirectory so stale
   artifacts from another backend or an upgraded toolchain can never
   be loaded — the invalidation story is "a new subdir", never a
   cache flush. Hit/miss/compile-seconds counters come from JAX's
   monitoring events and surface as `exec.compile.*` metrics.

2. **Shape bucket ladder** — `ShapeLadder` generalizes the historical
   "pad row counts to the next power of two" rule into an explicit
   closed bucket set shared by resident uploads, streamed pages and
   spill partitions. `steps_per_octave = 1` IS the historical pow2
   ladder (bit-identical bucket choices); larger values insert
   evenly-spaced intermediate buckets per octave, trading a bounded
   number of extra executables for less padding waste. Every bucket
   stays a multiple of 128 so Pallas kernel eligibility
   (`n % 128 == 0`) is ladder-invariant.

3. **Shapes journal** — statements that miss the executable cache
   append their text to a journal next to the compile cache;
   `Engine.prewarm` replays the top-K texts from the previous run so
   a restarted node compiles (from the persistent cache: deserializes)
   its hot executables before the first query arrives.

Per-statement attribution: XLA backend compilation runs synchronously
on the thread that traced the jitted call, so a thread-local tally of
`/jax/core/compile/backend_compile_duration` events gives each
statement its own compile-seconds split (`thread_compile_seconds`
deltas around dispatch), surfaced in `/_status/statements` and as a
`compile_s` trace tag.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass

# Bump when the on-disk layout (cache subdir contract, journal or
# autotune-table format) changes incompatibly: old state is simply
# never looked at again.
SCHEMA_VERSION = 1

_JOURNAL_NAME = "shapes_journal.jsonl"
_JOURNAL_MAX_BYTES = 8 << 20  # stop appending past this; bounded state

_LOCK = threading.Lock()
_ACTIVE_DIR: str | None = None
_LISTENERS = False

# process-wide tallies, bumped by the JAX monitoring listeners
_HITS = 0
_MISSES = 0
_SECONDS = 0.0
PREWARMED = 0  # statements re-prepared by Engine.prewarm

_TLS = threading.local()


def note_prewarmed() -> None:
    """Locked bump of the prewarm tally: engines prewarm on their own
    threads (tests run several engines in-process), and an unlocked
    cross-module ``PREWARMED += 1`` loses increments."""
    global PREWARMED
    with _LOCK:
        PREWARMED += 1


def cache_hits() -> int:
    return _HITS


def cache_misses() -> int:
    return _MISSES


def compile_seconds() -> float:
    return _SECONDS


def _cell() -> list:
    c = getattr(_TLS, "cell", None)
    if c is None:
        c = _TLS.cell = [0.0]
    return c


def thread_compile_seconds() -> float:
    """Cumulative XLA backend-compile seconds billed to THIS thread.
    Statement dispatch takes a delta around execution: compilation
    happens synchronously on the tracing thread — and when a plan is
    traced on a mesh-dispatcher thread instead, the dispatcher adopts
    the submitting thread's attribution cell (attribution_cell /
    set_attribution_cell), so the delta is still the statement's own
    compile bill."""
    return _cell()[0]


def attribution_cell() -> list:
    """The mutable cell compile seconds are billed to on this thread.
    Cross-thread executors (parallel/distagg._MeshDispatcher) capture
    it at submit time and adopt it on the worker around the call."""
    return _cell()


def set_attribution_cell(cell):
    """Point this thread's compile billing at `cell`; returns the
    previously active cell so callers can restore it."""
    prev = _cell()
    _TLS.cell = cell if cell is not None else [0.0]
    return prev


def _on_event(event: str, **kw) -> None:
    global _HITS, _MISSES
    if event == "/jax/compilation_cache/cache_hits":
        with _LOCK:
            _HITS += 1
    elif event == "/jax/compilation_cache/cache_misses":
        with _LOCK:
            _MISSES += 1


def _on_duration(event: str, duration: float, **kw) -> None:
    global _SECONDS
    if event == "/jax/core/compile/backend_compile_duration":
        with _LOCK:
            _SECONDS += duration
        _cell()[0] += duration


def _install_listeners() -> None:
    global _LISTENERS
    with _LOCK:
        if _LISTENERS:
            return
        _LISTENERS = True
    try:
        from jax._src import monitoring
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        # older/newer jax without the monitoring module: the cache
        # still works, only the counters stay at zero
        pass


def default_cache_root() -> str:
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "cockroach_tpu")


def resolve_cache_root(settings=None) -> str | None:
    """Setting > environment > user default; "off" disables."""
    configured = ""
    if settings is not None:
        try:
            configured = str(settings.get("sql.exec.compile_cache.dir"))
        except Exception:
            configured = ""
    if configured.lower() in ("off", "none", "disabled"):
        return None
    if configured:
        return configured
    env = os.environ.get("COCKROACH_TPU_COMPILE_CACHE_DIR", "")
    if env.lower() in ("off", "none", "disabled"):
        return None
    return env or default_cache_root()


def cache_dir(root: str) -> str:
    """Per-backend / per-jax-version / per-schema subdirectory: XLA
    serialized executables are not portable across backends or
    compiler versions, so stale artifacts are isolated by path instead
    of trusted-then-validated."""
    import jax
    backend = jax.default_backend()
    return os.path.join(root, f"{backend}-jax{jax.__version__}"
                              f"-v{SCHEMA_VERSION}")


def init_compile_cache(settings=None) -> str | None:
    """Point the JAX persistent compilation cache at the configured
    directory (idempotent; re-targets on a changed setting). Returns
    the active per-backend cache dir, or None when disabled/broken —
    the engine runs fine either way, just cold."""
    global _ACTIVE_DIR
    root = resolve_cache_root(settings)
    if root is None:
        return None
    try:
        import jax
        d = cache_dir(root)
        with _LOCK:
            changed = d != _ACTIVE_DIR
        if changed:
            os.makedirs(d, exist_ok=True)
            # every trace is worth persisting for an interactive
            # engine: the default 1s/min-size gates exist for training
            # jobs whose tiny programs aren't worth the disk
            jax.config.update("jax_compilation_cache_dir", d)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
            from jax.experimental import compilation_cache as cc
            # drop the in-memory handle to any previously-targeted
            # dir so the new path takes effect immediately
            cc.compilation_cache.reset_cache()
            with _LOCK:
                _ACTIVE_DIR = d
        _install_listeners()
        with _LOCK:
            return _ACTIVE_DIR
    except Exception:
        return None


def register_metrics(metrics) -> None:
    """exec.compile.* counters (idempotent per registry: func_counter
    re-registration under the same name returns the existing one)."""
    metrics.func_counter(
        "exec.compile.cache_hit", cache_hits,
        "XLA executables served from the persistent compile cache "
        "(process-wide; >0 on a warm restart is the cross-process "
        "reuse proof)")
    metrics.func_counter(
        "exec.compile.cache_miss", cache_misses,
        "XLA compilations that went to the backend compiler because "
        "the persistent cache had no entry")
    metrics.func_counter(
        "exec.compile.seconds", compile_seconds,
        "cumulative seconds inside XLA backend compilation "
        "(process-wide; near zero on a warm restart)")
    metrics.func_counter(
        "exec.compile.prewarmed", lambda: PREWARMED,
        "statements re-prepared by Engine.prewarm from the shapes "
        "journal at startup")


# -- shape bucket ladder -----------------------------------------------------

def _next_pow2(n: int) -> int:
    return 1 << (max(int(n), 1) - 1).bit_length()


@dataclass(frozen=True)
class ShapeLadder:
    """The closed set of padded row counts every executable is
    compiled for. `bucket(n)` maps a row count to its ladder rung;
    `budget(max_n)` is the executable count a row sweep up to max_n
    can possibly compile — the number the bucket-parity test gates.

    steps_per_octave = 1 reproduces the historical pow2 padding
    exactly; s > 1 inserts s evenly-spaced rungs per octave
    (e.g. s=2: 1024, 1536, 2048, 3072, 4096, ...). min_rows and
    steps_per_octave must be powers of two with
    min_rows/steps_per_octave >= 128, so every rung is a multiple of
    128 (Pallas kernel eligibility is ladder-invariant)."""

    min_rows: int = 1024
    steps_per_octave: int = 1

    def __post_init__(self):
        mr, s = self.min_rows, self.steps_per_octave
        if mr < 128 or mr & (mr - 1):
            raise ValueError("min_rows must be a power of two >= 128")
        if not (1 <= s <= 8) or s & (s - 1):
            raise ValueError(
                "steps_per_octave must be a power of two in [1, 8]")
        if mr // s < 128:
            raise ValueError("min_rows/steps_per_octave must be >= 128")

    def bucket(self, n: int) -> int:
        n = max(int(n), 1)
        if n <= self.min_rows:
            return self.min_rows
        p = _next_pow2(n)
        if self.steps_per_octave == 1:
            return p
        half = p // 2
        step = half // self.steps_per_octave
        # smallest rung in (half, p] that covers n
        return half + step * (-(-(n - half) // step))

    def budget(self, max_n: int, min_n: int = 1) -> int:
        """Distinct rungs a sweep over [min_n, max_n] can touch."""
        lo, hi = self.bucket(min_n), self.bucket(max_n)
        count, b = 1, lo
        while b < hi:
            b = self.bucket(b + 1)
            count += 1
        return count

    def rungs(self, max_n: int, min_n: int = 1) -> list[int]:
        out, b = [self.bucket(min_n)], self.bucket(min_n)
        hi = self.bucket(max_n)
        while b < hi:
            b = self.bucket(b + 1)
            out.append(b)
        return out


def ladder_from_settings(settings) -> ShapeLadder:
    try:
        return ShapeLadder(
            int(settings.get("sql.exec.shape_bucket.min_rows")),
            int(settings.get("sql.exec.shape_bucket.steps_per_octave")))
    except Exception:
        return ShapeLadder()


# -- shapes journal ----------------------------------------------------------

def journal_path(cache_d: str) -> str:
    return os.path.join(cache_d, _JOURNAL_NAME)


def journal_record(cache_d: str | None, sql_text: str,
                   bucket: int = 0, vars: dict | None = None) -> None:
    """Append an executable-cache miss to the shapes journal. Best
    effort: journal loss only costs pre-warm coverage. ``vars`` holds
    the plan-key-changing session vars the statement compiled under
    (non-default values only), so a pre-warm re-prepares the SAME
    executable the statement actually ran, not the default-session
    plan of the same text."""
    if not cache_d or not sql_text:
        return
    try:
        p = journal_path(cache_d)
        try:
            if os.path.getsize(p) > _JOURNAL_MAX_BYTES:
                return
        except OSError:
            pass
        rec = {"sql": sql_text, "n": int(bucket)}
        if vars:
            rec["vars"] = dict(vars)
        with _LOCK:
            with open(p, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
    except Exception:
        pass


def journal_entries(cache_d: str | None, k: int) -> list[tuple]:
    """The k hottest statement texts from the journal, each paired
    with its dominant recorded shape bucket (0 when the statement
    never journaled one — resident plans) and its dominant recorded
    session-var dict ({} when it always ran at defaults). The bucket
    is what Engine.prewarm compiles streamed-page and spill-partition
    executables at, and the vars are what it re-prepares under, so a
    restarted process warms the plans the previous one actually ran,
    not just the statement texts. Corrupt lines are skipped, a
    missing journal is an empty plan."""
    if not cache_d or k <= 0:
        return []
    from collections import Counter
    counts: Counter = Counter()
    buckets: dict[str, Counter] = {}
    varcounts: dict[str, Counter] = {}
    vartabs: dict[str, dict] = {}
    try:
        with open(journal_path(cache_d), encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                    sql = rec.get("sql")
                    if isinstance(sql, str) and sql:
                        counts[sql] += 1
                        b = int(rec.get("n") or 0)
                        if b > 0:
                            buckets.setdefault(sql, Counter())[b] += 1
                        jv = rec.get("vars")
                        if isinstance(jv, dict) and jv:
                            key = json.dumps(jv, sort_keys=True)
                            varcounts.setdefault(sql, Counter())[key] += 1
                            vartabs.setdefault(sql, {})[key] = jv
                except Exception:
                    continue
    except OSError:
        return []

    def dominant_vars(sql: str) -> dict:
        if sql not in varcounts:
            return {}
        return vartabs[sql][varcounts[sql].most_common(1)[0][0]]

    return [(sql,
             (buckets[sql].most_common(1)[0][0]
              if sql in buckets else 0),
             dominant_vars(sql))
            for sql, _ in counts.most_common(k)]


def journal_top(cache_d: str | None, k: int) -> list[str]:
    """The k statement texts with the most recorded compile misses,
    hottest first (journal_entries without the buckets/vars)."""
    return [e[0] for e in journal_entries(cache_d, k)]
