"""Cross-session batch windows for the OLTP fast lane.

The lane (exec/oltplane.py) already compiles a point statement down to
one native call — what remains at high concurrency is per-statement
dispatch: every session takes the statement gate, reads the clock,
bumps the timestamp cache, and (for writes) runs its own kv commit.
This module amortizes that across sessions the way the reference
amortizes WAL appends in its pipelined raft proposals: concurrent
eligible statements queue into a *window*, one thread (the leader)
drains the queue and executes the whole window fused — one multi-key
mirror probe for the reads, one group-committed kv transaction per
write round — and every waiter gets its own Result or statement error.

Batching is opportunistic, not timed: an uncontended request becomes
leader immediately and runs solo (zero added latency at low
concurrency); windows only grow when sessions actually pile up behind
a running window. Reads and writes collect into SEPARATE windows —
a group commit (kv transaction + intent resolution) is an order of
magnitude slower than a multi-key probe, and a shared queue would
head-of-line block every reader behind it. The session var
`oltp_batch=off` bypasses this module entirely and restores the
per-statement path bit-for-bit.
"""

from __future__ import annotations

import threading
import time
from collections import deque


class BatchReq:
    """One session's statement riding in a batch window."""

    __slots__ = ("plan", "lits", "session", "result", "error")

    def __init__(self, plan, lits, session):
        self.plan = plan
        self.lits = lits
        self.session = session
        self.result = None
        self.error = None

    @property
    def done(self) -> bool:
        return self.result is not None or self.error is not None


class _Collector:
    """One batch-window queue (reads or writes): its own
    condition-variable, queue, and leader slot, so the two statement
    kinds never wait on each other's windows."""

    def __init__(self, batcher, run_fn):
        self.batcher = batcher
        self.run_fn = run_fn
        # condition-variable idiom: the with-block IS the wait/notify
        # pattern (queue append, leader election, and waiter wakeup
        # all happen under this one cv)
        self.window_cv = threading.Condition()
        self.queue: list = []
        self.busy = False

    def submit(self, req) -> None:
        leader = False
        batch = None
        with self.window_cv:
            self.queue.append(req)
            while True:
                if req.done:
                    break
                if not self.busy:
                    # become the window leader: claim everything
                    # queued so far (including our own request)
                    self.busy = True
                    batch, self.queue = self.queue, []
                    leader = True
                    break
                self.window_cv.wait(timeout=1.0)
        if leader:
            try:
                self.batcher._run_window(batch, self.run_fn)
            finally:
                with self.window_cv:
                    self.busy = False
                    self.window_cv.notify_all()


class LaneBatcher:
    """Batch-window collector in front of the lane executors."""

    def __init__(self, engine):
        self.engine = engine
        self._reads = _Collector(self, engine._lane_read_batch)
        self._writes = _Collector(self, engine._lane_write_batch)
        # window stats (read by exec.oltp.batch.* metric families);
        # shared by both collectors, mutated only under this cv
        self.stats_cv = threading.Condition()
        self.windows = 0
        self.fused = 0
        self.statements = 0
        self._sizes: deque = deque(maxlen=512)
        # histogram .observe for flush-wait, assigned at engine metric
        # registration (None in engines built without a registry)
        self.wait_observer = None

    def size_p50(self) -> float:
        with self.stats_cv:
            sizes = sorted(self._sizes)
        if not sizes:
            return 0.0
        return float(sizes[len(sizes) // 2])

    def submit(self, plan, lits, session):
        """Execute one eligible statement through a batch window.
        Blocks until this request has an outcome; returns its Result
        or raises its per-statement error."""
        req = BatchReq(plan, lits, session)
        t0 = time.perf_counter()
        if plan.kind == "point":
            self._reads.submit(req)
        else:
            self._writes.submit(req)
        obs = self.wait_observer
        if obs is not None:
            obs(time.perf_counter() - t0)
        if req.error is not None:
            raise req.error
        return req.result

    # -- leader side ------------------------------------------------

    def _run_window(self, batch, fn) -> None:
        self._run_phase(batch, fn)
        with self.stats_cv:
            self.windows += 1
            self.statements += len(batch)
            if len(batch) > 1:
                self.fused += len(batch)
            self._sizes.append(len(batch))

    @staticmethod
    def _run_phase(reqs, fn) -> None:
        """Run one phase; guarantee every request leaves with exactly
        one outcome even if the executor dies mid-window (the fault
        bar: a waiter must never hang or see two outcomes)."""
        if not reqs:
            return
        try:
            fn(reqs)
        except BaseException as e:
            for r in reqs:
                if not r.done:
                    r.error = e
            if not isinstance(e, Exception):
                raise
        for r in reqs:
            if not r.done:  # pragma: no cover - executor contract
                r.error = RuntimeError(
                    "batch window dropped a request")
