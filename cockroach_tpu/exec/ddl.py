"""DDL: CREATE/DROP TABLE, secondary indexes, views, sequences, TRUNCATE
(pkg/sql/create_table.go, drop_table.go, create_view.go, truncate.go).

Split out of exec/engine.py (round-2 VERDICT Weak #4); see that
module's docstring for the overall execution model."""


import datetime


from ..sql import ast, parser
from ..sql.binder import Binder
from ..sql.types import ColumnSchema, Family, TableSchema
from ..storage import keys as K

EPOCH_DATE = datetime.date(1970, 1, 1)
EPOCH_DT = datetime.datetime(1970, 1, 1)

from .session import EngineError, Result, Session
from .stmtutil import _stmt_table_refs


class DDLMixin:
    """Engine methods for this concern; mixed into exec.engine.Engine
    (all state lives on the Engine instance)."""

    def _eval_column_default(self, d: ast.ColumnDef):
        """DEFAULT expr -> physical constant, or {"__seq__": name} for
        nextval('name') (evaluated per row at INSERT; pg stores the
        expression, we support the constant + sequence shapes)."""
        if d.default is None:
            return None
        e = d.default
        if isinstance(e, ast.FuncCall) and e.name == "nextval" \
                and len(e.args) == 1 \
                and isinstance(e.args[0], ast.Literal):
            return {"__seq__": str(e.args[0].value)}
        from ..sql.binder import Scope
        from ..sql.bound import BConst
        binder = Binder(Scope())
        try:
            b = binder.bind(e)
        except Exception as ex:
            raise EngineError(f"unsupported DEFAULT for column "
                              f"{d.name!r}: {ex}") from ex
        if not isinstance(b, BConst):
            raise EngineError(
                f"DEFAULT for column {d.name!r} must be a constant "
                f"or nextval(...)")
        if b.value is None:
            return None
        return binder._const_to(b, d.type).value

    # -- DDL -----------------------------------------------------------------
    def _exec_create(self, c: ast.CreateTable) -> Result:
        from ..catalog import (CatalogError, IndexDescriptor,
                               TableDescriptor)
        if c.name in self.store.tables:
            if c.if_not_exists:
                return Result(tag="CREATE TABLE")
            raise EngineError(f"table {c.name!r} already exists")
        schema = TableSchema(
            name=c.name,
            columns=[ColumnSchema(d.name, d.type, d.nullable,
                                  default=self._eval_column_default(d))
                     for d in c.columns],
            primary_key=list(c.primary_key))
        colnames = {d.name for d in c.columns}
        # validate FK references now (the reference resolves them in
        # the descriptor builder): target must exist and the referenced
        # columns must be its primary key or a unique index
        # unique column / table constraints become unique indexes at
        # birth (the table is empty — no backfill, straight to PUBLIC)
        uniq_sets = [[d.name] for d in c.columns if d.unique] \
            + [list(u) for u in c.uniques]
        fk_records = []
        for fkname, lcols, rt, rcols in c.foreign_keys:
            for cn in lcols:
                if cn not in colnames:
                    raise EngineError(f"fk column {cn!r} not in table")
            if rt == c.name:
                # self-referential: validate against the in-flight
                # definition (the table does not exist yet)
                rcols = rcols or list(c.primary_key)
                unique_sets = [tuple(c.primary_key)] + \
                    [tuple(u) for u in uniq_sets]
            elif rt in self.store.tables:
                rschema = self.store.table(rt).schema
                rcols = rcols or list(rschema.primary_key)
                unique_sets = [tuple(rschema.primary_key)] + [
                    tuple(i.columns) for i in self._table_indexes(rt)
                    if i.unique]
            else:
                raise EngineError(
                    f"referenced table {rt!r} does not exist")
            if tuple(rcols) not in unique_sets:
                raise EngineError(
                    f"foreign key must reference a primary key or "
                    f"unique index of {rt!r} (got {rcols})")
            if len(rcols) != len(lcols):
                raise EngineError("foreign key column count mismatch")
            fk_records.append({"name": fkname, "columns": list(lcols),
                               "ref_table": rt,
                               "ref_columns": list(rcols)})
        for u in uniq_sets:
            for cn in u:
                if cn not in colnames:
                    raise EngineError(
                        f"unique column {cn!r} not in table")
        desc0 = TableDescriptor.from_schema(schema)
        desc0.checks = [{"name": n, "expr_sql": text}
                        for n, _e, text in c.checks]
        desc0.fks = fk_records
        desc0.indexes = [
            IndexDescriptor(f"{c.name}_{'_'.join(u)}_key", 2 + i,
                            list(u), True, "public")
            for i, u in enumerate(uniq_sets)]
        # the descriptor (catalog, system of record) is written first,
        # transactionally — two racing CREATEs conflict on the
        # namespace key; the columnstore table is the scan-plane
        # materialization keyed by the allocated descriptor id
        try:
            desc = self.catalog.create_table(desc0)
        except CatalogError as e:
            if c.if_not_exists:
                return Result(tag="CREATE TABLE")
            raise EngineError(str(e)) from e
        schema.table_id = desc.id
        # copy the allocated stable column ids into the runtime schema
        # so the row codec's value tags match what a catalog-derived
        # schema (another gateway's refresh) will decode with
        by_name = {cd.name: cd.col_id for cd in desc.columns}
        for cs in schema.columns:
            cs.cid = by_name.get(cs.name, 0)
        self.store.create_table(schema)
        self._index_defs.pop(c.name, None)
        self._constraint_defs.pop(c.name, None)
        self._fk_children = None
        # CHECK expressions must bind against the new schema (catches
        # unknown columns / type errors at DDL time)
        try:
            scope, _ = self._dml_scope(c.name)
            for n, e, _text in c.checks:
                b = Binder(scope).bind(e)
                if b.type.family != Family.BOOL:
                    raise EngineError(
                        f"check constraint {n!r} must be boolean")
        except Exception:
            self.store.drop_table(c.name)
            self.catalog.drop_table(c.name)
            self._fk_children = None
            raise
        from ..utils import log
        log.structured(log.SQL_SCHEMA, "create_table", table=c.name,
                       columns=len(c.columns))
        return Result(tag="CREATE TABLE")

    def _check_no_open_txn_effects(self, table: str, verb: str) -> None:
        """Non-transactional DDL (TRUNCATE/DROP) vs open txns: a txn
        holding buffered effects on the table would resurrect rows (or
        crash _publish) when it commits after the DDL ran."""
        for s in list(self._open_sessions):
            if s.txn is not None and any(
                    eff[0] == table for eff in s.effects):
                raise EngineError(
                    f"cannot {verb} {table!r}: an open "
                    f"transaction has pending writes on it")

    def _exec_drop(self, d: ast.DropTable) -> Result:
        from ..catalog import CatalogError
        if d.name in self._view_map():
            raise EngineError(
                f"{d.name!r} is a view; use DROP VIEW")
        deps = [v for v, vd in self._view_map().items()
                if d.name in _stmt_table_refs(
                    parser.parse(vd.view_sql))]
        if deps:
            raise EngineError(
                f"cannot drop table {d.name!r}: view(s) "
                f"{sorted(deps)} depend on it")
        fk_deps = sorted({child for child, _fk in
                          self._fk_children_of(d.name)
                          if child != d.name})
        if fk_deps:
            raise EngineError(
                f"cannot drop table {d.name!r}: foreign key(s) on "
                f"{fk_deps} reference it")
        if d.name not in self.store.tables:
            if d.if_exists:
                return Result(tag="DROP TABLE")
            raise EngineError(f"table {d.name!r} does not exist")
        self._check_no_open_txn_effects(d.name, "DROP TABLE")
        try:
            self.catalog.drop_table(d.name)
        except CatalogError:
            pass  # store-only table (pre-catalog tests); still drop it
        self.store.drop_table(d.name)
        self._index_defs.pop(d.name, None)
        self._constraint_defs.pop(d.name, None)
        self._fk_children = None
        for k in [k for k in self._device_tables if k[0] == d.name]:
            self._evict_device(k)
        self._bump_tgen_ddl(d.name, dropped=True)
        return Result(tag="DROP TABLE")

    # -- secondary indexes ----------------------------------------------------
    # Design (vs pkg/sql/rowenc + colfetcher/index_join.go): the scan
    # plane is columnar and the analytic path never decodes keys, so a
    # non-unique index is a *derived* host-side locator over the
    # columnstore (generation-cached, storage/columnstore.py
    # ensure_secondary_index) used for point-read/DML acceleration.
    # UNIQUE indexes additionally materialize KV entries at
    # /Table/<tid>/<index_id>/<vals> -> pk-key through the row-plane
    # txn, so two concurrent writers of the same value conflict
    # transactionally — the same guarantee the reference gets from
    # CPut on index keys (pkg/sql/row/writer.go).

    def _table_indexes(self, table: str) -> list:
        cached = self._index_defs.get(table)
        if cached is not None:
            return cached
        # a transient catalog error must fail the statement, NOT be
        # cached as "no indexes" (which would silently drop unique
        # enforcement); a missing descriptor (pre-catalog test table)
        # legitimately has none
        d = self.catalog.get_by_name(table)
        idxs = list(d.indexes) if d is not None else []
        self._index_defs[table] = idxs
        return idxs

    def _exec_create_index(self, c: ast.CreateIndex,
                           session: Session) -> Result:
        from ..catalog import IndexDescriptor
        from ..catalog.descriptor import WRITE_ONLY
        from ..jobs.schemachange import INDEX_BACKFILL_JOB
        if c.table not in self.store.tables:
            raise EngineError(f"table {c.table!r} does not exist")
        td = self.store.table(c.table)
        for cn in c.columns:
            try:
                td.schema.column(cn)
            except KeyError:
                raise EngineError(
                    f"column {cn!r} does not exist in {c.table!r}")
        desc = self.catalog.get_by_name(c.table)
        if desc is None:
            raise EngineError(
                f"table {c.table!r} has no descriptor (pre-catalog)")
        if c.name == "primary":
            raise EngineError(
                "index name 'primary' is reserved for the primary key")
        if any(i.name == c.name for i in desc.indexes):
            if c.if_not_exists:
                return Result(tag="CREATE INDEX")
            raise EngineError(
                f"index {c.name!r} already exists on {c.table!r}")
        next_id = 1 + max([i.index_id for i in desc.indexes],
                          default=1)  # primary index is 1
        # step 1: WRITE_ONLY — after the lease drain every writer
        # maintains the index, but readers don't use it yet
        desc.indexes.append(IndexDescriptor(
            c.name, next_id, list(c.columns), c.unique, WRITE_ONLY))
        desc = self.leases.publish(desc)
        self._index_defs.pop(c.table, None)
        # step 2: chunk-checkpointed backfill + validation + PUBLIC
        # publish as a durable job (resumable after a crash), like the
        # reference's index backfiller (pkg/sql/backfill via pkg/jobs)
        job_id = self.jobs.create(INDEX_BACKFILL_JOB,
                                  {"table": c.table, "index": c.name})
        rec = self.jobs.run_job(job_id)
        self._index_defs.pop(c.table, None)
        if rec.status != "succeeded":
            raise EngineError(
                f"CREATE INDEX failed: {rec.error or rec.status}")
        return Result(tag="CREATE INDEX")

    def _exec_drop_index(self, d_stmt: ast.DropIndex,
                         session: Session) -> Result:
        found = []
        for desc in self.catalog.list_tables():
            for i in desc.indexes:
                if i.name == d_stmt.name:
                    found.append((desc, i))
        if not found:
            if d_stmt.if_exists:
                return Result(tag="DROP INDEX")
            raise EngineError(f"index {d_stmt.name!r} does not exist")
        if len(found) > 1:
            tables = sorted(d.name for d, _ in found)
            raise EngineError(
                f"index name {d_stmt.name!r} is ambiguous (exists on "
                f"tables {tables}); drop and recreate with distinct "
                f"names")
        desc, idx = found[0]
        desc.indexes = [i for i in desc.indexes if i.name != idx.name]
        self.leases.publish(desc)
        self._index_defs.pop(desc.name, None)
        if idx.unique:
            # clear the index keyspace (the reference runs this as a
            # GC-TTL'd schema-change job; immediate here)
            p = K.table_prefix(desc.id, idx.index_id)
            self.kv.txn(lambda t: t.delete_range(p, K.prefix_end(p)))
        return Result(tag="DROP INDEX")

    # -- views ----------------------------------------------------------------
    # A view is a descriptor carrying SQL text; every use re-plans it
    # as a derived table (pkg/sql/create_view.go + opt view expansion).

    def _view_map(self) -> dict:
        if getattr(self, "_view_defs", None) is None:
            self._view_defs = {
                d.name: d for d in self.catalog.list_tables()
                if d.view_sql}
        return self._view_defs

    def _expand_views(self, sel: ast.Select,
                      depth: int = 0) -> ast.Select:
        views = self._view_map()
        # SQL scoping: a CTE binding shadows a same-named view
        cte_names = {name for name, _c, _s in sel.ctes}
        if cte_names:
            views = {k: v for k, v in views.items()
                     if k not in cte_names}
        if not views:
            return sel
        if depth > 16:
            raise EngineError("view nesting too deep (cycle?)")
        import copy
        refs = ([sel.table] if sel.table is not None else []) \
            + [j.table for j in sel.joins]
        if not any(r.subquery is None and r.name in views
                   for r in refs):
            return sel
        sel = copy.copy(sel)

        def expand_ref(ref: ast.TableRef) -> ast.TableRef:
            if ref.subquery is not None or ref.name not in views:
                return ref
            d = views[ref.name]
            body = parser.parse(d.view_sql)
            if not isinstance(body, ast.Select):
                raise EngineError(
                    f"view {d.name!r} body is not a plain SELECT")
            body = self._expand_views(body, depth + 1)
            if d.view_columns:
                body = copy.copy(body)
                body.items = [
                    ast.SelectItem(it.expr, alias=cn, star=False)
                    for it, cn in zip(body.items, d.view_columns)]
            return ast.TableRef(name=f"__view_{d.name}",
                                alias=ref.alias or ref.name,
                                subquery=body)

        if sel.table is not None:
            sel.table = expand_ref(sel.table)
        sel.joins = [ast.JoinClause(expand_ref(j.table), j.join_type,
                                    j.on) for j in sel.joins]
        return sel

    def _exec_create_view(self, c: ast.CreateView,
                          session: Session) -> Result:
        import copy
        from ..catalog import CatalogError, TableDescriptor
        if c.name in self.store.tables or c.name in self._view_map():
            if c.if_not_exists:
                return Result(tag="CREATE VIEW")
            raise EngineError(f"relation {c.name!r} already exists")
        if not isinstance(c.select, ast.Select):
            raise EngineError(
                "CREATE VIEW body must be a plain SELECT")
        if c.columns is not None and any(
                it.star for it in c.select.items):
            raise EngineError(
                "view column list requires explicit select items")
        # validate by executing the body with LIMIT 0 — catches
        # unknown tables/columns and type errors at DDL time, like the
        # reference's view dependency check
        probe = copy.deepcopy(c.select)
        probe.limit = 0
        res = self._exec_select(probe, session,
                                f"(create-view {c.name})")
        if c.columns is not None and len(c.columns) != len(res.names):
            raise EngineError(
                f"view column list has {len(c.columns)} names, "
                f"SELECT produces {len(res.names)}")
        try:
            self.catalog.create_table(TableDescriptor(
                id=0, name=c.name, view_sql=c.sql,
                view_columns=list(c.columns or [])))
        except CatalogError as e:
            if c.if_not_exists:
                return Result(tag="CREATE VIEW")
            raise EngineError(str(e)) from e
        self._view_defs = None
        return Result(tag="CREATE VIEW")

    def _exec_drop_view(self, d: ast.DropView) -> Result:
        if d.name not in self._view_map():
            if d.if_exists:
                return Result(tag="DROP VIEW")
            raise EngineError(f"view {d.name!r} does not exist")
        deps = [v for v, vd in self._view_map().items()
                if v != d.name and d.name in _stmt_table_refs(
                    parser.parse(vd.view_sql))]
        if deps:
            raise EngineError(
                f"cannot drop view {d.name!r}: view(s) "
                f"{sorted(deps)} depend on it")
        self.catalog.drop_table(d.name)
        self._view_defs = None
        return Result(tag="DROP VIEW")

    # -- sequences (DDL) ------------------------------------------------------
    def _exec_create_sequence(self, c: ast.CreateSequence) -> Result:
        import json as _json
        key = self.SEQ_PREFIX + c.name.encode()

        def fn(t):
            if t.get(key) is not None:
                if c.if_not_exists:
                    return
                raise EngineError(
                    f"sequence {c.name!r} already exists")
            t.put(key, _json.dumps({
                "start": c.start, "increment": c.increment,
                "value": None}).encode())
        self.kv.txn(fn)
        return Result(tag="CREATE SEQUENCE")

    def _exec_drop_sequence(self, d: ast.DropSequence) -> Result:
        key = self.SEQ_PREFIX + d.name.encode()

        def fn(t):
            if t.get(key) is None:
                if d.if_exists:
                    return
                raise EngineError(
                    f"sequence {d.name!r} does not exist")
            t.delete(key)
        self.kv.txn(fn)
        return Result(tag="DROP SEQUENCE")

    # -- TRUNCATE -------------------------------------------------------------
    def _exec_truncate(self, tr: ast.Truncate) -> Result:
        """Clear all rows + KV pairs + index entries, keep the schema
        (the reference swaps in fresh empty indexes and lets GC reap
        the old keyspace, pkg/sql/truncate.go)."""
        if tr.table not in self.store.tables:
            raise EngineError(f"table {tr.table!r} does not exist")
        fk_deps = sorted({child for child, _fk in
                          self._fk_children_of(tr.table)
                          if child != tr.table})
        if fk_deps:
            raise EngineError(
                f"cannot truncate {tr.table!r}: foreign key(s) on "
                f"{fk_deps} reference it")
        # TRUNCATE rebuilds the store table outside any txn: a txn that
        # committed afterwards would resurrect its buffered rows/index
        # entries, so refuse while open txns hold effects on the table
        # (including the caller's own — our TRUNCATE is not
        # transactional, unlike pg's)
        self._check_no_open_txn_effects(tr.table, "TRUNCATE")
        td = self.store.table(tr.table)
        schema = td.schema
        # the whole table keyspace: every index id under the table
        base = bytearray(K.TABLE_PREFIX)
        K.encode_int(base, schema.table_id)
        base = bytes(base)
        self.kv.txn(lambda t: t.delete_range(base, K.prefix_end(base)))
        self.store.drop_table(tr.table)
        self.store.create_table(schema)
        self._evict(tr.table)
        self._bump_tgen_ddl(tr.table)
        return Result(tag="TRUNCATE")

