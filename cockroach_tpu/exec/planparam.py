"""Statement-shape plan parameterization for the analytic path.

The OLTP lane already strips literals from statement TEXT
(oltplane.normalize) so point reads share a compiled kernel. This
module does the same one level down, on the bound PLAN: eligible
filter literals are replaced by ``BParam`` placeholders whose values
ride the dispatch as runtime scalars, so 100 sessions running the
same parameterized q3/q6 with different dates/quantities share ONE
``_exec_cache`` entry instead of each paying a trace (the reference's
plan cache keyed on the statement fingerprint, pkg/sql/plan_cache).

Conservative by construction: only constants inside ``Filter.pred`` /
``Scan.filter`` comparison spines are lifted — anything that shapes
the compiled program stays baked and keeps the plan fingerprint
distinct, so a shape-changing literal (LIMIT, Compact.frac derived
from selectivity, dictionary masks, function args read at compile
time) misses the cache instead of sharing a wrong executable.
"""

from __future__ import annotations

import dataclasses
import hashlib
import re

import numpy as np

from ..sql import bound as B
from ..sql import plan as P
from ..sql.types import Family

# Literal families whose physical scalars can ride as runtime args.
# STRING (and ARRAY/JSON) predicates are host-pre-evaluated into
# dictionary tables at bind time, so they are inherently baked; BOOL
# constants often fold control flow.
_ELIGIBLE = (Family.INT, Family.DECIMAL, Family.DATE, Family.TIMESTAMP,
             Family.FLOAT)

# Bound on lifted literals per statement: each becomes one extra jit
# argument; a pathological filter should fall back to text keying.
_MAX_PARAMS = 16


def _eligible_const(e) -> bool:
    return (isinstance(e, B.BConst) and e.value is not None
            and not isinstance(e.value, bool)
            and e.type is not None and e.type.family in _ELIGIBLE)


class _Lifter:
    def __init__(self):
        self.values: list = []
        self.overflow = False

    def const(self, e: B.BConst):
        dt = e.type.np_dtype
        v = np.asarray(e.value, dtype=dt)
        if v.item() != e.value:  # lossy physical round-trip: keep baked
            return e
        if len(self.values) >= _MAX_PARAMS:
            self.overflow = True
            return e
        self.values.append(v)
        return B.BParam(len(self.values) - 1, e.type)

    def expr(self, e):
        """Rewrite the comparison spine of a predicate. Recursion is a
        whitelist — BBin/BUnary/BBetween — because other nodes read
        constant args structurally at compile time (BFunc's round_n
        digits, BInList value lists, dictionary tables)."""
        if _eligible_const(e):
            return self.const(e)
        if isinstance(e, B.BBin):
            l, r = self.expr(e.left), self.expr(e.right)
            if l is not e.left or r is not e.right:
                return B.BBin(e.op, l, r, e.type)
            return e
        if isinstance(e, B.BUnary):
            o = self.expr(e.operand)
            if o is not e.operand:
                return B.BUnary(e.op, o, e.type)
            return e
        if isinstance(e, B.BBetween):
            x, lo, hi = self.expr(e.expr), self.expr(e.lo), self.expr(e.hi)
            if x is not e.expr or lo is not e.lo or hi is not e.hi:
                return B.BBetween(x, lo, hi, e.negated, e.type)
            return e
        return e

    def node(self, n):
        if isinstance(n, P.Scan):
            if n.filter is None:
                return n
            f = self.expr(n.filter)
            return n if f is n.filter else dataclasses.replace(n, filter=f)
        if isinstance(n, P.Filter):
            c = self.node(n.child)
            p = self.expr(n.pred) if n.pred is not None else None
            if c is n.child and p is n.pred:
                return n
            return dataclasses.replace(n, child=c, pred=p)
        if isinstance(n, P.HashJoin):
            l, r = self.node(n.left), self.node(n.right)
            if l is n.left and r is n.right:
                return n
            return dataclasses.replace(n, left=l, right=r)
        if isinstance(n, (P.Project, P.Aggregate, P.Sort, P.Limit,
                          P.Window, P.Compact)):
            c = self.node(n.child)
            return n if c is n.child else dataclasses.replace(n, child=c)
        return n  # unknown node: leave baked (conservative)


def parameterize(node):
    """Lift eligible filter literals out of ``node``.

    Returns ``(parameterized_node, values)`` — values is a tuple of np
    scalars positionally matching the BParam indices — or
    ``(node, None)`` when nothing was lifted (or too much would be)."""
    lf = _Lifter()
    out = lf.node(node)
    if lf.overflow or not lf.values:
        return node, None
    return out, tuple(lf.values)


def plan_fingerprint(node) -> str:
    """Deterministic structural fingerprint of a plan tree.

    Unlike ``hash(repr(node))``, ndarray payloads (dictionary masks,
    remap tables) hash their full bytes — repr truncates large arrays,
    which could collide two different plans once sql_text leaves the
    cache key. Fields marked repr=False (e.g. BDictGather.dictionary,
    a fresh object per bind) are skipped, matching the planner's
    structural-match convention."""
    h = hashlib.sha1()

    def feed(o):
        if isinstance(o, np.ndarray):
            h.update(b"nd|")
            h.update(str(o.dtype).encode())
            h.update(str(o.shape).encode())
            h.update(o.tobytes())
        elif dataclasses.is_dataclass(o) and not isinstance(o, type):
            h.update(type(o).__name__.encode())
            for f in dataclasses.fields(o):
                if not f.repr:
                    continue
                h.update(f.name.encode())
                feed(getattr(o, f.name))
        elif isinstance(o, (list, tuple)):
            h.update(b"[")
            for x in o:
                feed(x)
            h.update(b"]")
        elif isinstance(o, dict):
            h.update(b"{")
            for k, v in o.items():
                feed(k)
                feed(v)
            h.update(b"}")
        elif isinstance(o, frozenset):
            h.update(b"fs")
            for x in sorted(repr(x) for x in o):
                h.update(x.encode())
        else:
            h.update(repr(o).encode())
        h.update(b";")

    feed(node)
    return h.hexdigest()


# Statement-shape text: literals -> "?" so literal-varying texts key
# identically. Broader than oltplane._LIT_RE (floats too); string
# literals normalize here even though their plans stay distinct — the
# plan fingerprint disambiguates them.
_LIT_RE = re.compile(
    r"'(?:[^']|'')*'|(?<![\w.])\d+(?:\.\d+(?:[eE][+-]?\d+)?)?(?![\w.])")


def shape_text(sql: str) -> str:
    return _LIT_RE.sub("?", sql)
