"""Scan-plane runtime: hash-partitioned spill, beyond-HBM streaming, the
device table cache, and result materialization (the block-cache +
disk-spiller analogues, colexecdisk/disk_spiller.go:75).

Split out of exec/engine.py (round-2 VERDICT Weak #4); see that
module's docstring for the overall execution model."""


import datetime
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.batch import ColumnBatch
from ..parallel import mesh as meshmod
from ..parallel.distagg import analyze as dist_analyze
from ..parallel.distagg import make_distributed_fn, queued_collective_call
from ..parallel.mesh import SHARD_AXIS
from ..sql import plan as P
from ..storage.hlc import Timestamp
from ..utils.mon import MemoryQuotaError
from .compile import (ExecParams, RunContext, can_spill_sort,
                      can_stream, compile_plan)

EPOCH_DATE = datetime.date(1970, 1, 1)
EPOCH_DT = datetime.datetime(1970, 1, 1)

from .session import (SENTINEL_COLUMNS, CompactOverflow, EngineError,
                      HashCapacityExceeded, Prepared, TopKInexact,
                      Result, Session)
from .stmtutil import (_collect_scans, _count_aggs, _decode_column, _has_join, _host_sort, _pad)
from .stream import PageSource
from .stream import prefetch as stream_prefetch
from . import profile as _prof
import time as _time


# exception factory per sentinel; names come from the one registry
# (session.SENTINEL_COLUMNS) so a new sentinel missing its mapping
# here fails loudly at import time
_SENTINEL_EXCS = {
    "__ht_overflow": lambda: HashCapacityExceeded(
        "GROUP BY cardinality exceeded hash_group_capacity; "
        "SET hash_group_capacity to a larger power of two"),
    "__sum_overflow": lambda: EngineError(
        "decimal SUM overflowed int64 accumulation; "
        "CAST the argument to FLOAT to trade exactness for range"),
    "__topk_inexact": lambda: TopKInexact(
        "top-k cut crossed a primary-key tie group; "
        "replanning with the full sort"),
    "__compact_overflow": lambda: CompactOverflow(
        "selection compaction overflowed a block's capacity; "
        "replanning uncompacted"),
}
_SENTINEL_PAIRS = tuple((n, _SENTINEL_EXCS[n]) for n in SENTINEL_COLUMNS)


class ScanPlaneMixin:
    """Engine methods for this concern; mixed into exec.engine.Engine
    (all state lives on the Engine instance)."""

    # -- hash-partitioned spill ---------------------------------------------
    MAX_SPILL_PARTITIONS = 256
    # duplicate-key join expansion cap: output rows = probe.n * K
    MAX_JOIN_EXPANSION = 32

    def _run_partitioned(self, prep: "Prepared",
                         read_ts: Optional[Timestamp]) -> Result:
        """Partition-and-recurse fallback for hash GROUP BY overflow.

        The compiled program already takes (nparts, pid) scalars and
        keeps only rows whose salted key-hash lands in partition pid
        (ops/hashtable.py partition_mask), so spilling is: rerun the
        SAME program once per partition, concatenate the per-partition
        group rows on the host, then apply any Sort/Limit there
        (device sort/limit would have been per-partition). Doubling
        the partition count until every partition fits mirrors the
        reference's recursive hash_based_partitioner; re-reads hit the
        resident HBM table instead of disk.
        """
        node, meta = self._plan(prep.stmt, prep.session)
        limit_node = sort_node = None
        if isinstance(node, P.Limit):
            limit_node, node = node, node.child
        if isinstance(node, P.Sort):
            sort_node, node = node, node.child
        if not isinstance(node, P.Aggregate) or node.max_groups > 0:
            raise HashCapacityExceeded(
                "GROUP BY overflow in a non-spillable plan shape; "
                "SET hash_group_capacity to a larger power of two")

        # compile the STRIPPED plan (no device Sort/Limit — a per-
        # partition limit would truncate wrongly); reuse prep's device
        # scans, which already match the distribution decision
        cap = int(prep.session.vars.get("hash_group_capacity", 1 << 17))
        decision = self._dist_decision(node, prep.session)
        shapes = tuple(sorted((a, b.n) for a, b in prep.scans.items()))
        dictlens = tuple(
            sorted((t, tuple(sorted((cn, len(d)) for cn, d in
                                    self.store.table(t).dictionaries
                                    .items())))
                   for t, _ in prep.gens))
        key = ("spill", prep.sql_text, shapes, dictlens, cap,
               decision is not None, hash(repr(node)))
        cached = self._exec_cache.get(key)
        if cached is None:
            params = ExecParams(
                hash_group_capacity=cap,
                axis_name=SHARD_AXIS if decision is not None else None,
                n_shards=(self.mesh.devices.size
                          if decision is not None else 1))
            runf = compile_plan(node, params, meta)
            if decision is not None:
                jfn = queued_collective_call(jax.jit(
                    make_distributed_fn(
                        runf, self.mesh, _collect_scans(node),
                        decision)),
                    metrics=self.metrics, mesh=self.mesh)
            else:
                def fn(scans_in, ts_in, np_, pid_):
                    return runf(RunContext(scans_in, ts_in, np_, pid_))
                jfn = jax.jit(fn)
            self._exec_cache_put(key, (jfn, meta))
        else:
            jfn, meta = cached

        ts = read_ts or self._read_ts(prep.session)
        tsv = np.int64(ts.to_int())

        def run_pid(fn, scans, np_enc: int, pid_enc: int) -> list:
            out = fn(scans, tsv, np.int32(np_enc), np.int32(pid_enc))
            return self._materialize(out, meta).rows

        def pid_rows(fn, scans, nparts: int, pid: int) -> list:
            try:
                return run_pid(fn, scans, nparts, pid)
            except HashCapacityExceeded:
                if nparts < self.MAX_SPILL_PARTITIONS:
                    raise  # outer loop doubles the level-1 fan-out
                # grace-style recursion (the reference's
                # hash_based_partitioner): at the level-1 ceiling this
                # partition's keys collide under the first salt, so
                # doubling can never separate them — subdivide JUST
                # this partition under the rotated salt (encoded into
                # the same (nparts, pid) scalars, ops/hashtable.py)
                l2 = 2
                while l2 <= self.MAX_SPILL_PARTITIONS:
                    try:
                        rows: list = []
                        for pid2 in range(l2):
                            rows.extend(run_pid(
                                fn, scans, nparts * l2,
                                pid2 * nparts + pid))
                        self.metrics.counter(
                            "exec.spill.grace_subsweeps",
                            "spill partitions subdivided under a "
                            "rotated hash past the level-1 ceiling"
                        ).inc()
                        return rows
                    except HashCapacityExceeded:
                        l2 *= 2
                raise HashCapacityExceeded(
                    f"GROUP BY did not fit hash_group_capacity even "
                    f"at {self.MAX_SPILL_PARTITIONS} spill partitions "
                    f"x {self.MAX_SPILL_PARTITIONS} rotated-salt "
                    f"sub-partitions")

        # transient working-set estimate for the unified transfer
        # budget: one partition's slice of the resident inputs
        scan_bytes = sum(int(x.nbytes)
                         for b in prep.scans.values()
                         for x in jax.tree.leaves(b))
        nparts = 2
        while True:
            try:
                with self.movement.soft_lease(
                        "spill", scan_bytes // max(nparts, 1)):
                    all_rows = self._sweep_spill_partitions(
                        jfn, decision, prep, nparts, pid_rows, key,
                        node, meta, cap)
                break
            except HashCapacityExceeded:
                if nparts >= self.MAX_SPILL_PARTITIONS:
                    raise  # grace depth exhausted inside pid_rows
                nparts *= 2

        _prof.note("spill:agg", batches=nparts, rows=len(all_rows))
        rows = all_rows
        if sort_node is not None:
            rows = _host_sort(rows, meta, sort_node.keys)
        if limit_node is not None:
            off = limit_node.offset or 0
            end = (off + limit_node.limit
                   if limit_node.limit is not None else None)
            rows = rows[off:end]
        return Result(names=list(meta.names), rows=rows)

    def _sweep_spill_partitions(self, jfn, decision, prep, nparts: int,
                                pid_rows, key, node, meta, cap) -> list:
        """Run every spill partition and concatenate rows in pid
        order. With a distributed decision and a splittable mesh, the
        sweep fans out over DISJOINT pool sub-meshes (round-10
        MeshPool) so independent partitions overlap instead of
        serializing through one device set; any failure to stand up
        the sub-mesh plane (budget, pool shape) falls back to the
        serial full-mesh sweep."""
        subs = None
        if decision is not None and nparts >= 2:
            subs = self._submesh_spill_calls(key, node, meta, cap,
                                             decision)
        if subs is None:
            out: list = []
            for pid in range(nparts):
                out.extend(pid_rows(jfn, prep.scans, nparts, pid))
            return out
        calls, scanses = subs
        nsub = len(calls)
        import concurrent.futures as cf
        results: list = [None] * nparts

        def worker(pid: int) -> list:
            # fixed pid->sub-mesh assignment: two pids on one sub-mesh
            # serialize through its FIFO dispatcher; different
            # sub-meshes run concurrently (disjoint rendezvous
            # domains, same-mode gate windows)
            idx = pid % nsub
            return pid_rows(calls[idx], scanses[idx], nparts, pid)

        with cf.ThreadPoolExecutor(max_workers=nsub) as ex:
            futs = {pid: ex.submit(worker, pid)
                    for pid in range(nparts)}
            err = None
            for pid, f in futs.items():
                try:
                    results[pid] = f.result()
                except HashCapacityExceeded as e:
                    err = err or e
            if err is not None:
                raise err
        self.metrics.counter(
            "exec.spill.submesh_sweeps",
            "spill partition sweeps fanned out over pool sub-meshes"
        ).inc()
        return [r for part in results for r in part]

    def _submesh_spill_calls(self, key, node, meta, cap, decision):
        """Per-sub-mesh compiled calls + re-resolved device scans for
        the concurrent spill sweep, cached under the spill exec-cache
        key. None when the pool can't yield >=2 disjoint sub-meshes
        or the budget can't hold the per-sub-mesh table copies."""
        pool = self._submesh_pool()
        if pool is None:
            return None
        sizes = [s for s in sorted(pool.sizes(), reverse=True)
                 if s >= 2 and pool.count(s) >= 2]
        if not sizes:
            return None
        size = sizes[0]
        ck = key + ("submesh", size)
        cached = self._exec_cache.get(ck)
        if cached is not None:
            return cached
        aliases = _collect_scans(node)
        params = ExecParams(hash_group_capacity=cap,
                            axis_name=SHARD_AXIS, n_shards=size)
        runf = compile_plan(node, params, meta)
        calls = []
        scanses = []
        try:
            for sub in pool.submeshes(size):
                calls.append(queued_collective_call(
                    jax.jit(make_distributed_fn(runf, sub, aliases,
                                                decision)),
                    metrics=self.metrics, mesh=sub))
                scanses.append({
                    alias: self._device_table(
                        tname,
                        ("sharded" if alias in decision.sharded
                         else "replicated"),
                        cols=None, narrow=False, mesh=sub)
                    for alias, tname in aliases.items()})
        except MemoryQuotaError:
            return None
        out = (calls, scanses)
        self._exec_cache_put(ck, out)
        return out

    # -- beyond-HBM streaming ------------------------------------------------
    def _stream_decision(self, node, scan_aliases: dict, scan_cols: dict,
                         session: Session):
        """Page the fact table through HBM when its pruned upload would
        not fit the device budget. Eligibility mirrors the mesh
        distribution analysis (the plan must reduce to mergeable
        aggregate partials); only the probe-spine scan streams.
        Returns (alias, table, page_rows) or None."""
        if session.vars.get("streaming", "auto") == "off":
            return None
        budget = int(self.settings.get("sql.exec.hbm_budget_bytes"))
        if budget <= 0:
            return None
        if not can_stream(node):
            # dist_analyze accepts more shapes (e.g. hash GROUP BY)
            # than paging can compile; never pick those
            return None
        d = dist_analyze(node)
        if not d.ok or len(d.sharded) != 1:
            return None
        alias = next(iter(d.sharded))
        tname = scan_aliases[alias]
        td = self.store.table(tname)
        if td.row_count == 0:
            return None
        # working set = pruned upload + aggregation temporaries. XLA's
        # segment reductions materialize ~2 n-length temps per
        # aggregate concurrently (measured: TPC-H Q1 at 2^27 rows
        # compiles to ~12GB of HLO temps), so a table that "fits" can
        # still OOM at compile time without this term.
        n_aggs = _count_aggs(node)
        # the resident upload this decision weighs would narrow its
        # int32-provable columns UNLESS the scan feeds a join
        # (_set_scan_narrowing keeps probe spines wide) — charging
        # int64 width for narrowed columns inflates the estimate ~2x
        # and streams tables that actually fit
        cols = scan_cols.get(alias)
        narrow = (frozenset() if _has_join(node)
                  else self.narrow32_cols(tname, cols))
        # the working set a resident execution would REALLY upload:
        # zone-surviving chunks when the whole table is over budget
        # (selective scans stop escalating to paging unnecessarily)
        eff_bytes, eff_rows = self._effective_table_bytes(
            node, alias, tname, cols, narrow=narrow)
        temp_bytes = 16 * n_aggs * self._row_bucket(eff_rows)
        if eff_bytes + temp_bytes <= budget:
            return None
        # Build-side tables still upload whole: streaming the probe is
        # strictly better than not, and an over-budget build fails
        # upstream with a clean quota error rather than silently here.
        return (alias, tname, self._page_rows(session))

    def _page_rows(self, session: Session) -> int:
        """Session page size rounded UP to a shape-ladder bucket: page
        shapes feed the same bucket-padded programs as resident
        uploads and spill partitions (exec/coldstart.ShapeLadder), so
        an off-ladder SET streaming_page_rows would give the tail page
        a shape no other page shares and recompile per page."""
        return self._row_bucket(
            int(session.vars.get("streaming_page_rows", 1 << 21)))

    # -- out-of-core spill tier (exec/spill.py) -----------------------------
    def _spill_decision(self, node, scan_aliases: dict, scan_cols: dict,
                        session: Session, meta):
        """Third verdict of the four-way plan placement (resident |
        stream-scan | spill-join | spill-sort): hand the plan to the
        out-of-core tier when the working set cannot fit the device
        budget any other way. ``SET spill = auto|on|off`` gates it:
        auto spills only when the resident/stream paths would blow
        ``sql.exec.hbm_budget_bytes``, on forces every eligible shape
        (tests/bench), off disables (the A/B lever). Returns a
        spill.SpillPlan or None."""
        mode = session.vars.get("spill", "auto")
        if mode == "off":
            return None
        budget = int(self.settings.get("sql.exec.hbm_budget_bytes"))
        if budget <= 0:
            return None
        page_rows = self._page_rows(session)
        sp = self._spill_join_decision(node, scan_aliases, scan_cols,
                                       mode, budget, page_rows)
        if sp is not None:
            return sp
        return self._spill_sort_decision(node, scan_aliases, scan_cols,
                                         meta, mode, budget, page_rows)

    def _spill_join_decision(self, node, scan_aliases: dict,
                             scan_cols: dict, mode: str, budget: int,
                             page_rows: int):
        """Partitioned-external-hash-join eligibility + trigger.

        Shape: a streamable aggregate over a join spine (the same
        can_stream + single-sharded-alias contract the stream-scan
        path uses — the probe pages through the device either way),
        where some build side is a plain Scan joined on raw stored
        int-family keys on BOTH sides. STRING keys are out: their
        stored values are per-table dictionary codes, so one side
        compares through a code remap and raw-code partitioning would
        split equal keys. Int-family keys are safe regardless of
        width: the device compares values (int32 uploads upcast), and
        equal values cast to equal int64 bits, so both sides of an
        equal pair hash to the same partition. Inner/left only — a
        build row unmatched in ITS partition is genuinely unmatched.

        Trigger (auto): the stream-scan path uploads every build
        whole, so its runtime floor is sum(build uploads) + two
        in-flight probe pages + per-page aggregation temps (the
        streamed compile aggregates page-at-a-time, so temps scale
        with the page, not the table); spill when that floor exceeds
        the budget (the resident path needs strictly more). The
        LARGEST eligible build spills; the partition count doubles
        until one resident partition fits what the budget leaves."""
        from .spill import SpillPlan
        if not _has_join(node) or not can_stream(node):
            return None
        d = dist_analyze(node)
        if not d.ok or len(d.sharded) != 1:
            return None
        alias = next(iter(d.sharded))
        tname = scan_aliases[alias]
        ptd = self.store.table(tname)
        if ptd.row_count == 0:
            return None
        probe_scan = None
        cands = []  # (build_bytes, join, build_scan, pkeys, bkeys)
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, P.Scan) and n.alias == alias:
                probe_scan = n
            if (isinstance(n, P.HashJoin)
                    and n.join_type in ("inner", "left")
                    and isinstance(n.right, P.Scan)
                    and alias in _collect_scans(n.left)):
                cands.append(n)
            for attr in ("child", "left", "right"):
                c = getattr(n, attr, None)
                if c is not None:
                    stack.append(c)
        if probe_scan is None:
            return None
        joins = []
        for j in cands:
            b = j.right
            pkeys = tuple(probe_scan.columns.get(k) for k in j.left_keys)
            bkeys = tuple(b.columns.get(k) for k in j.right_keys)
            if None in pkeys or None in bkeys:
                continue  # a computed/remapped key: raw partitioning
                # would not match the device's comparison space
            if not all(self._raw_partitionable(t, ks) for t, ks in
                       ((tname, pkeys), (b.table, bkeys))):
                continue
            btd = self.store.table(b.table)
            if btd.row_count == 0:
                continue
            bb = self._table_device_bytes(btd,
                                          scan_cols.get(b.alias))
            joins.append((bb, j, b, pkeys, bkeys))
        if not joins:
            return None
        n_aggs = _count_aggs(node)
        page_padded = self._row_bucket(page_rows)
        temp_bytes = 2 * 16 * n_aggs * page_padded
        page_bytes = 2 * self._page_device_bytes(
            ptd, scan_cols.get(alias), page_rows)  # depth-2 prefetch
        # builds charge what they will actually upload (the scans loop
        # prunes zone-failing chunks from over-budget builds), so a
        # selective build no longer forces the spill tier
        build_total = sum(
            self._effective_table_bytes(node, a, t, scan_cols.get(a))[0]
            for a, t in scan_aliases.items() if a != alias)
        if (mode == "auto"
                and build_total + temp_bytes + page_bytes <= budget):
            return None
        des_bytes, j, b, pkeys, bkeys = max(joins, key=lambda x: x[0])
        # des_bytes is the FULL build (partitions gather every build
        # row); build_total is effective, so clamp the residual
        avail = max(budget - max(build_total - des_bytes, 0)
                    - temp_bytes - page_bytes, 1)
        nparts = 2
        while (nparts < self.MAX_SPILL_PARTITIONS
               and des_bytes // nparts > avail):
            nparts *= 2
        return SpillPlan(kind="join", alias=alias, table=tname,
                         page_rows=page_rows, build_alias=b.alias,
                         build_table=b.table, probe_keys=pkeys,
                         build_keys=bkeys, nparts=nparts)

    def _raw_partitionable(self, tname: str, stored_keys) -> bool:
        """May the spill partitioner hash these stored columns raw?
        Int-family only (incl. bool); STRING dictionary codes and
        FLOAT (-0.0 == 0.0 with different bits) partition wrong."""
        from ..sql.types import Family
        td = self.store.table(tname)
        by_name = {c.name: c for c in td.schema.columns}
        for k in stored_keys:
            col = by_name.get(k)
            if col is None or col.type.family == Family.STRING:
                return False
            if np.dtype(col.type.np_dtype).kind not in "iub":
                return False
        return True

    def _spill_sort_decision(self, node, scan_aliases: dict,
                             scan_cols: dict, meta, mode: str,
                             budget: int, page_rows: int):
        """External-merge-sort eligibility + trigger: Limit?/Sort over
        a join-free single-scan spine (can_spill_sort) whose every
        key is normalized-encodable — the uint64 lanes double as the
        device run keys AND the host merge keys, so the merged order
        is byte-for-byte the device's. Auto triggers when the pruned
        resident upload + sort temporaries (perm + lane per row)
        would blow the budget."""
        from .spill import SpillPlan
        if not can_spill_sort(node) or len(scan_aliases) != 1:
            return None
        from ..sql.types import Family
        alias, tname = next(iter(scan_aliases.items()))
        td = self.store.table(tname)
        if td.row_count == 0:
            return None
        limit_node = None
        n = node
        if isinstance(n, P.Limit):
            limit_node, n = n, n.child
        sort_node = n
        names = list(meta.names)
        for key in sort_node.keys:
            kn = key[0]
            if kn not in names:
                return None  # hidden key: type unknowable here
            fam = meta.types[names.index(kn)].family
            if fam == Family.STRING:
                if meta.dictionaries.get(kn) is None:
                    return None  # no rank table -> unencodable
            elif fam not in (Family.INT, Family.DECIMAL, Family.DATE,
                             Family.TIMESTAMP, Family.BOOL,
                             Family.FLOAT):
                return None
        cols = scan_cols.get(alias)
        if mode == "auto":
            eff_bytes, eff_rows = self._effective_table_bytes(
                node, alias, tname, cols,
                narrow=self.narrow32_cols(tname, cols))
            if eff_bytes + 24 * self._row_bucket(eff_rows) <= budget:
                return None
        return SpillPlan(
            kind="sort", alias=alias, table=tname, page_rows=page_rows,
            sort_keys=tuple(
                (k[0], bool(k[1]), (k[2] if len(k) > 2 else None))
                for k in sort_node.keys),
            limit=(limit_node.limit
                   if limit_node is not None
                   and limit_node.limit is not None else -1),
            offset=((limit_node.offset or 0)
                    if limit_node is not None else 0))

    def _page_device_bytes(self, td, cols, page_rows: int) -> int:
        """Device bytes of one streamed page of this table's pruned
        column set (PageSource.page_bytes, computed pre-source)."""
        total = 16 * page_rows
        for col in td.schema.columns:
            if cols is not None and col.name not in cols:
                continue
            w = np.dtype(col.type.np_dtype).itemsize
            total += (w + 1) * page_rows
        return total

    def stream_verdict(self, sql: str, session: Session | None = None
                       ) -> str:
        """Which placement tier would this SELECT execute on?
        "distributed" | "spill-join" | "spill-sort" | "stream-scan" |
        "resident" — the planner's four-way verdict plus the mesh
        plane, exposed for eligibility tests and EXPLAIN-style
        introspection (no execution, no uploads)."""
        session = session or self.session()
        stmt = self._parse_cached(sql)
        node, meta = self._plan(stmt, session)
        from .stmtutil import _collect_scan_columns
        scan_aliases = _collect_scans(node)
        scan_cols = _collect_scan_columns(node)
        if self._dist_decision(node, session) is not None:
            return "distributed"
        sp = self._spill_decision(node, scan_aliases, scan_cols,
                                  session, meta)
        if sp is not None:
            return f"spill-{sp.kind}"
        if self._stream_decision(node, scan_aliases, scan_cols,
                                 session) is not None:
            return "stream-scan"
        return "resident"

    def _table_device_bytes(self, td, cols,
                            narrow: frozenset = frozenset()) -> int:
        """Device bytes a pruned upload of this table would take.
        Columns in ``narrow`` upload as int32 (narrow32_cols), so they
        charge 4+1 bytes per row, not the stored 8+1."""
        n = td.row_count
        padded = self._row_bucket(n)
        total = 16 * padded  # the two MVCC int64 columns
        for col in td.schema.columns:
            if cols is not None and col.name not in cols:
                continue
            w = (4 if col.name in narrow
                 else np.dtype(col.type.np_dtype).itemsize)
            total += (w + 1) * padded
        return total

    def _chunks_device_bytes(self, td, chunks, cols,
                             narrow: frozenset = frozenset()) -> int:
        """_table_device_bytes over a chunk subset (+ any open rows)."""
        n = sum(c.n for c in chunks) + len(td.open_ts)
        padded = self._row_bucket(n)
        total = 16 * padded
        for col in td.schema.columns:
            if cols is not None and col.name not in cols:
                continue
            w = (4 if col.name in narrow
                 else np.dtype(col.type.np_dtype).itemsize)
            total += (w + 1) * padded
        return total

    def _zone_surviving_chunks(self, node, alias, tname):
        """(surviving chunks, compiled preds) for the plan's pushed-
        down predicates over `alias`, judged against seal-time zones
        and blooms — the same per-chunk verdict the streamed page
        source renders, evaluated once at decision/upload time. Empty
        preds means nothing was zone-judgeable (keep == all chunks)."""
        from .stream import extract_zone_preds
        td = self.store.table(tname)
        preds = extract_zone_preds(node, alias)
        if not preds:
            return list(td.chunks), ()
        keep = []
        for c in td.chunks:
            ok = True
            for p in preds:
                if p.col is None:
                    if not p.check(None, None, 0, 0):
                        ok = False
                        break
                    continue
                lo, hi, nulls, nvalid = c.zone(p.col)
                if not p.check(lo, hi, nulls, nvalid):
                    ok = False
                    break
                if p.member is not None \
                        and not p.member.chunk_ok(c, p.col):
                    ok = False
                    break
            if ok:
                keep.append(c)
        return keep, preds

    def _effective_table_bytes(self, node, alias, tname, cols,
                               narrow: frozenset = frozenset()
                               ) -> tuple[int, int]:
        """(device bytes, rows) the upload of this scan will ACTUALLY
        take: the whole table when it fits the budget (the cached
        resident path), else the zone-surviving chunk subset — exactly
        what _maybe_pruned_upload ships. Sizing the stream/spill
        verdicts from this instead of the declared table keeps
        selective scans from escalating to paging/spill when their
        post-filter working set fits."""
        td = self.store.table(tname)
        full = self._table_device_bytes(td, cols, narrow=narrow)
        budget = int(self.settings.get("sql.exec.hbm_budget_bytes"))
        if budget <= 0 or full <= budget:
            return full, td.row_count
        keep, preds = self._zone_surviving_chunks(node, alias, tname)
        if not preds or len(keep) == len(td.chunks):
            return full, td.row_count
        rows = sum(c.n for c in keep) + len(td.open_ts)
        return (self._chunks_device_bytes(td, keep, cols,
                                          narrow=narrow), rows)

    def _maybe_pruned_upload(self, node, alias, tname, cols,
                             do_narrow: bool):
        """UNCACHED upload of only the zone-surviving chunks, used
        when the whole table would blow the HBM budget but the scan's
        pushed-down predicates prune chunks host-side — the resident
        analogue of streamed page skipping, with the same correctness
        contract (a dropped chunk's rows fail the predicate for every
        row version, so the device filter would drop them anyway).
        None -> caller keeps the cached whole-table path."""
        budget = int(self.settings.get("sql.exec.hbm_budget_bytes"))
        if budget <= 0:
            return None
        td = self.store.table(tname)
        narrow = (self.narrow32_cols(tname, cols) if do_narrow
                  else frozenset())
        if self._table_device_bytes(td, cols, narrow=narrow) <= budget:
            return None
        if td.open_ts:
            self.store.seal(tname)
        keep, preds = self._zone_surviving_chunks(node, alias, tname)
        if not preds or len(keep) == len(td.chunks):
            return None
        row_w = 16 + sum(
            np.dtype(c.type.np_dtype).itemsize + 1
            for c in td.schema.columns
            if cols is None or c.name in cols)
        dropped_rows = sum(c.n for c in td.chunks) \
            - sum(c.n for c in keep)
        self.metrics.counter(
            "exec.skip.predicate.chunks",
            "over-budget resident scan chunks pruned host-side by "
            "pushed-down zone predicates").inc(
                len(td.chunks) - len(keep))
        self.metrics.counter(
            "exec.skip.predicate.bytes",
            "host->device bytes avoided by predicate chunk pruning"
        ).inc(row_w * dropped_rows)
        return self._batch_from_chunks(td, keep, cols, narrow=narrow)

    def _scan_survival_frac(self, node, alias, tname) -> float:
        """Estimated post-filter fraction of a scan's rows: sketch-
        stats selectivity of its pushed-down predicates (scan filter
        plus Filter nodes separated only by Filter/Compact, the
        extract_zone_preds discipline). 1.0 when nothing is judgeable;
        floored at 1/64 so footprint heuristics never size to zero."""
        from ..sql import stats as S
        from .stream import _find_chain
        td = self.store.table(tname)
        if td.row_count == 0:
            return 1.0
        try:
            st = self.store.sketch_stats(tname)
        except Exception:
            return 1.0
        chain = _find_chain(node, alias)
        if chain is None:
            return 1.0
        sel = 1.0
        scan = chain[0]
        if scan.filter is not None:
            sel *= S._pred_selectivity(scan.filter, st)
        for anc in chain[1:]:
            if isinstance(anc, P.Compact):
                continue
            if isinstance(anc, P.Filter):
                if anc.pred is not None:
                    sel *= S._pred_selectivity(anc.pred, st)
                continue
            break
        return float(min(1.0, max(sel, 1.0 / 64.0)))

    def _page_source(self, tname: str, cols, page_rows: int,
                     zone_preds=(), read_ts=None) -> PageSource:
        """One-time per-execution setup for streamed paging: seal open
        rows ONCE here (not per page), snapshot the chunk list, and
        hand the prefix-offset assembler its zone predicates plus the
        read timestamp (chunk MVCC-window skipping)."""
        td = self.store.table(tname)
        if td.open_ts:
            self.store.seal(tname)
        return PageSource(td, cols, page_rows, zone_preds=zone_preds,
                          metrics=self.metrics, read_ts=read_ts)

    def _stream_pages(self, tname: str, cols, page_rows: int,
                      zone_preds=(), pipeline: bool = True,
                      read_ts=None):
        """Iterator of fixed-shape device pages of a table's chunks,
        padded to page_rows with never-visible rows so one XLA program
        serves every page. With ``pipeline``, a bounded background
        worker assembles+uploads page i+1 while the caller's device
        work on page i runs; zone-pruned pages never leave the host."""
        src = self._page_source(tname, cols, page_rows, zone_preds,
                                read_ts=read_ts)
        if not pipeline:
            it = src.pages()
        else:
            it = stream_prefetch(
                src.pages(),
                stall_hist=self.metrics.histogram(
                    "exec.stream.prefetch_stall_seconds",
                    "consumer wait per streamed page (0 when the "
                    "prefetch pipeline is ahead of the device)"))
        metered = self._metered_pages(it, tname, src.page_bytes,
                                      stalls=pipeline)
        # the stream's transient working window (the page computing +
        # the one the prefetch worker holds) charges the unified
        # movement budget for its lifetime — best-effort, so a tight
        # budget degrades to observable overcommit, never a failure
        window = (2 if pipeline else 1) * src.page_bytes

        def leased():
            with self.movement.soft_lease("page", window):
                yield from metered
        return leased()

    @staticmethod
    def _metered_pages(it, tname: str, page_bytes: int,
                       stalls: bool = False):
        """Statement-profile metering wrapper around a page iterator:
        runs on the CONSUMER thread (where the statement's thread-local
        sink lives — the prefetch worker would miss it). With a
        pipeline upstream the wait for ``next`` is consumer stall; the
        synchronous path's wait is assembly+upload work, not stall."""
        inner = iter(it)
        label = f"stream:{tname}"
        try:
            while True:
                t0 = _time.monotonic()
                try:
                    b = next(inner)
                except StopIteration:
                    return
                sink = _prof.current()
                if sink is not None:
                    sink.note(label, batches=1, rows=int(b.n),
                              bytes_uploaded=page_bytes,
                              stall_seconds=((_time.monotonic() - t0)
                                             if stalls else 0.0))
                yield b
        finally:
            close = getattr(inner, "close", None)
            if close is not None:
                close()

    def _filtered_scan_batch(self, tname: str, filters, read_ts):
        """Remote-side application of gateway-shipped join-filter
        frames (distsql/node.py): drop whole chunks whose key set
        cannot match before anything serializes or uploads. Returns
        None when nothing prunes (the caller keeps its cached
        device-table path); otherwise an UNCACHED wide upload of the
        surviving chunks — correctness is untouched because a dropped
        chunk's rows would have been dropped by the inner/semi join
        (or by MVCC) on device anyway."""
        td = self.store.table(tname)
        if td.open_ts:
            self.store.seal(tname)
        row_w = 16 + sum(
            np.dtype(c.type.np_dtype).itemsize + 1
            for c in td.schema.columns)
        keep, dropped, dropped_bytes = [], 0, 0
        for c in td.chunks:
            ok = True
            if read_ts is not None:
                ts_min, del_max = c.mvcc_window()
                ok = ts_min <= read_ts < del_max
            if ok:
                ok = all(f.chunk_ok(c, f.col) for f in filters)
            if ok:
                keep.append(c)
            else:
                dropped += 1
                dropped_bytes += row_w * c.n
        if dropped == 0:
            return None
        self.metrics.counter(
            "exec.skip.joinfilter.chunks",
            "remote scan chunks pruned host-side by a gateway-shipped "
            "join-filter frame (DistSQL)").inc(dropped)
        self.metrics.counter(
            "exec.skip.joinfilter.bytes",
            "host->device bytes avoided by join-induced skipping"
        ).inc(dropped_bytes)
        return self._batch_from_chunks(td, keep)

    # -- device table cache --------------------------------------------------
    def _evict_device(self, key) -> None:
        with self._device_lock:
            self._device_tables.pop(key, None)
            self.movement.release_resident(key)

    def drop_device_cache(self) -> None:
        """Evict every resident table upload AND release its memory
        reservation (a raw _device_tables.clear() would leak the
        monitor's accounting)."""
        for k in list(self._device_tables):
            self._evict_device(k)

    def _device_table(self, name: str, placement: str = "single",
                      cols: frozenset | None = None,
                      narrow: bool = True, mesh=None) -> ColumnBatch:
        """Resident device copy of ``name`` — cached, or uploaded now.

        The cache lock guards only dict state. The expensive part
        (host assembly + jax.device_put, tens of ms for a large
        table) runs OUTSIDE ``_device_lock`` behind a per-(table,
        placement) in-flight event, so concurrent statements needing
        OTHER tables — or a cached hit on this one — never convoy
        behind a PCIe transfer, and two statements needing the SAME
        cold table produce one upload, not two."""
        # the target mesh is part of the upload's identity: sub-mesh
        # dispatch (parallel/mesh.py MeshPool) shards/replicates the
        # same table over different device subsets, and a batch placed
        # on sub-mesh A must never serve a program compiled for B
        if placement == "single":
            mesh, devids = None, ()
        else:
            mesh = mesh if mesh is not None else self.mesh
            devids = tuple(int(d.id) for d in mesh.devices.flat)
        flight = (name, placement, devids, narrow)
        while True:
            with self._device_lock:
                td = self.store.table(name)
                hit = self._device_lookup_locked(
                    name, td.generation, placement, devids, narrow,
                    cols)
                if hit is not None:
                    return hit
                ev = self._device_inflight.get(flight)
                if ev is None:
                    ev = threading.Event()
                    self._device_inflight[flight] = ev
                    break  # this thread owns the upload
            # another thread is uploading this table: wait without the
            # lock, then retry the lookup (the timeout only bounds the
            # re-check; a failed owner clears the event in its finally
            # and the retrier becomes the new owner)
            ev.wait(timeout=5.0)
        try:
            return self._device_upload(name, td, placement, cols,
                                       narrow, mesh, devids)
        finally:
            with self._device_lock:
                self._device_inflight.pop(flight, None)
            ev.set()

    def _device_lookup_locked(self, name: str, generation,
                              placement: str, devids: tuple,
                              narrow: bool,
                              cols: frozenset | None):
        """Cache probe; caller holds ``_device_lock``. A cached upload
        with a SUPERSET of the needed columns serves this scan
        directly (scans read columns by name); this keeps one resident
        copy per table instead of one per column set. The narrow flag
        is part of the identity: a wide consumer (DistSQL workers
        compile without the upcast) must never be served an
        int32-narrowed upload."""
        for k, v in self._device_tables.items():
            if (k[0] == name and k[1] == generation
                    and k[2] == placement and k[4] == narrow
                    and k[5] == devids
                    and (k[3] is None
                         or (cols is not None and cols <= k[3]))):
                return v
        return None

    def _device_upload(self, name: str, td, placement: str,
                       cols: frozenset | None, narrow: bool, mesh,
                       devids: tuple) -> ColumnBatch:
        """Assemble and upload one resident table copy. Runs with NO
        lock held (graftlint blocking-under-lock: the original
        held ``_device_lock`` across seal + host assembly +
        jax.device_put, serializing every concurrent scan behind one
        upload); only the final cache insert re-takes the lock."""
        # evict stale generations of this table
        with self._device_lock:
            stale = [k for k in self._device_tables if k[0] == name
                     and k[1] != td.generation]
        for k in stale:
            self._evict_device(k)
        if td.open_ts:
            self.store.seal(name)
        key = (name, td.generation, placement, cols, narrow, devids)
        # account BEFORE upload; replication costs a copy per device.
        # The reservation uses the same narrow set the upload will,
        # so narrowed tables no longer reserve ~2x their real bytes
        narrow_set = (self.narrow32_cols(name, cols) if narrow
                      else frozenset())
        nbytes = self._table_device_bytes(td, cols, narrow=narrow_set)
        if placement == "replicated" and mesh is not None:
            nbytes *= mesh.size
        if placement != "single" and mesh is not None:
            from ..parallel import multihost
            if multihost.num_hosts() > 1:
                # resident uploads are strictly host-local on a pod:
                # device_put of host arrays cannot address another
                # process's devices, and silently trying yields an XLA
                # crash deep in the upload. The cross-host dimension
                # of a scan is the distsql merge tree's job (each host
                # owns its shard), never a cross-DCN placement here.
                local = set(jax.local_devices())
                if any(d not in local for d in mesh.devices.flat):
                    raise EngineError(
                        f"table {name!r}: resident upload targets a "
                        "mesh with non-addressable (remote-host) "
                        "devices; use the host-local mesh "
                        "(parallel.mesh.pod_mesh degrades to it)")
        self.movement.reserve_resident(key, nbytes)
        try:
            b = self._batch_from_chunks(td, td.chunks, cols,
                                        narrow=narrow_set)
            if placement == "sharded":
                b = jax.device_put(b, meshmod.row_sharding(mesh))
            elif placement == "replicated":
                b = jax.device_put(b, meshmod.replicated(mesh))
        except BaseException:
            self.movement.release_resident(key)
            raise
        # drop now-redundant strict-subset uploads of the same table
        with self._device_lock:
            subsets = [k for k in self._device_tables
                       if k[0] == name and k[1] == td.generation
                       and k[2] == placement and k[5] == devids
                       and k[3] is not None
                       and (cols is None or k[3] < cols)]
        for k in subsets:
            self._evict_device(k)
        with self._device_lock:
            self._device_tables[key] = b
        self.metrics.counter("sql.device.table_uploads",
                             "resident table uploads to HBM").inc()
        self.metrics.counter(
            "sql.device.upload.bytes",
            "host->device bytes moved by table uploads").inc(nbytes)
        _prof.note(f"upload:{name}", batches=1, rows=td.row_count,
                   bytes_uploaded=nbytes)
        return b

    def narrow32_cols(self, name: str,
                      cols: frozenset | None = None) -> frozenset:
        """Stored int64 columns of `name` whose ALL-VERSIONS value
        range fits int32 (generation-cached store probe): these upload
        to HBM as int32 and the compiled scan upcasts them back —
        identical program semantics, half the HBM bytes, and none of
        the software-emulated int64 limb ops on the first touch
        (int64 is emulated on TPU; Q6's scan measured ~2x from this).
        NULL lanes may wrap when narrowed — they are masked by
        validity everywhere downstream, same as any garbage lane."""
        from ..sql.types import Family
        td = self.store.table(name)
        out = set()
        for col in td.schema.columns:
            cn = col.name
            if cols is not None and cn not in cols:
                continue
            if col.type.family not in (Family.INT, Family.DECIMAL,
                                       Family.DATE, Family.TIMESTAMP):
                continue
            if np.dtype(col.type.np_dtype) != np.dtype(np.int64):
                continue
            try:
                r = self.store.key_int_range(name, cn)
            except (KeyError, TypeError):
                continue
            if r is None:
                continue
            lo, hi, _n = r
            if -(2 ** 31) < lo and hi < 2 ** 31 - 1:
                out.add(cn)
        return frozenset(out)

    def _batch_from_chunks(self, td, chunks: list,
                           prune: frozenset | None = None,
                           narrow: frozenset = frozenset()
                           ) -> ColumnBatch:
        """Concatenate chunks, pad to a power-of-two row bucket, and
        upload as a device-resident ColumnBatch with MVCC columns.
        With ``prune`` set, only those stored columns upload (the scan
        projection; HBM is the scarce resource the reference's
        needed-columns fetch logic protects, cfetcher.go:668).
        Columns in ``narrow`` upload as int32 (see narrow32_cols)."""
        cols: dict[str, np.ndarray] = {}
        valid: dict[str, np.ndarray] = {}
        n = sum(c.n for c in chunks)
        padded = self._row_bucket(n)
        for col in td.schema.columns:
            cn = col.name
            if prune is not None and cn not in prune:
                continue
            parts = [c.data[cn] for c in chunks]
            arr = (np.concatenate(parts) if parts
                   else np.zeros(0, dtype=col.type.np_dtype))
            if cn in narrow:
                arr = arr.astype(np.int32)
            vparts = [c.valid[cn] for c in chunks]
            va = np.concatenate(vparts) if vparts else np.zeros(0, bool)
            cols[cn] = _pad(arr, padded)
            if not va.all():
                # all-valid masks regenerate on device (ones) for free
                # instead of paying PCIe for a constant
                valid[cn] = _pad(va, padded)
        ts_parts = [c.mvcc_ts for c in chunks]
        del_parts = [c.mvcc_del for c in chunks]
        mts = np.concatenate(ts_parts) if ts_parts else np.zeros(0, np.int64)
        mdl = (np.concatenate(del_parts) if del_parts
               else np.zeros(0, np.int64))
        # padding rows are never visible: created at +inf
        cols["_mvcc_ts"] = _pad(mts, padded, fill=np.int64(2**62))
        cols["_mvcc_del"] = _pad(mdl, padded, fill=np.int64(0))
        # graftlint: waive[no-aliasing-upload] cols/valid hold fresh
        # np.concatenate/_pad outputs built above; no later writes
        return ColumnBatch.from_dict(
            {k: jnp.asarray(v) for k, v in cols.items()},
            {k: jnp.asarray(v) for k, v in valid.items()})

    def _overlay_batch(self, name: str, effects: list,
                       read_ts: Timestamp) -> ColumnBatch:
        """Uncached device snapshot of committed chunks + this txn's
        buffered effects (read-your-own-writes)."""
        td = self.store.table(name)
        chunks = self._overlay_chunks(name, effects, read_ts)
        return self._batch_from_chunks(td, chunks)

    # -- result materialization ---------------------------------------------

    _SENTINELS = _SENTINEL_PAIRS

    def _materialize(self, out: ColumnBatch, meta: P.OutputMeta) -> Result:
        """Decode a device result batch into host rows.

        Transfer discipline (the whole game on a remote-attached TPU,
        ~60-90ms RTT per transfer): sentinel flags reduce to scalars on
        device and ride the same packed pull as the data — one
        transfer for small batches; for wide (join-expanded) batches,
        one pull for (sel + flags), then one pull of the live rows
        gathered on device."""
        from ..ops.batch import _SMALL_PULL, pull_arrays, \
            pull_batch_columns
        sent = [(n, exc) for n, exc in self._SENTINELS if out.has(n)]
        flags_dev = [jnp.any(out.col(n)) for n, _ in sent]
        names = list(meta.names)
        if out.n <= _SMALL_PULL:
            pulled, flags = pull_batch_columns(out, names,
                                               extra=flags_dev)
            self._raise_sentinels(sent, flags)
        else:
            # sentinel flags ride the sel pull so an overflow raises
            # BEFORE the (possibly garbage-width) live gather runs
            first = pull_arrays([out.sel] + flags_dev)
            self._raise_sentinels(sent, first[1:])
            pulled, _ = pull_batch_columns(out, names,
                                           sel_np=first[0])
        host = {c: np.ma.masked_array(d, mask=~v)
                for c, (d, v) in pulled.items()}
        res = Result(names=names, types=list(meta.types))
        cols = []
        for name, ty in zip(names, meta.types):
            arr = host[name]
            d = meta.dictionaries.get(name)
            cols.append(_decode_column(arr, ty, d))
        res.rows = list(zip(*cols)) if cols else []
        return res

    @staticmethod
    def _raise_sentinels(sent, flags) -> None:
        for (name, exc), f in zip(sent, flags):
            if bool(f):
                raise exc()

