"""DML through the transactional KV plane: INSERT/UPSERT, DELETE, UPDATE
with intents, overlay chunks, and effect publication (pkg/sql/opt_exec_factory.go insert/update/delete nodes; txn effects
buffer like the reference's txn write buffer).

Split out of exec/engine.py (round-2 VERDICT Weak #4); see that
module's docstring for the overall execution model."""


import datetime
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..kv.concurrency import (Span, TxnAbortedError, TxnRetryError)
from ..kv.txn import DB as KVDB
from ..kv.txn import Txn
from ..sql import ast
from ..sql.binder import Binder, ColumnBinding, Scope
from ..sql.bound import BConst
from ..sql.rowenc import ROWID
from ..sql.types import Family, TableSchema
from ..storage.columnstore import Chunk, MAX_TS_INT
from ..storage.hlc import Timestamp
from .expr import ExprContext, compile_expr

EPOCH_DATE = datetime.date(1970, 1, 1)
EPOCH_DT = datetime.datetime(1970, 1, 1)

from .session import EngineError, Result, Session
from .stmtutil import _contains_func, _stmt_table_refs


def retry_exhausted(last: Exception | None) -> EngineError:
    """The serialization-failure error after the DML retry budget.
    Still the retryable class — pgwire maps the "restart transaction"
    phrasing to SQLSTATE 40001. Single source for every autocommit
    retry loop (the full DML path here, the OLTP lane's per-statement
    writes, and its fused batch-window rounds), so a client's retry
    matcher sees one phrasing regardless of which path a statement
    took."""
    return EngineError(
        f"restart transaction: DML exhausted retries: {last}")


class DMLMixin:
    """Engine methods for this concern; mixed into exec.engine.Engine
    (all state lives on the Engine instance)."""

    # -- DML (through the transactional KV plane) ----------------------------
    # Every DML statement writes row intents through kv.Txn (latches,
    # tscache floors, pushes, read refresh — the TxnCoordSender stack)
    # and records scan-plane effects that are published into the
    # columnstore only at the commit timestamp. Mirrors the reference's
    # write path: sql/row writers -> kv.Txn -> intents, resolved at
    # commit (pkg/kv/db.go:896, pkg/sql/row/writer.go).

    def _dml(self, session: Session, fn) -> Result:
        """Run fn(txn, effects)->Result in the session's open txn, or
        in a fresh auto-commit txn with the kv retry loop."""
        if session.txn is not None:
            # a failed statement aborts the whole explicit txn: its
            # partial intents are resolved away and nothing publishes.
            # This is how statement atomicity holds without kv-level
            # savepoints (pg's "aborted until end of txn block").
            try:
                return fn(session.txn, session.effects)
            except (TxnRetryError, TxnAbortedError) as e:
                session.txn_aborted = True
                session.txn.rollback()
                raise EngineError(f"restart transaction: {e}") from e
            except BaseException:
                session.txn_aborted = True
                session.txn.rollback()
                raise
        last: Exception | None = None
        for _ in range(KVDB.MAX_ATTEMPTS):
            t = Txn(self.kv.store)
            effects: list = []
            try:
                res = fn(t, effects)
                toks = {}
                if self.cluster is not None and effects:
                    toks = self._bump_table_gens(
                        t, sorted({tb for tb, _ in effects}))
                commit_ts = t.commit()
                self._publish(effects, commit_ts)
                self._scan_gens.update(toks)
                return res
            except (TxnRetryError, TxnAbortedError) as e:
                t.rollback()
                last = e
            except BaseException:
                t.rollback()
                raise
        raise retry_exhausted(last)

    # -- range-plane scan-plane sync ----------------------------------------
    # With a Cluster attached, the columnstore is a materialization of
    # committed range data. Every DML txn bumps an opaque generation
    # token at /tgen/<table> inside the SAME txn as its row intents;
    # engines compare the replicated token against the one their local
    # materialization was built from and re-fetch when they differ
    # (the reference gets equivalent coherence from leaseholder reads;
    # our scan plane is a cache, so it carries its own epoch).

    TGEN_PREFIX = b"/tgen/"

    def _bump_table_gens(self, t: Txn, tables: list) -> dict:
        import uuid
        toks = {}
        for tb in tables:
            toks[tb] = uuid.uuid4().hex[:16].encode()
            t.put(self.TGEN_PREFIX + tb.encode(), toks[tb])
        return toks

    def _bump_tgen_ddl(self, name: str, dropped: bool = False) -> None:
        """Schema-affecting DDL (DROP/TRUNCATE/ALTER) invalidates other
        gateways' materializations through the same token."""
        if self.cluster is None:
            return
        import uuid
        tok = b"ddl-" + uuid.uuid4().hex[:12].encode()
        self.kv.put(self.TGEN_PREFIX + name.encode(), tok)
        if dropped:
            self._scan_gens.pop(name, None)
        else:
            self._scan_gens[name] = tok

    def _sync_scan_plane(self, stmt) -> None:
        """Before executing a statement on a cluster-backed engine,
        make sure every referenced table's columnstore materialization
        matches the replicated generation token."""
        refs = set(_stmt_table_refs(stmt))
        tb = getattr(stmt, "table", None)
        if isinstance(tb, str):
            refs.add(tb)
        seen = set()
        while refs:
            name = refs.pop()
            if name in seen:
                continue
            seen.add(name)
            if name in self.store.tables:
                gen = self.kv.get(self.TGEN_PREFIX + name.encode())
                if gen == self._scan_gens.get(name):
                    continue
                self.refresh_table_from_ranges(name)
                continue
            desc = self.catalog.get_by_name(name)
            if desc is None:
                continue  # CTE alias / unknown: the binder will say so
            if desc.view_sql:
                from ..sql import parser as _p
                refs |= set(_stmt_table_refs(_p.parse(desc.view_sql)))
                continue
            self.refresh_table_from_ranges(name)

    def refresh_table_from_ranges(self, name: str) -> bool:
        """(Re)build one table's columnstore from committed range data
        (the cFetcher materialization path, kv/rowfetch.py promoted
        into the engine per round-3 VERDICT #1).

        The rebuild is version-faithful: every committed MVCC version
        becomes a columnstore row with its true (mvcc_ts, mvcc_del)
        interval, so open snapshots on this gateway and AS OF SYSTEM
        TIME keep reading correct history after a refresh triggered by
        another gateway's writes. Unresolved intents are skipped (the
        pebbleMVCCScanner contract: the scan plane only ever sees
        resolved committed versions)."""
        desc = self.catalog.get_by_name(name)
        if desc is None or desc.view_sql:
            if desc is None and name in self.store.tables:
                # dropped on another gateway: retire the local cache
                self.store.drop_table(name)
                self._evict(name)
                self._scan_gens.pop(name, None)
            return False
        from ..sql.rowenc import RowCodec
        from ..storage.keys import EngineKey
        from ..storage.mvcc import TxnMeta, _dec_value
        schema = desc.public_schema()
        codec = RowCodec(schema)
        start, end = codec.span()
        gen = self.kv.get(self.TGEN_PREFIX + name.encode())

        # committed versions per key from every range overlapping the
        # table span (raw engine iteration: tombstones and history too)
        per_key: dict[bytes, list] = {}
        store = self.kv.store
        range_iter = getattr(store.mvcc, "_ranges_overlapping", None)
        if range_iter is None:   # local single-store plane
            sources = [(start, end, store.mvcc)]
        else:
            sources = [(max(start, d.start_key), min(end, d.end_key),
                        rep.mvcc)
                       for d, rep in range_iter(start, end)]
        for lo, hi, mvcc in sources:
            # one shared implementation of the committed-version
            # extraction (storage/mvcc.py committed_versions) serves
            # the local plane, cluster-local replicas, and — via the
            # replica-side RPC — remote leaseholders alike
            for key, tsi, val in mvcc.committed_versions(lo, hi):
                per_key.setdefault(key, []).append((tsi, val))
        versions: list[tuple[dict, int, int]] = []
        for key, vers in per_key.items():
            vers.sort()
            for i, (tsi, val) in enumerate(vers):
                if val is None:
                    continue   # MVCC delete: bounds the prior version
                del_i = vers[i + 1][0] if i + 1 < len(vers) \
                    else MAX_TS_INT
                versions.append((codec.decode_row(key, val), tsi, del_i))

        if name in self.store.tables:
            self.store.drop_table(name)
            self._evict(name)
        self.store.create_table(schema)
        self.store.insert_versions(name, versions)
        self._scan_gens[name] = gen
        self._index_defs.pop(name, None)
        self._constraint_defs.pop(name, None)
        self._fk_children = None
        return True

    def _publish(self, effects: list, ts: Timestamp) -> None:
        if not effects:
            return
        by_table: dict[str, list] = {}
        order: list[str] = []
        for table, op in effects:
            if table not in by_table:
                by_table[table] = []
                order.append(table)
            by_table[table].append(op)
        for table in order:
            self.store.apply_committed(table, by_table[table], ts)
            self._evict(table)
            for feed in self.cdc_feeds:
                if feed.table == table:
                    feed.on_publish(by_table[table], ts)

    def _register_table_read(self, txn: Optional[Txn], table: str,
                             read_ts: Timestamp) -> None:
        """Record a scan-plane read in the KV concurrency plane: the
        table span goes into the txn's refresh set and the timestamp
        cache, so conflicting writers get pushed above our read — the
        contract of Replica.Send read path + span refresher."""
        codec = self.store.table(table).codec
        start, end = codec.span()
        span = Span(start, end)
        self.kv.store.tscache.add(span, read_ts,
                                  txn.meta.id if txn else None)
        if txn is not None:
            txn.read_spans.append(span)

    def _txn_key_state(self, effects: list, table: str) -> dict:
        """Net per-key state of buffered effects for one table:
        key -> row dict (pending put) or None (pending delete)."""
        state: dict[bytes, object] = {}
        for tb, op in effects:
            if tb != table:
                continue
            if op[0] == "put":
                state[op[1]] = op[2]
            else:
                state[op[1]] = None
        return state

    def _overlay_chunks(self, table: str, effects: list,
                        read_ts: Timestamp) -> list[Chunk]:
        """Committed chunks with this txn's buffered effects applied:
        pending deletes/overwrites tombstone the committed version
        (copy-on-write of the deletion column), pending puts appear as
        a delta chunk visible at the txn's read timestamp. This is the
        read-your-own-writes overlay; the reference gets the same from
        MVCC intents being visible to their own txn."""
        td = self.store.table(table)
        state = self._txn_key_state(effects, table)
        if not state:
            self.store.seal(table)
            return list(td.chunks)
        idx = self.store.ensure_pk_index(table)
        rts = read_ts.to_int()
        shadow: dict[int, np.ndarray] = {}   # chunk idx -> COW mvcc_del

        def _tombstone(ci: int, ri: int):
            if ci not in shadow:
                shadow[ci] = td.chunks[ci].mvcc_del.copy()
            shadow[ci][ri] = rts   # hidden from this txn's reads
        for key in state:
            pos = idx.get(key)
            if pos is None:
                continue
            ci, ri = pos
            if td.chunks[ci].mvcc_ts[ri] > rts:
                # live version is newer than our snapshot (a concurrent
                # txn superseded the key after our read_ts): it is
                # already invisible at rts; the version we must hide is
                # found by the superseded-after-rts sweep below
                continue
            _tombstone(ci, ri)
        # Versions visible at rts but superseded/deleted after it are
        # NOT in the live pk index, yet they are exactly what a pending
        # write must shadow (otherwise the old version + our delta row
        # would both surface). They satisfy rts < mvcc_del < MAX — a
        # small candidate set (recent MVCC garbage) we key-match.
        for ci, c in enumerate(td.chunks):
            cand = np.nonzero((c.mvcc_ts <= rts) & (rts < c.mvcc_del)
                              & (c.mvcc_del != MAX_TS_INT))[0]
            for ri in cand:
                if self.store.row_key(td, c, int(ri)) in state:
                    _tombstone(ci, int(ri))
        chunks = []
        for ci, c in enumerate(td.chunks):
            if ci in shadow:
                c = Chunk(data=c.data, valid=c.valid, mvcc_ts=c.mvcc_ts,
                          mvcc_del=shadow[ci], n=c.n, rowid=c.rowid)
            chunks.append(c)
        pending_rows = [r for r in state.values() if r is not None]
        if pending_rows:
            chunks.append(self._delta_chunk(td, pending_rows, rts))
        return chunks

    def _delta_chunk(self, td, rows: list[dict], ts_int: int) -> Chunk:
        n = len(rows)
        data, vmap = {}, {}
        for col in td.schema.columns:
            vals = [r.get(col.name) for r in rows]
            v = np.array([x is not None for x in vals], dtype=bool)
            if col.type.uses_dictionary:
                d = td.dictionaries[col.name]
                arr = np.fromiter(
                    (d.encode(x) if x is not None else 0 for x in vals),
                    dtype=np.int32, count=n)
            else:
                arr = np.array([x if x is not None else 0 for x in vals],
                               dtype=col.type.np_dtype)
            data[col.name] = arr
            vmap[col.name] = v
        return Chunk(
            data=data, valid=vmap,
            mvcc_ts=np.full(n, ts_int, dtype=np.int64),
            mvcc_del=np.full(n, MAX_TS_INT, dtype=np.int64), n=n,
            rowid=np.asarray([int(r.get(ROWID, 0)) for r in rows],
                             dtype=np.int64))

    def _apply_column_defaults(self, schema, provided_cols, rows,
                               session) -> None:
        """Fill DEFAULT values for columns absent from the INSERT
        column list; {"__seq__": name} defaults draw nextval per row
        (pg evaluates defaults row-at-a-time)."""
        defaulted = [c for c in schema.columns
                     if c.name not in provided_cols
                     and getattr(c, "default", None) is not None]
        if not defaulted:
            return
        seq_ops = self._sequence_ops(session)
        for row in rows:
            for c in defaulted:
                if row.get(c.name) is not None:
                    continue
                d = c.default
                if isinstance(d, dict) and "__seq__" in d:
                    row[c.name] = int(seq_ops("nextval", d["__seq__"],
                                              None))
                else:
                    row[c.name] = d

    def _exec_insert(self, ins: ast.Insert, session: Session) -> Result:
        td = self.store.table(ins.table)
        schema = td.schema
        if ins.select is not None:
            for vol in ("nextval", "gen_random_uuid"):
                if _contains_func(ins.select, vol):
                    # the select binds the volatile fn ONCE, handing
                    # every produced row the same value (pg evaluates
                    # per row); reject instead of silently corrupting
                    # keys/uuids
                    raise EngineError(
                        f"{vol} inside INSERT ... SELECT is not "
                        "supported; insert explicit VALUES instead")
            # cache key must identify the inner select (repr is stable
            # and content-based for the AST dataclasses)
            src = self._exec_select(ins.select, session,
                                    sql_text="insert-select:" + repr(ins.select))
            cols = ins.columns or schema.column_names
            rows = [dict(zip(cols, r)) for r in src.rows]
            rows = [self._encode_row(schema, r) for r in rows]
        else:
            cols = ins.columns or schema.column_names
            binder = Binder(Scope(),
                            sequence_ops=self._sequence_ops(session))
            rows = []
            for row_exprs in ins.rows:
                if len(row_exprs) != len(cols):
                    raise EngineError("INSERT value count mismatch")
                row = {}
                for cname, e in zip(cols, row_exprs):
                    col = schema.column(cname)
                    b = binder.bind(e)
                    if not isinstance(b, BConst):
                        raise EngineError("INSERT values must be constants")
                    if b.value is None:
                        if not col.nullable:
                            raise EngineError(
                                f"null in non-null column {cname}")
                        row[cname] = None
                    else:
                        row[cname] = binder._const_to(b, col.type).value
                rows.append(row)
        self._apply_column_defaults(schema, set(cols), rows, session)
        for row in rows:
            for col in schema.columns:
                if not col.nullable and row.get(col.name) is None:
                    raise EngineError(f"null in non-null column {col.name}")
        codec = td.codec

        def fn(t: Txn, effects: list) -> Result:
            pending = self._txn_key_state(effects, ins.table)
            idx = self.store.ensure_pk_index(ins.table)
            rts = t.meta.read_ts.to_int()
            self._enforce_checks(ins.table, td, rows, rts)
            self._enforce_fks(ins.table, rows, session, rts)
            new_rows = []
            for row in rows:
                r = dict(row)
                if codec.synthetic_pk:
                    r[ROWID] = self.store.alloc_rowids(ins.table, 1)[0]
                key = codec.key(r)
                old_row = None
                if not codec.synthetic_pk and not ins.upsert:
                    # duplicate-key check = CPut semantics: a KV read
                    # (sees concurrent intents, registers the span)
                    # plus the scan-plane live index (covers
                    # bulk-ingested rows with no KV pair)
                    in_txn = pending.get(key, "absent")
                    committed = (t.get(key) is not None or key in idx)
                    if in_txn not in (None, "absent") or \
                            (committed and in_txn == "absent"):
                        pk = codec.pk_values(r)
                        raise EngineError(
                            f"duplicate key value {pk!r} violates "
                            f"primary key of {ins.table!r}")
                elif ins.upsert:
                    # the row being replaced (if any), for secondary-
                    # index entry cleanup and FK RESTRICT
                    in_txn = pending.get(key, "absent")
                    if in_txn not in (None, "absent"):
                        old_row = in_txn
                    elif key in idx:
                        ci, ri = idx[key]
                        old_row = self.store.extract_row(
                            td, td.chunks[ci], ri)
                    if old_row is not None:
                        changed = set()
                        for _ch, fk in self._fk_children_of(
                                ins.table):
                            changed |= {
                                cn for cn in fk["ref_columns"]
                                if old_row.get(cn) != r.get(cn)}
                        if changed:
                            self._enforce_fk_restrict(
                                ins.table, [old_row], session, rts,
                                changed_cols=changed)
                self._maintain_indexes(ins.table, td, t, pending,
                                       old_row, r, rts)
                t.put(key, codec.encode_value(r))
                pending[key] = r
                new_rows.append((key, r))
            for key, r in new_rows:
                effects.append((ins.table, ("put", key, r)))
            return Result(row_count=len(rows),
                          tag="UPSERT" if ins.upsert else "INSERT")

        return self._dml(session, fn)

    def _encode_row(self, schema: TableSchema, row: dict) -> dict:
        out = {}
        for cname, v in row.items():
            col = schema.column(cname)
            if v is None:
                out[cname] = None
            elif col.type.family == Family.DECIMAL:
                out[cname] = int(round(float(v) * 10 ** col.type.scale))
            elif col.type.family == Family.DATE:
                out[cname] = ((v - EPOCH_DATE).days
                              if isinstance(v, datetime.date) else int(v))
            elif col.type.family == Family.TIMESTAMP:
                out[cname] = (int((v - EPOCH_DT).total_seconds() * 1e6)
                              if isinstance(v, datetime.datetime) else int(v))
            else:
                out[cname] = v
        return out

    def _dml_scope(self, table: str) -> tuple[Scope, TableSchema]:
        td = self.store.table(table)
        scope = Scope()
        cols = {}
        for c in td.schema.columns:
            cols[c.name] = ColumnBinding(
                f"{table}.{c.name}", c.type, td.dictionaries.get(c.name))
        scope.add_table(table, cols)
        return scope, td.schema

    def _host_eval(self):
        """Eager host-side expression evaluation context: pin to the
        CPU backend so point-op predicates/assignments never pay a
        device round trip (on a tunnel-attached TPU one eager sync
        costs ~50-150ms — it would dominate every OLTP statement)."""
        return jax.default_device(jax.devices("cpu")[0])

    def _chunk_pred(self, table: str, where, scope: Scope,
                    session: Session | None = None):
        if where is None:
            return lambda chunk: np.ones(chunk.n, dtype=bool)
        session = session or self.session()
        binder = Binder(
            scope,
            subquery_eval=lambda s, lim: self._eval_subquery(
                s, session, lim),
            now_micros=self._read_ts(session).wall // 1000,
            sequence_ops=self._sequence_ops(session))
        pred = binder.bind(where)
        predf = compile_expr(pred)

        def f(chunk):
            with self._host_eval():
                ctx = ExprContext(
                    {f"{table}.{k}": (chunk.data[k], chunk.valid[k])
                     for k in chunk.data}, chunk.n)
                d, v = predf(ctx)
                return np.asarray(jnp.logical_and(d, v))
        return f

    def _exec_delete(self, d: ast.Delete, session: Session) -> Result:
        scope, _ = self._dml_scope(d.table)
        td = self.store.table(d.table)
        codec = td.codec
        predf = self._chunk_pred(d.table, d.where, scope, session)

        def fn(t: Txn, effects: list) -> Result:
            read_ts = t.meta.read_ts
            self._register_table_read(t, d.table, read_ts)
            rts = read_ts.to_int()
            n = 0
            pending = self._txn_key_state(effects, d.table)
            cand = self._dml_index_candidates(d.table, d.where, session)
            n_committed = len(td.chunks)
            victims: list[tuple[bytes, dict]] = []
            for ci, chunk in enumerate(
                    self._overlay_chunks(d.table, effects, read_ts)):
                if cand is not None and ci < n_committed \
                        and ci not in cand:
                    continue
                mask = chunk.live_mask(rts) & predf(chunk)
                for ri in np.nonzero(mask)[0]:
                    row = self.store.extract_row(td, chunk, int(ri))
                    victims.append((codec.key(row), row))
            # one batched RESTRICT probe for the whole statement; child
            # rows removed by this same statement are excluded so a
            # bulk delete over a self-referential FK (parent and child
            # in one statement, legal in pg) passes
            self._enforce_fk_restrict(d.table,
                                      [r for _k, r in victims],
                                      session, rts,
                                      exclude_keys={k for k, _r
                                                    in victims})
            for key, row in victims:
                self._maintain_indexes(d.table, td, t, pending,
                                       row, None, rts)
                t.delete(key)
                effects.append((d.table, ("del", key)))
                n += 1
            return Result(row_count=n, tag="DELETE")

        return self._dml(session, fn)

    def _exec_update(self, u: ast.Update, session: Session) -> Result:
        scope, schema = self._dml_scope(u.table)
        td = self.store.table(u.table)
        binder = Binder(scope,
                        sequence_ops=self._sequence_ops(session))
        assigned = {}
        for cname, e in u.assignments:
            col = schema.column(cname)
            # nextval is volatile and must allocate PER ROW (pg
            # semantics): a bare nextval('s') assignment allocates in
            # the row loop below; nextval nested inside a larger
            # expression would fold to one shared value — reject it
            if isinstance(e, ast.FuncCall) and e.name == "nextval" \
                    and len(e.args) == 1 \
                    and isinstance(e.args[0], ast.Literal):
                self._seq_desc(e.args[0].value)  # must exist
                assigned[cname] = ("seq", e.args[0].value)
                continue
            if _contains_func(e, "nextval"):
                raise EngineError(
                    "nextval may only be the entire SET expression "
                    "(per-row allocation); fold it into a bare "
                    "nextval('seq') assignment")
            if _contains_func(e, "gen_random_uuid"):
                raise EngineError(
                    "gen_random_uuid in UPDATE SET would give every "
                    "row the same uuid (bound once per statement); "
                    "not supported")
            b = binder.bind(e)
            if isinstance(b, BConst) and isinstance(b.value, str) \
                    and col.type.uses_dictionary:
                if col.type.family != Family.STRING:
                    b = binder.coerce(b, col.type)  # canonicalize datum
                code = td.dictionaries[cname].encode(b.value)
                assigned[cname] = ("const", code)
            elif isinstance(b, BConst):
                phys = binder._const_to(b, col.type).value if b.value is not None else None
                if phys is None and not col.nullable:
                    raise EngineError(
                        f"null in non-null column {cname}")
                assigned[cname] = ("const", phys)
            else:
                b2 = binder.coerce(b, col.type) if b.type.family != col.type.family else b
                assigned[cname] = ("expr", compile_expr(b2))

        def assign(chunk, mask, _he=self._host_eval):
            idx = np.nonzero(mask)[0]
            data, valid = {}, {}
            ctx = ExprContext(
                {f"{u.table}.{k}": (chunk.data[k], chunk.valid[k])
                 for k in chunk.data}, chunk.n)
            for c in schema.columns:
                cn = c.name
                if cn in assigned:
                    kind, v = assigned[cn]
                    if kind == "seq":
                        # placeholder; allocated per row in the todo
                        # loop (volatile, must not fold per chunk)
                        data[cn] = np.zeros(len(idx),
                                            dtype=c.type.np_dtype)
                        valid[cn] = np.ones(len(idx), dtype=bool)
                    elif kind == "const":
                        if v is None:
                            data[cn] = np.zeros(len(idx), dtype=c.type.np_dtype)
                            valid[cn] = np.zeros(len(idx), dtype=bool)
                        else:
                            data[cn] = np.full(len(idx), v,
                                               dtype=c.type.np_dtype)
                            valid[cn] = np.ones(len(idx), dtype=bool)
                    else:
                        with _he():
                            dd, vv = v(ctx)
                            dd, vv = np.asarray(dd), np.asarray(vv)
                        if not c.nullable and not vv[idx].all():
                            raise EngineError(
                                f"null in non-null column {cn}")
                        data[cn] = dd[idx].astype(c.type.np_dtype)
                        valid[cn] = vv[idx]
                else:
                    data[cn] = chunk.data[cn][idx]
                    valid[cn] = chunk.valid[cn][idx]
            return data, valid

        codec = td.codec
        predf = self._chunk_pred(u.table, u.where, scope, session)

        def fn(t: Txn, effects: list) -> Result:
            read_ts = t.meta.read_ts
            self._register_table_read(t, u.table, read_ts)
            rts = read_ts.to_int()
            idx = self.store.ensure_pk_index(u.table)
            n = 0
            todo = []
            cand = self._dml_index_candidates(u.table, u.where, session)
            n_committed = len(td.chunks)
            for ci, chunk in enumerate(
                    self._overlay_chunks(u.table, effects, read_ts)):
                if cand is not None and ci < n_committed \
                        and ci not in cand:
                    continue
                mask = chunk.live_mask(rts) & predf(chunk)
                if not mask.any():
                    continue
                data, valid = assign(chunk, mask)
                for j, ri in enumerate(np.nonzero(mask)[0]):
                    old = self.store.extract_row(td, chunk, int(ri))
                    new = dict(old)
                    for c in schema.columns:
                        cn = c.name
                        if not valid[cn][j]:
                            new[cn] = None
                        elif c.type.uses_dictionary:
                            new[cn] = td.dictionaries[cn].values[
                                int(data[cn][j])]
                        else:
                            new[cn] = data[cn][j].item()
                    for cn, kv in assigned.items():
                        if kv[0] == "seq":
                            new[cn] = self._sequence_op(
                                session, "nextval", kv[1], None)
                    todo.append((old, new))
            pending = self._txn_key_state(effects, u.table)
            self._enforce_checks(u.table, td,
                                 [new for _o, new in todo], rts)
            self._enforce_fks(u.table, [new for _o, new in todo],
                              session, rts)
            ref_cols_all = set()
            for child, fk in self._fk_children_of(u.table):
                ref_cols_all |= set(fk["ref_columns"])
            for old, new in todo:
                changed = {c for c in ref_cols_all
                           if old.get(c) != new.get(c)}
                if changed:
                    # probe only FKs whose own ref columns changed for
                    # THIS row (ADVICE r2: the union gate over-fired)
                    self._enforce_fk_restrict(u.table, [old],
                                              session, rts,
                                              changed_cols=changed)
            for old, new in todo:
                okey = codec.key(old)
                nkey = codec.key(new)
                if nkey != okey:
                    # pk change: delete old kv, insert new (dup-checked)
                    in_txn = pending.get(nkey, "absent")
                    committed = (t.get(nkey) is not None or nkey in idx)
                    if in_txn not in (None, "absent") or \
                            (committed and in_txn == "absent"):
                        raise EngineError(
                            f"duplicate key {codec.pk_values(new)!r} on "
                            f"UPDATE of {u.table!r}")
                    t.delete(okey)
                    effects.append((u.table, ("del", okey)))
                    pending[okey] = None
                self._maintain_indexes(u.table, td, t, pending,
                                       old, new, rts)
                t.put(nkey, codec.encode_value(new))
                effects.append((u.table, ("put", nkey, new)))
                pending[nkey] = new
                n += 1
            return Result(row_count=n, tag="UPDATE")

        return self._dml(session, fn)

    def _evict(self, name: str):
        for k in [k for k in self._device_tables if k[0] == name]:
            self._evict_device(k)


