"""Constraint enforcement: CHECK, FOREIGN KEY (restrict), UNIQUE, and
per-row index maintenance inside DML txns (pkg/sql/check.go,
row/fk_existence_*.go).

Split out of exec/engine.py (round-2 VERDICT Weak #4); see that
module's docstring for the overall execution model."""


import datetime
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..kv.txn import Txn
from ..sql import parser
from ..sql.binder import Binder
from ..storage import keys as K
from .expr import ExprContext, compile_expr

EPOCH_DATE = datetime.date(1970, 1, 1)
EPOCH_DT = datetime.datetime(1970, 1, 1)

from .session import EngineError


class ConstraintMixin:
    """Engine methods for this concern; mixed into exec.engine.Engine
    (all state lives on the Engine instance)."""

    # -- constraints (CHECK + FOREIGN KEY, restrict semantics) ---------------
    # The analogue of the reference's row-level constraint checks
    # (pkg/sql/row/fk_existence_*.go, check constraints in the
    # writer). FK existence probes run against the scan-plane index
    # locators plus this txn's buffered effects; concurrent-txn races
    # are serialized by the KV plane the same way unique indexes are.

    def _table_constraints(self, table: str) -> tuple:
        cached = self._constraint_defs.get(table)
        if cached is not None:
            return cached
        d = self.catalog.get_by_name(table)
        out = ((list(d.checks), list(d.fks)) if d is not None
               else ([], []))
        self._constraint_defs[table] = out
        return out

    def _fk_children_of(self, table: str) -> list:
        """[(child_table, fk_record)] of FKs referencing `table`."""
        if self._fk_children is None:
            m: dict[str, list] = {}
            for d in self.catalog.list_tables():
                for fk in d.fks:
                    m.setdefault(fk["ref_table"], []).append(
                        (d.name, fk))
            self._fk_children = m
        return self._fk_children.get(table, [])

    def _enforce_checks(self, table: str, td, rows: list,
                        rts: int) -> None:
        checks, _ = self._table_constraints(table)
        if not checks or not rows:
            return
        # the mini chunk must be built FIRST: encoding the new rows
        # can append fresh string values to the table dictionaries,
        # and the compiled predicate bakes dictionary lookup tables —
        # compiling before the growth would miss the new codes
        mini = self._delta_chunk(td, rows, rts)
        # compiled per (table, string-dictionary sizes): dictionary
        # growth recompiles — same fingerprint idea as the plan cache
        dictlens = tuple(sorted((cn, len(d)) for cn, d in
                                td.dictionaries.items()))
        key = (table, dictlens)
        fns = getattr(self, "_check_fn_cache", None)
        if fns is None:
            fns = self._check_fn_cache = {}
        compiled = fns.get(key)
        if compiled is None:
            scope, _s = self._dml_scope(table)
            compiled = []
            for ck in checks:
                e = parser.Parser(ck["expr_sql"]).parse_expr()
                b = Binder(scope).bind(e)
                compiled.append((ck, compile_expr(b)))
            # evict stale entries for THIS table (old dictlens), keep
            # other tables' hot entries
            for k in [k for k in fns if k[0] == table]:
                del fns[k]
            fns[key] = compiled
        ctx = ExprContext(
            {f"{table}.{k}": (mini.data[k], mini.valid[k])
             for k in mini.data}, mini.n)
        for ck, f in compiled:
            with self._host_eval():
                d, v = f(ctx)
                # SQL: CHECK fails only on FALSE (NULL passes)
                viol = np.asarray(jnp.logical_and(
                    jnp.logical_not(d), v))
            if viol.any():
                raise EngineError(
                    f"new row violates check constraint "
                    f"{ck['name']!r} ({ck['expr_sql']})")

    def _fk_parent_exists(self, fk: dict, vals: tuple, session,
                          rts: int) -> bool:
        rt = fk["ref_table"]
        rtd = self.store.table(rt)
        pending = (self._txn_key_state(session.effects, rt)
                   if session is not None and session.txn is not None
                   else {})
        sec = self.store.ensure_secondary_index(
            rt, tuple(fk["ref_columns"]))
        for ci, ri in sec.get(vals, []):
            ch = rtd.chunks[ci]
            if not (ch.mvcc_ts[ri] <= rts < ch.mvcc_del[ri]):
                continue
            if pending and self.store.row_key(rtd, ch, ri) in pending:
                continue  # deleted/superseded in this txn
            return True
        for _k, r in pending.items():
            if r is None:
                continue
            if tuple(r.get(c) for c in fk["ref_columns"]) == vals:
                return True
        return False

    def _enforce_fks(self, table: str, rows: list, session,
                     rts: int) -> None:
        """Child-side: every non-NULL FK value in `rows` must have a
        visible parent row."""
        _checks, fks = self._table_constraints(table)
        for fk in fks:
            # self-FKs may be satisfied by rows of this very statement
            self_vals = None
            if fk["ref_table"] == table:
                self_vals = {tuple(r.get(c) for c in fk["ref_columns"])
                             for r in rows}
            for r in rows:
                vals = tuple(r.get(c) for c in fk["columns"])
                if any(v is None for v in vals):
                    continue
                if self_vals is not None and vals in self_vals:
                    continue
                if not self._fk_parent_exists(fk, vals, session, rts):
                    raise EngineError(
                        f"insert on {table!r} violates foreign key "
                        f"{fk['name']!r}: no row in "
                        f"{fk['ref_table']!r} with "
                        f"{fk['ref_columns']} = {vals!r}")

    def _enforce_fk_restrict(self, table: str, removed_rows: list,
                             session, rts: int,
                             changed_cols: Optional[set] = None,
                             exclude_keys: Optional[set] = None) -> None:
        """Parent-side RESTRICT: removing/changing a referenced key
        fails while child rows still point at it.

        ``changed_cols`` (UPDATE/UPSERT): probe only FKs whose own
        ref_columns actually changed — probing every child FK with the
        old row's values spuriously fails when an unrelated FK (e.g.
        one on the PK) has referencing rows.
        ``exclude_keys`` (DELETE): row keys removed by this very
        statement — a bulk delete over a self-referential FK may
        legally remove parent and child together (pg semantics)."""
        for child, fk in self._fk_children_of(table):
            if child not in self.store.tables:
                continue
            if changed_cols is not None and \
                    not (set(fk["ref_columns"]) & changed_cols):
                continue
            ctd = self.store.table(child)
            pending = (self._txn_key_state(session.effects, child)
                       if session is not None
                       and session.txn is not None else {})
            sec = self.store.ensure_secondary_index(
                child, tuple(fk["columns"]))
            for row in removed_rows:
                vals = tuple(row.get(c) for c in fk["ref_columns"])
                if any(v is None for v in vals):
                    continue
                for ci, ri in sec.get(vals, []):
                    ch = ctd.chunks[ci]
                    if not (ch.mvcc_ts[ri] <= rts < ch.mvcc_del[ri]):
                        continue
                    if pending and self.store.row_key(
                            ctd, ch, ri) in pending:
                        continue
                    if exclude_keys and child == table and \
                            self.store.row_key(ctd, ch, ri) \
                            in exclude_keys:
                        continue  # this child row dies in the same stmt
                    raise EngineError(
                        f"delete/update on {table!r} violates "
                        f"foreign key {fk['name']!r} on {child!r}: "
                        f"row still references {vals!r}")
                for _k, r in pending.items():
                    if exclude_keys and child == table and \
                            _k in exclude_keys:
                        continue  # txn-buffered row dying in this stmt
                    if r is not None and tuple(
                            r.get(c) for c in fk["columns"]) == vals:
                        raise EngineError(
                            f"delete/update on {table!r} violates "
                            f"foreign key {fk['name']!r} on "
                            f"{child!r} (pending row)")

    def _maintain_indexes(self, table: str, td, t: Txn, pending: dict,
                          old_row, new_row, rts: int) -> None:
        """Per-row index maintenance inside a DML txn: drop stale
        unique-index KV entries for old_row, uniqueness-check and
        write entries for new_row. NULL in any indexed column exempts
        the row (SQL unique semantics)."""
        idxs = self._table_indexes(table)
        if not idxs:
            return
        tid = td.schema.table_id
        for idx in idxs:
            cols = tuple(idx.columns)
            old_vals = (tuple(old_row.get(cn) for cn in cols)
                        if old_row is not None else None)
            if old_vals is not None and any(v is None for v in old_vals):
                old_vals = None
            new_vals = (tuple(new_row.get(cn) for cn in cols)
                        if new_row is not None else None)
            if new_vals is not None and any(v is None for v in new_vals):
                new_vals = None
            if not idx.unique or old_vals == new_vals:
                continue
            if old_vals is not None:
                t.delete(K.table_key(tid, old_vals, idx.index_id))
            if new_vals is not None:
                self._check_unique(table, td, idx, new_vals, t,
                                   pending, new_row, rts)
                t.put(K.table_key(tid, new_vals, idx.index_id),
                      td.codec.key(new_row))

    def _check_unique(self, table: str, td, idx, vals: tuple, t: Txn,
                      pending: dict, new_row: dict, rts: int) -> None:
        tid = td.schema.table_id
        new_key = td.codec.key(new_row)
        # 1. the KV entry: covers committed rows written through the
        # row plane AND this txn's earlier writes (MVCC reads see own
        # intents); concurrent writers conflict on this same key
        raw = t.get(K.table_key(tid, vals, idx.index_id))
        if raw is not None and raw != new_key:
            raise EngineError(
                f"duplicate key value {vals!r} violates unique "
                f"index {idx.name!r} of {table!r}")
        # 2. the scan plane: covers bulk-ingested rows that never had
        # KV pairs (tpch.load-style ingest); visibility at our read ts
        sec = self.store.ensure_secondary_index(table, tuple(idx.columns))
        for ci, ri in sec.get(vals, []):
            c = td.chunks[ci]
            if not (c.mvcc_ts[ri] <= rts < c.mvcc_del[ri]):
                continue
            rk = self.store.row_key(td, c, ri)
            if rk == new_key or rk in pending:
                continue  # the row being replaced / superseded in-txn
            raise EngineError(
                f"duplicate key value {vals!r} violates unique "
                f"index {idx.name!r} of {table!r}")

