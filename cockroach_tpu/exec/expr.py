"""Compile bound expressions to device computations.

The analogue of the reference's projection/selection operator planning
(pkg/sql/colexec/colbuilder/execplan.go planning render expressions +
the generated colexecproj/colexecsel kernels) — except one recursive
compiler covers all types, and XLA fuses the resulting elementwise
graph into the surrounding scan/aggregate (no per-operator batch
materialization at all).

``compile_expr(e)`` returns ``fn(ctx) -> (data, valid)`` where ctx maps
batch column names to (data, valid) pairs and carries aggregate results
for post-aggregation projections.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import kernels as K
from ..sql.bound import (BAggRef, BBetween, BBin, BCase, BCast, BCoalesce,
                         BCol, BConst, BDictGather, BDictLookup, BDictRemap,
                         BExpr, BExtract, BFunc, BInList, BIsNull, BParam,
                         BUnary, BWinRef)
from ..sql.types import Family, SQLType


class ExprContext:
    """Evaluation context: column name -> (data, valid); agg results;
    runtime statement parameters (exec/planparam.py BParam values)."""

    def __init__(self, cols: dict, n: int, aggs: list | None = None,
                 params: tuple = ()):
        self.cols = cols
        self.n = n
        self.aggs = aggs or []
        self.params = params

    def col(self, name: str):
        return self.cols[name]


CompiledExpr = Callable[[ExprContext], tuple]


def _np_dtype(t: SQLType):
    return t.np_dtype


def compile_expr(e: BExpr) -> CompiledExpr:
    if isinstance(e, BConst):
        ty = e.type
        if e.value is None:
            def f_null(ctx):
                z = jnp.zeros((ctx.n,), dtype=_np_dtype(ty))
                return z, jnp.zeros((ctx.n,), dtype=jnp.bool_)
            return f_null
        val = e.value

        def f_const(ctx):
            d = jnp.full((ctx.n,), val, dtype=_np_dtype(ty))
            return d, jnp.ones((ctx.n,), dtype=jnp.bool_)
        return f_const

    if isinstance(e, BParam):
        idx, pty = e.index, e.type

        def f_param(ctx):
            # runtime scalar (statement-shape plan cache): same dtype
            # and broadcast semantics as the baked f_const above
            v = jnp.array(ctx.params[idx], dtype=_np_dtype(pty))
            d = jnp.broadcast_to(v, (ctx.n,))
            return d, jnp.ones((ctx.n,), dtype=jnp.bool_)
        return f_param

    if isinstance(e, BCol):
        name = e.name

        def f_col(ctx):
            return ctx.col(name)
        return f_col

    if isinstance(e, BAggRef):
        i = e.index

        def f_agg(ctx):
            return ctx.aggs[i]
        return f_agg

    if isinstance(e, BWinRef):
        wname = f"__win{e.index}"

        def f_win(ctx):
            return ctx.col(wname)
        return f_win

    if isinstance(e, BBin):
        lf, rf = compile_expr(e.left), compile_expr(e.right)
        op = e.op
        if op in ("and", "or"):
            k = K.and_ if op == "and" else K.or_

            def f_logic(ctx):
                return k(lf(ctx), rf(ctx))
            return f_logic
        table = {"+": K.add, "-": K.sub, "*": K.mul, "/": K.div,
                 "%": K.mod, "//": None,
                 "=": K.eq, "!=": K.ne, "<": K.lt, "<=": K.le,
                 ">": K.gt, ">=": K.ge}
        if op == "//":
            def f_idiv(ctx):
                a, b = lf(ctx), rf(ctx)
                return a[0] // b[0], jnp.logical_and(a[1], b[1])
            return f_idiv
        k = table[op]
        out_ty = e.type

        def f_bin(ctx):
            a, b = lf(ctx), rf(ctx)
            d, v = k(a, b)
            if op in ("+", "-", "*") and out_ty.family in (
                    Family.INT, Family.DECIMAL, Family.DATE,
                    Family.TIMESTAMP):
                d = d.astype(_np_dtype(out_ty))
            return d, v
        return f_bin

    if isinstance(e, BUnary):
        xf = compile_expr(e.operand)
        op = e.op
        if op == "not":
            def f_not(ctx):
                return K.not_(xf(ctx))
            return f_not
        if op == "-":
            def f_neg(ctx):
                return K.neg(xf(ctx))
            return f_neg
        fn = {"abs": jnp.abs, "floor": jnp.floor, "ceil": jnp.ceil,
              "round": jnp.round, "sqrt": jnp.sqrt, "ln": jnp.log,
              "exp": jnp.exp}[op]

        def f_un(ctx):
            d, v = xf(ctx)
            return fn(d), v
        return f_un

    if isinstance(e, BBetween):
        xf = compile_expr(e.expr)
        lof, hif = compile_expr(e.lo), compile_expr(e.hi)
        neg = e.negated

        def f_between(ctx):
            r = K.between(xf(ctx), lof(ctx), hif(ctx))
            return K.not_(r) if neg else r
        return f_between

    if isinstance(e, BInList):
        xf = compile_expr(e.expr)
        vals = list(e.values)
        neg = e.negated

        def f_in(ctx):
            r = K.in_list(xf(ctx), vals)
            return K.not_(r) if neg else r
        return f_in

    if isinstance(e, BIsNull):
        xf = compile_expr(e.expr)
        k = K.is_not_null if e.negated else K.is_null

        def f_isnull(ctx):
            return k(xf(ctx))
        return f_isnull

    if isinstance(e, BCase):
        whenfs = [(compile_expr(c), compile_expr(v)) for c, v in e.whens]
        elsef = compile_expr(e.else_)

        def f_case(ctx):
            return K.case_when([(cf(ctx), vf(ctx)) for cf, vf in whenfs],
                               elsef(ctx))
        return f_case

    if isinstance(e, BCast):
        xf = compile_expr(e.expr)
        src, dst = e.expr.type, e.type

        def f_cast(ctx):
            d, v = xf(ctx)
            if dst.family == Family.FLOAT:
                out = d.astype(jnp.float64)
                if src.family == Family.DECIMAL:
                    out = out / (10.0 ** src.scale)
                return out, v
            if dst.family == Family.DECIMAL:
                if src.family == Family.FLOAT:
                    return jnp.round(d * 10.0 ** dst.scale).astype(jnp.int64), v
                return d.astype(jnp.int64), v
            if dst.family == Family.INT:
                if src.family == Family.DECIMAL:
                    # numeric -> int rounds half away from zero
                    div = 10 ** src.scale
                    mag = (jnp.abs(d) + div // 2) // div
                    d = jnp.where(d < 0, -mag, mag)
                elif src.family == Family.FLOAT:
                    d = jnp.rint(d)  # float -> int: half-even (pg)
                return d.astype(_np_dtype(dst)), v
            if dst.family == Family.BOOL:
                return d.astype(jnp.bool_), v
            raise NotImplementedError(f"cast {src} -> {dst}")
        return f_cast

    if isinstance(e, BCoalesce):
        fs = [compile_expr(a) for a in e.args]

        def f_coalesce(ctx):
            return K.coalesce(*[f(ctx) for f in fs])
        return f_coalesce

    if isinstance(e, BExtract):
        xf = compile_expr(e.expr)
        part = e.part
        fam = "timestamp" if e.expr.type.family == Family.TIMESTAMP else "date"

        def f_extract(ctx):
            d, v = xf(ctx)
            return K.extract_part(part, d, fam), v
        return f_extract

    if isinstance(e, BFunc):
        return _compile_func(e)

    if isinstance(e, BDictGather):
        xf = compile_expr(e.expr)
        tbl = np.asarray(e.table)
        ntbl = (np.asarray(e.null_table, dtype=bool)
                if e.null_table is not None else None)

        def f_gather(ctx):
            d, v = xf(ctx)
            # jnp.array, not asarray: tbl can alias the dictionary's
            # live array, and an aliased trace constant is only safe
            # by a distant append-only argument (graftlint
            # no-aliasing-upload)
            lut = jnp.array(tbl)
            codes = jnp.clip(d, 0, tbl.shape[0] - 1)
            if ntbl is not None:
                v = v & _small_lut(ntbl, codes)
            return lut[codes], v
        return f_gather

    if isinstance(e, BDictLookup):
        xf = compile_expr(e.expr)
        tbl = np.asarray(e.table, dtype=bool)

        def f_dict(ctx):
            d, v = xf(ctx)
            codes = jnp.clip(d, 0, tbl.shape[0] - 1)
            return _small_lut(tbl, codes), v
        return f_dict

    if isinstance(e, BDictRemap):
        xf = compile_expr(e.expr)
        rtbl = np.asarray(e.table, dtype=np.int32)
        ntbl = (np.asarray(e.null_table, dtype=bool)
                if e.null_table is not None else None)

        def f_remap(ctx):
            d, v = xf(ctx)
            codes = jnp.clip(d, 0, rtbl.shape[0] - 1)
            if ntbl is not None:
                v = v & _small_lut(ntbl, codes)
            return _small_lut(rtbl, codes), v
        return f_remap

    raise NotImplementedError(f"cannot compile {e!r}")


# small-LUT gathers ride the MXU: TPU VPU dynamic gathers run ~100-200M
# lookups/s, while a one-hot matmul against a <=512-entry table is
# effectively free next to the surrounding streaming work (the MXU is
# idle in scan programs). Measured on v5e (round 3): 8.4M boolean
# lookups via gather +70ms, via one-hot matmul +0ms. f32 keeps integer
# remap values exact (<= 2^24); the dictionary LIKE/IN/= predicates
# TPC-H and SSB lean on are all <=512-entry LUTs.
_ONE_HOT_MAX = 512


def _small_lut(tbl: np.ndarray, codes):
    L = tbl.shape[0]
    if L > _ONE_HOT_MAX or (
            tbl.dtype != np.bool_ and L > 0
            and np.abs(tbl).max() >= (1 << 24)):
        # f32 holds integers exactly only below 2^24: big remap values
        # (SF100-class target dictionaries) stay on the gather path
        # (jnp.array: tbl is caller-owned, copy rather than alias —
        # graftlint no-aliasing-upload)
        return jnp.array(tbl)[codes]
    lp = max(128, 1 << (L - 1).bit_length())
    padded = np.zeros((lp,), dtype=np.float32)
    padded[:L] = tbl.astype(np.float32)
    oh = jax.nn.one_hot(codes, lp, dtype=jnp.float32)
    # graftlint: waive[no-aliasing-upload] padded is np.zeros allocated
    # two lines up, function-local and never written after this point
    out = oh @ jnp.asarray(padded)
    if tbl.dtype == np.bool_:
        return out > 0.5
    return jnp.round(out).astype(tbl.dtype)


# 1-arg elementwise builtin kernels (sql/builtins.py registry); all
# fuse into the surrounding scan program
_UNARY_KERNELS = {
    "sqrt": jnp.sqrt, "ln": jnp.log, "exp": jnp.exp,
    "log10": jnp.log10, "log2": jnp.log2, "cbrt": jnp.cbrt,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "cot": lambda x: 1.0 / jnp.tan(x),
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "floor": jnp.floor, "ceil": jnp.ceil, "ceiling": jnp.ceil,
    "trunc": jnp.trunc, "sign": jnp.sign,
    "erf": jax.scipy.special.erf,
    "erfc": jax.scipy.special.erfc,
    "sind": lambda x: jnp.sin(jnp.radians(x)),
    "cosd": lambda x: jnp.cos(jnp.radians(x)),
    "tand": lambda x: jnp.tan(jnp.radians(x)),
    "cotd": lambda x: 1.0 / jnp.tan(jnp.radians(x)),
    "asind": lambda x: jnp.degrees(jnp.arcsin(x)),
    "acosd": lambda x: jnp.degrees(jnp.arccos(x)),
    "atand": lambda x: jnp.degrees(jnp.arctan(x)),
}

_BINARY_KERNELS = {
    "pow": jnp.power, "power": jnp.power, "atan2": jnp.arctan2,
}


def _compile_func(e: BFunc) -> CompiledExpr:
    name = e.name
    fs = [compile_expr(a) for a in e.args]
    if name in _UNARY_KERNELS:
        fn = _UNARY_KERNELS[name]

        def f1(ctx):
            d, v = fs[0](ctx)
            return fn(d), v
        return f1
    if name in _BINARY_KERNELS:
        fn = _BINARY_KERNELS[name]

        def f2(ctx):
            (a, va), (b, vb) = fs[0](ctx), fs[1](ctx)
            return fn(a, b), jnp.logical_and(va, vb)
        return f2
    if name in ("round_n", "trunc_n"):
        ndigits = e.args[1].value
        scale = 10.0 ** ndigits
        op = jnp.round if name == "round_n" else jnp.trunc

        def f_round(ctx):
            d, v = fs[0](ctx)
            return op(d * scale) / scale, v
        return f_round
    if name == "mod":
        def f_mod(ctx):
            return K.mod(fs[0](ctx), fs[1](ctx))
        return f_mod
    if name == "logb":
        def f_logb(ctx):
            # args are [base, x] (pg's log(b, x))
            (b, vb), (x, vx) = fs[0](ctx), fs[1](ctx)
            ok = jnp.logical_and(b > 0, x > 0)
            d = jnp.log(jnp.where(ok, x, 1.0)) / \
                jnp.log(jnp.where(ok, b, 2.0))
            return d, jnp.logical_and(jnp.logical_and(vb, vx), ok)
        return f_logb
    if name == "div":
        def f_div(ctx):
            (a, va), (b, vb) = fs[0](ctx), fs[1](ctx)
            ok = b != 0
            q = jnp.trunc(a / jnp.where(ok, b, 1.0))
            return q, jnp.logical_and(jnp.logical_and(va, vb), ok)
        return f_div
    if name in ("greatest", "least"):
        pick = jnp.maximum if name == "greatest" else jnp.minimum

        def f_gl(ctx):
            # SQL GREATEST/LEAST ignore NULL arguments
            d, v = fs[0](ctx)
            for f in fs[1:]:
                d2, v2 = f(ctx)
                both = jnp.logical_and(v, v2)
                d = jnp.where(both, pick(d, d2), jnp.where(v, d, d2))
                v = jnp.logical_or(v, v2)
            return d, v
        return f_gl
    if name == "nullif":
        def f_nullif(ctx):
            (a, va), (b, vb) = fs[0](ctx), fs[1](ctx)
            eq = jnp.logical_and(a == b, jnp.logical_and(va, vb))
            return a, jnp.logical_and(va, jnp.logical_not(eq))
        return f_nullif
    if name == "isfinite":
        def f_isfinite(ctx):
            d, v = fs[0](ctx)
            return jnp.isfinite(d), v
        return f_isfinite
    if name == "width_bucket":
        def f_wb(ctx):
            (x, vx), (lo, vl), (hi, vh), (n, vn) = [f(ctx)
                                                    for f in fs]
            nb = n.astype(jnp.int64)
            frac = (x - lo) / jnp.where(hi != lo, hi - lo, 1.0)
            inner = jnp.floor(frac * nb).astype(jnp.int64) + 1
            d = jnp.where(x < lo, 0,
                          jnp.where(x >= hi, nb + 1, inner))
            ok = jnp.logical_and(jnp.logical_and(vx, vl),
                                 jnp.logical_and(vh, vn))
            return d, jnp.logical_and(ok, hi != lo)
        return f_wb
    if name == "isnan":
        def f_isnan(ctx):
            d, v = fs[0](ctx)
            return jnp.isnan(d), v
        return f_isnan
    if name in ("date_trunc_date", "date_trunc_ts"):
        part = e.args[0].value
        kern = (K.date_trunc_days if name == "date_trunc_date"
                else K.date_trunc_micros)

        def f_trunc(ctx):
            d, v = fs[1](ctx)
            return kern(part, d), v
        return f_trunc
    raise NotImplementedError(f"no kernel for builtin {name}")
