"""The OLTP fast lane: statement-shape cache + native row plane.

Round-4's named limiter (BENCHMARKS.md:39-41): every OLTP op re-parses
its SQL (literals vary per op), re-matches the fastpath, and walks
rows as Python dicts — ~300µs of GIL-held Python per op, capping
16 concurrent YCSB-E drivers at ~3.7K ops/s. The reference's hot loop
is compiled Go end to end (conn_executor.go:1835 → kv →
pebbleMVCCScanner). This module is the equivalent compiled lane:

1. **Statement shapes** (`normalize`): literals are stripped from the
   SQL text (`SELECT … WHERE k = 42` → `… WHERE k = ?`, lits=[42]) and
   the shape keys a cache of prebuilt handlers — the same idea as the
   reference's plan cache keyed on fingerprint (sql/plan_cache.go),
   applied one level earlier so unparameterized client traffic still
   hits it.
2. **Native row plane** (`native/oltp.cpp`): eligible tables (single
   int primary key, all int64-representable columns) keep an MVCC
   version mirror in C++ — contiguous arrays + a key-ordered index.
   Point reads and ordered range scans run there with the GIL
   released; an internal shared_mutex admits truly parallel readers.
3. **Write lane + deferred publish**: single-row INSERT/UPDATE/DELETE
   still write through kv.Txn (latches, tscache floor, intents,
   commit — the concurrency truth is unchanged) and apply to the
   mirror at commit; the *columnstore* publish is queued and flushed
   in one batch before the next non-lane statement touches the table
   — the memtable pattern, which also stops the one-chunk-per-
   statement chunk explosion.

Serializability notes: lane reads bump the timestamp cache exactly
like the Python fastpath (a later writer can never commit beneath a
served read); lane writes take per-key latches and push above the
tscache floor; write-write conflicts surface as WriteTooOld/intent
pushes and retry through the same loop as `_dml`.
"""

from __future__ import annotations

import ctypes
import functools
import re
import threading
import time
from typing import Optional

import numpy as np

from ..native import get_oltp
from ..sql import ast
from ..kv.concurrency import Span
from ..sql.types import Family
from .session import EngineError, Result, Session

MAX_I64 = np.iinfo(np.int64).max

# literals: quoted strings first (so ints inside them don't match),
# then standalone integer tokens (not part of an identifier/number)
_LIT_RE = re.compile(r"'(?:[^']|'')*'|(?<![\w.])\d+(?![\w.\d])")


@functools.lru_cache(maxsize=8192)
def _normalize_text(sql: str):
    """Memoized (shape, literals-tuple) for one statement text: the
    regex pass runs once per DISTINCT text, not once per execution —
    YCSB-style drivers repeat a small set of literal combinations
    millions of times and this sat at the top of the lane profile."""
    lits: list = []

    def sub(m):
        tok = m.group(0)
        if tok.startswith("'"):
            lits.append(tok[1:-1].replace("''", "'"))
        else:
            lits.append(int(tok))
        return "?"

    return _LIT_RE.sub(sub, sql), tuple(lits)


def normalize(sql: str):
    """(shape, literals): literals replaced by ? placeholders."""
    shape, lits = _normalize_text(sql)
    return shape, list(lits)


# ---------------------------------------------------------------------------
# native table mirror
# ---------------------------------------------------------------------------

_INT_FAMS = (Family.INT, Family.BOOL, Family.DATE, Family.TIMESTAMP,
             Family.INTERVAL, Family.DECIMAL)


def mirror_eligible(schema) -> bool:
    """Single-column INT primary key, every column int64-representable
    in storage form, no hidden columns."""
    if len(schema.primary_key) != 1:
        return False
    pk = schema.primary_key[0]
    for c in schema.columns:
        if getattr(c, "hidden", False):
            return False
        if c.type.uses_dictionary or c.type.family not in _INT_FAMS:
            return False
        if np.dtype(c.type.np_dtype).kind not in "iub":
            return False
        if c.name == pk and c.type.family != Family.INT:
            return False
    return True


class TableMirror:
    """One table's native MVCC version mirror."""

    def __init__(self, lib, schema):
        self.lib = lib
        self.schema = schema
        self.pk = schema.primary_key[0]
        self.cols = [c.name for c in schema.columns]
        self.col_pos = {n: i for i, n in enumerate(self.cols)}
        self.ncols = len(self.cols)
        self.h = lib.oltp_create(self.ncols)
        self.synced_gen = -1
        # scratch buffers for point reads (per-mirror; guarded by the
        # caller holding no buffer across calls — each call copies out)
        self._local = threading.local()

    def __del__(self):
        try:
            self.lib.oltp_destroy(self.h)
        except Exception:
            pass

    def _bufs(self, cap: int):
        st = getattr(self._local, "bufs", None)
        if st is None or st[0] < cap:
            keys = np.empty(cap, dtype=np.int64)
            vals = np.empty(cap * self.ncols, dtype=np.int64)
            vld = np.empty(cap * self.ncols, dtype=np.uint8)
            st = (cap, keys, vals, vld,
                  keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                  vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                  vld.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
            self._local.bufs = st
        return st

    def rebuild(self, td) -> None:
        """Load every row version from the columnstore chunks (all
        versions: historical reads walk the same chains)."""
        self.lib.oltp_destroy(self.h)
        self.h = self.lib.oltp_create(self.ncols)
        parts = []
        for ch in td.chunks:
            n = ch.n
            if n == 0:
                continue
            keys = np.ascontiguousarray(ch.data[self.pk],
                                        dtype=np.int64)
            cols = np.empty((self.ncols, n), dtype=np.int64)
            vld = np.empty((self.ncols, n), dtype=np.uint8)
            for i, cn in enumerate(self.cols):
                cols[i] = ch.data[cn].astype(np.int64)
                vld[i] = ch.valid[cn].astype(np.uint8)
            parts.append((keys, ch.mvcc_ts.astype(np.int64),
                          ch.mvcc_del.astype(np.int64), cols, vld))
        if parts:
            keys = np.concatenate([p[0] for p in parts])
            ts = np.concatenate([p[1] for p in parts])
            del_ = np.concatenate([p[2] for p in parts])
            cols = np.concatenate([p[3] for p in parts], axis=1)
            vld = np.concatenate([p[4] for p in parts], axis=1)
            order = np.lexsort((ts, keys))
            keys = np.ascontiguousarray(keys[order])
            ts = np.ascontiguousarray(ts[order])
            del_ = np.ascontiguousarray(del_[order])
            cols = np.ascontiguousarray(cols[:, order])
            vld = np.ascontiguousarray(vld[:, order])
            i64p = ctypes.POINTER(ctypes.c_int64)
            self.lib.oltp_bulk(
                self.h, len(keys),
                keys.ctypes.data_as(i64p),
                ts.ctypes.data_as(i64p),
                del_.ctypes.data_as(i64p),
                cols.ctypes.data_as(i64p),
                vld.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        self.synced_gen = td.generation

    def put(self, key: int, ts: int, vals: dict) -> None:
        v = np.empty(self.ncols, dtype=np.int64)
        m = np.empty(self.ncols, dtype=np.uint8)
        for i, cn in enumerate(self.cols):
            x = vals.get(cn)
            if x is None:
                v[i] = 0
                m[i] = 0
            else:
                v[i] = int(x)
                m[i] = 1
        self.lib.oltp_put(
            self.h, int(key), int(ts),
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            m.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))

    def delete(self, key: int, ts: int) -> None:
        self.lib.oltp_del(self.h, int(key), int(ts))

    def read(self, key: int, read_ts: int):
        """(vals_i64_list, valid_list) or None."""
        _, _, vals, vld, _, vp, mp = self._bufs(max(64, self.ncols))
        ok = self.lib.oltp_read(self.h, int(key), int(read_ts), vp, mp)
        if not ok:
            return None
        return vals[:self.ncols].tolist(), vld[:self.ncols].tolist()

    def multiread(self, keys, read_ts: int):
        """Fused gather for one batch window: (vals row-major list,
        valid list, found list) across the whole key vector — a single
        native call (one shared-lock acquisition, one GIL release)
        instead of len(keys) point reads."""
        n = len(keys)
        karr = np.ascontiguousarray(keys, dtype=np.int64)
        vals = np.empty(max(n, 1) * self.ncols, dtype=np.int64)
        vld = np.empty(max(n, 1) * self.ncols, dtype=np.uint8)
        fnd = np.zeros(max(n, 1), dtype=np.uint8)
        if hasattr(self.lib, "oltp_multiread"):
            i64p = ctypes.POINTER(ctypes.c_int64)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            self.lib.oltp_multiread(
                self.h, n, karr.ctypes.data_as(i64p), int(read_ts),
                vals.ctypes.data_as(i64p), vld.ctypes.data_as(u8p),
                fnd.ctypes.data_as(u8p))
        else:  # pragma: no cover - stale cached .so without the symbol
            for i in range(n):
                got = self.read(int(karr[i]), read_ts)
                if got is not None:
                    fnd[i] = 1
                    vals[i * self.ncols:(i + 1) * self.ncols] = got[0]
                    vld[i * self.ncols:(i + 1) * self.ncols] = got[1]
        return vals.tolist(), vld.tolist(), fnd.tolist()

    def scan(self, lo, lo_strict, hi, hi_strict, read_ts: int,
             cap: int):
        """(nrows, keys[], vals row-major, valid row-major)."""
        _, keys, vals, vld, kp, vp, mp = self._bufs(
            max(cap * self.ncols, cap, 64))
        n = self.lib.oltp_scan(
            self.h,
            int(lo) if lo is not None else 0, int(lo is not None),
            int(bool(lo_strict)),
            int(hi) if hi is not None else 0, int(hi is not None),
            int(bool(hi_strict)),
            int(read_ts), int(cap), kp, vp, mp)
        return n, keys, vals, vld


# ---------------------------------------------------------------------------
# lane plans (one per statement shape)
# ---------------------------------------------------------------------------

class LanePlan:
    """Prebuilt executor for one statement shape. kind:
    'point' | 'scan' | 'insert' | 'update' | 'delete'."""

    __slots__ = ("kind", "table", "out_names", "out_types", "out_pos",
                 "out_decode", "out_pairs", "pk_lit", "lo_lit",
                 "lo_strict", "hi_lit", "hi_strict", "limit_lit",
                 "limit_const", "set_cols", "set_lits", "ins_cols",
                 "ins_lits", "nlits", "lit_kinds", "order_desc", "td",
                 "codec")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))


class ShapeIneligible(Exception):
    pass


# sentinel literal values used to discover slot roles: the shape text
# re-parses with slot i carrying SENT_BASE+i (or a marker string), so
# the role of each ? is read off the AST structurally — never guessed
# from runtime values (two slots can carry equal values)
SENT_BASE = 7_700_000_000
SENT_STR = "\x00slot{}"


class _Slot:
    """One literal slot reference discovered at sentinel position i;
    neg marks a sentinel consumed under unary minus."""

    __slots__ = ("i", "neg")

    def __init__(self, i: int, neg: bool = False):
        self.i = i
        self.neg = neg

    def get(self, lits):
        v = lits[self.i]
        return -v if self.neg else v


def _slot_of(value, nlits):
    """Map a parsed literal value back to its slot (or None for a
    constant baked into the shape)."""
    if isinstance(value, str) and value.startswith("\x00slot"):
        return _Slot(int(value[6:]))
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        iv = int(value)
        if SENT_BASE <= iv < SENT_BASE + nlits:
            return _Slot(iv - SENT_BASE)
        if -SENT_BASE - nlits < iv <= -SENT_BASE:
            return _Slot(-iv - SENT_BASE, neg=True)
    return None


def _sentinel_sql(shape: str, lits: list) -> str:
    out = []
    i = 0
    for part in shape.split("?"):
        out.append(part)
        if i < len(lits):
            if isinstance(lits[i], str):
                out.append("'" + SENT_STR.format(i) + "'")
            else:
                out.append(str(SENT_BASE + i))
            i += 1
    return "".join(out)


class _Const:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def get(self, _lits):
        return self.v


class OltpLaneMixin:
    """Engine methods for the OLTP fast lane (state on the Engine)."""

    def _lane_init(self) -> None:
        self._lane_lib = get_oltp()
        self._lane_shapes: dict = {}       # shape -> LanePlan | None
        self._lane_mirrors: dict = {}      # table -> TableMirror
        self._lane_pending: dict = {}      # table -> [(op, tsi), ...]
        self._lane_lock = threading.Lock()
        # commit-vs-snapshot fence: a lane COMMIT (active check + kv
        # commit + mirror/queue apply) and a full-path statement's
        # (active increment + pending check) each happen atomically
        # under this lock, so a full-path read can never take a
        # snapshot between a lane commit and its queue append
        # (review round-5 finding #3)
        self._lane_sync = threading.Lock()
        self._nonlane_active = 0
        # statement-scoped suspension: full-path statements whose base
        # table set is known suspend lane writes ONLY for those tables
        # (table -> active statement count, under _lane_sync). An
        # analytic tenant scanning other tables no longer stalls the
        # OLTP lane or forces its flush (engine.execute_stmt).
        self._nonlane_tables: dict = {}
        self.lane_hits = 0
        self.lane_misses = 0
        # cross-session batch windows (exec/oltpbatch.py): concurrent
        # point statements fuse into one multi-key probe / one group
        # commit. Session var oltp_batch=off restores the
        # per-statement path bit-for-bit.
        from .oltpbatch import LaneBatcher
        self._lane_batcher = LaneBatcher(self)

    # -- entry ------------------------------------------------------

    def lane_execute(self, sql: str,
                     session: Optional[Session]) -> Optional[Result]:
        """Serve `sql` from the fast lane, or None to take the normal
        path. Never raises for ineligibility — only for real statement
        errors (duplicate key etc.)."""
        if self._lane_lib is None or self.cluster is not None:
            return None
        if session is not None and (
                session.txn is not None or session.effects
                or session.txn_aborted
                or session.vars.get("index_scan", "on") == "off"
                or session.vars.get("tracing", "off") == "on"):
            return None
        got = normalize(sql)
        shape, lits = got
        plan = self._lane_shapes.get(shape, ShapeIneligible)
        if plan is ShapeIneligible:
            plan = self._lane_build(shape, lits)
        if plan is None:
            self.lane_misses += 1
            return None
        if len(lits) != plan.nlits:
            return None
        if plan.lit_kinds is not None and \
                plan.lit_kinds != [isinstance(v, str) for v in lits]:
            # literal-kind mismatch vs the cached classification
            # (e.g. WHERE k = 'abc' hitting a shape built for
            # WHERE k = 42): the full path binds it properly and
            # raises a real SQL type error instead of a bare
            # ValueError out of int()
            return None
        t0 = time.perf_counter()
        try:
            if plan.kind == "scan":
                # range scans stay per-statement: their native scan is
                # already one fused pass and their result sizes would
                # make window buffers unbounded
                res = self._lane_read(plan, lits, session)
            elif session is not None and \
                    session.vars.get("oltp_batch", "auto") == "off":
                # the A/B lever: off is exactly the per-statement path
                res = (self._lane_read(plan, lits, session)
                       if plan.kind == "point"
                       else self._lane_write(plan, lits, session))
            else:
                res = self._lane_batcher.submit(plan, lits, session)
        except ShapeIneligible:
            return None
        if res is not None:
            self.lane_hits += 1
            self.sqlstats.record_fp(shape, time.perf_counter() - t0,
                                    max(len(res.rows), res.row_count))
        return res

    # -- shape classification ---------------------------------------

    def _lane_build(self, shape: str, lits: list):
        try:
            plan = self._lane_classify(shape, lits)
        except Exception:
            plan = None
        if plan is not None:
            # the plan was classified against THESE literal kinds (the
            # sentinel SQL bakes int-vs-string into the parse); a later
            # statement with the same shape but a different kind in
            # some slot must take the full path, not int() a string
            plan.lit_kinds = [isinstance(v, str) for v in lits]
        if len(self._lane_shapes) > 4096:
            self._lane_shapes.clear()
        self._lane_shapes[shape] = plan
        return plan

    def _lane_table_ok(self, tname: str) -> bool:
        """Schema-level eligibility: mirrorable columns and none of
        the write-path features the lane skips (checks, FKs, secondary
        indexes, cdc) — those statements take the full path."""
        if tname not in self.store.tables:
            return False
        td = self.store.table(tname)
        if not mirror_eligible(td.schema):
            return False
        if self._table_indexes(tname):
            return False
        d = self.catalog.get_by_name(tname)
        if d is not None and (d.checks or d.fks):
            return False
        if self._fk_children_of(tname):
            return False
        if any(f.table == tname for f in self.cdc_feeds):
            return False
        if getattr(td, "column_defaults", None):
            return False
        return True

    def _lane_classify(self, shape: str, lits: list):
        from ..sql import parser as _parser
        stmt = _parser.parse(_sentinel_sql(shape, lits))
        n = len(lits)

        def lit_ref(e):
            if not isinstance(e, ast.Literal) or e.value is None:
                return None
            s = _slot_of(e.value, n)
            return s if s is not None else _Const(e.value)

        if isinstance(stmt, ast.Select):
            return self._classify_select(stmt, n, lit_ref)
        if isinstance(stmt, ast.Insert):
            return self._classify_insert(stmt, n, lit_ref)
        if isinstance(stmt, ast.Update):
            return self._classify_update(stmt, n, lit_ref)
        if isinstance(stmt, ast.Delete):
            return self._classify_delete(stmt, n, lit_ref)
        return None

    def _classify_select(self, sel, n, lit_ref):
        from .stmtutil import split_conjuncts_ast
        if (sel.table is None or sel.joins or sel.group_by
                or sel.having or sel.distinct or sel.ctes
                or getattr(sel, "as_of", None) is not None
                or sel.table.subquery is not None
                or getattr(sel, "windows", None)):
            return None
        tname = sel.table.name
        if sel.table.alias not in (None, tname):
            return None
        if not self._lane_table_ok(tname) or tname in self._view_map():
            return None
        schema = self.store.table(tname).schema
        pk = schema.primary_key[0]
        out = []
        for item in sel.items:
            if item.star:
                for c in schema.columns:
                    out.append((c.name, c.name))
            else:
                e = item.expr
                if not (isinstance(e, ast.ColumnRef)
                        and e.table in (None, tname)
                        and any(c.name == e.name
                                for c in schema.columns)):
                    return None
                out.append((item.alias or e.name, e.name))
        eq = lo = hi = None
        lo_strict = hi_strict = False
        if sel.where is None:
            return None
        for c in split_conjuncts_ast(sel.where):
            if not (isinstance(c, ast.BinOp)
                    and c.op in ("=", "<", "<=", ">", ">=")):
                return None
            lhs, rhs, op = c.left, c.right, c.op
            if isinstance(lhs, ast.Literal) and \
                    isinstance(rhs, ast.ColumnRef):
                lhs, rhs = rhs, lhs
                op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(
                    op, op)
            if not (isinstance(lhs, ast.ColumnRef) and lhs.name == pk
                    and lhs.table in (None, tname)):
                return None
            ref = lit_ref(rhs)
            if ref is None or isinstance(
                    getattr(rhs, "value", None), str):
                return None
            if op == "=":
                if eq is not None:
                    return None
                eq = ref
            elif op in (">", ">="):
                if lo is not None:
                    return None
                lo, lo_strict = ref, op == ">"
            else:
                if hi is not None:
                    return None
                hi, hi_strict = ref, op == "<"
        if eq is not None and (lo is not None or hi is not None):
            return None
        if sel.order_by:
            if len(sel.order_by) != 1:
                return None
            ob = sel.order_by[0]
            if not (isinstance(ob.expr, ast.ColumnRef)
                    and ob.expr.name == pk and not ob.desc):
                return None
        limit_ref = None
        if sel.limit is not None:
            limit_ref = lit_ref(ast.Literal(sel.limit)) \
                if not isinstance(sel.limit, ast.Literal) \
                else lit_ref(sel.limit)
            if limit_ref is None:
                return None
        if getattr(sel, "offset", None):
            return None
        types = {c.name: c.type for c in schema.columns}
        pos = {c.name: i for i, c in enumerate(schema.columns)}
        if eq is not None:
            kind = "point"
        else:
            if lo is None and hi is None:
                return None
            kind = "scan"
        plan = LanePlan(
            kind=kind, table=tname, nlits=n,
            out_names=[o for o, _ in out],
            out_types=[types[s] for _, s in out],
            out_pos=[pos[s] for _, s in out],
            out_decode=[_decoder(types[s]) for _, s in out],
            pk_lit=eq, lo_lit=lo, lo_strict=lo_strict,
            hi_lit=hi, hi_strict=hi_strict, limit_lit=limit_ref)
        plan.out_pairs = list(zip(plan.out_pos, plan.out_decode))
        return plan

    def _classify_insert(self, ins, n, lit_ref):
        if ins.select is not None or ins.upsert or len(ins.rows) != 1:
            return None
        tname = ins.table
        if not self._lane_table_ok(tname):
            return None
        schema = self.store.table(tname).schema
        cols = ins.columns or schema.column_names
        if callable(cols):
            cols = cols()
        cols = list(cols)
        if len(ins.rows[0]) != len(cols):
            return None
        refs = []
        for e in ins.rows[0]:
            if isinstance(e, ast.Literal) and e.value is None:
                refs.append(_Const(None))
                continue
            r = lit_ref(e)
            if r is None:
                return None
            refs.append(r)
        # every non-listed column must be nullable or defaulted
        defaults = getattr(self.store.table(tname), "column_defaults",
                           {})
        for c in schema.columns:
            if c.name not in cols and not c.nullable \
                    and c.name not in defaults:
                return None
        if defaults:
            return None           # default exprs take the full path
        return LanePlan(kind="insert", table=tname, nlits=n,
                        ins_cols=list(cols), ins_lits=refs)

    def _classify_update(self, upd, n, lit_ref):
        tname = upd.table
        if not self._lane_table_ok(tname):
            return None
        schema = self.store.table(tname).schema
        pk = schema.primary_key[0]
        sets, slits = [], []
        for cname, e in upd.assignments:
            if cname == pk:
                return None       # pk rewrite: full path
            if not any(c.name == cname for c in schema.columns):
                return None
            if isinstance(e, ast.Literal) and e.value is None:
                slits.append(_Const(None))
                sets.append(cname)
                continue
            r = lit_ref(e)
            if r is None:
                return None
            sets.append(cname)
            slits.append(r)
        eq = self._pk_eq(upd.where, tname, pk, lit_ref)
        if eq is None:
            return None
        return LanePlan(kind="update", table=tname, nlits=n,
                        pk_lit=eq, set_cols=sets, set_lits=slits)

    def _classify_delete(self, dele, n, lit_ref):
        tname = dele.table
        if not self._lane_table_ok(tname):
            return None
        schema = self.store.table(tname).schema
        pk = schema.primary_key[0]
        eq = self._pk_eq(dele.where, tname, pk, lit_ref)
        if eq is None:
            return None
        return LanePlan(kind="delete", table=tname, nlits=n,
                        pk_lit=eq)

    @staticmethod
    def _pk_eq(where, tname, pk, lit_ref):
        if not (isinstance(where, ast.BinOp) and where.op == "="):
            return None
        lhs, rhs = where.left, where.right
        if isinstance(lhs, ast.Literal) and isinstance(
                rhs, ast.ColumnRef):
            lhs, rhs = rhs, lhs
        if not (isinstance(lhs, ast.ColumnRef) and lhs.name == pk
                and lhs.table in (None, tname)):
            return None
        if isinstance(getattr(rhs, "value", None), str):
            return None
        return lit_ref(rhs)

    # -- mirrors ----------------------------------------------------

    def _lane_mirror(self, tname: str):
        """Current mirror for `tname`, rebuilt if the columnstore
        moved underneath it (non-lane writes bump the generation)."""
        td = self.store.tables.get(tname)
        if td is None:
            raise ShapeIneligible(tname)
        m = self._lane_mirrors.get(tname)
        if m is not None and (m.synced_gen == td.generation
                              or self._lane_pending.get(tname)):
            return m
        with self._lane_lock:
            m = self._lane_mirrors.get(tname)
            if m is not None and (m.synced_gen == td.generation
                                  or self._lane_pending.get(tname)):
                return m
            self.store.seal(tname)
            m = TableMirror(self._lane_lib, td.schema)
            m.rebuild(td)
            self._lane_mirrors[tname] = m
            return m

    # -- read handlers ----------------------------------------------

    def _lane_read(self, plan: LanePlan, lits, session):
        self._stmt_lock.acquire_read()
        try:
            m = self._lane_mirror(plan.table)
            td = plan.td
            if td is None:
                td = plan.td = self.store.table(plan.table)
                plan.codec = td.codec
            read_ts = self.clock.now()
            rtsi = read_ts.to_int()
            tsc = self.kv.store.tscache
            if plan.kind == "point":
                key = int(plan.pk_lit.get(lits))
                kb = plan.codec.key_from_pk((key,))
                tsc.add(Span(kb), read_ts, None)
                got = m.read(key, rtsi)
                rows = []
                if got is not None:
                    vals, vld = got
                    rows.append(tuple(
                        dec(vals[p]) if vld[p] else None
                        for p, dec in plan.out_pairs))
                if plan.limit_lit is not None:
                    rows = rows[:max(int(plan.limit_lit.get(lits)),
                                     0)]
                return Result(names=plan.out_names, rows=rows,
                              types=plan.out_types)
            lo = (int(plan.lo_lit.get(lits))
                  if plan.lo_lit is not None else None)
            hi = (int(plan.hi_lit.get(lits))
                  if plan.hi_lit is not None else None)
            limit = (int(plan.limit_lit.get(lits))
                     if plan.limit_lit is not None else None)
            cap_var = int(session.vars.get("index_lookup_limit", 4096)
                          if session is not None else 4096)
            if limit is not None and (limit < 0 or limit > cap_var):
                return None   # compiled path; also bounds the buffer
                # allocation at cap_var (a 1e8 LIMIT must not reserve
                # gigabytes up front — review round-5 finding #6)
            cap = limit if limit is not None else cap_var + 1
            start, end = plan.codec.span()
            kb = (plan.codec.key_from_pk((lo,)) if lo is not None
                  else start)
            ke = (plan.codec.key_from_pk((hi,)) + b"\xff"
                  if hi is not None else end)
            tsc.add(Span(kb, ke), read_ts, None)
            nrow, keys, vals, vld = m.scan(lo, plan.lo_strict, hi,
                                           plan.hi_strict, rtsi, cap)
            if limit is None and nrow > cap_var:
                return None       # low selectivity: compiled path
            ncols = m.ncols
            pairs = plan.out_pairs
            vlist = vals[:nrow * ncols].tolist()
            mlist = vld[:nrow * ncols].tolist()
            out = []
            base = 0
            for r in range(nrow):
                out.append(tuple(
                    dec(vlist[base + p]) if mlist[base + p] else None
                    for p, dec in pairs))
                base += ncols
            return Result(names=plan.out_names, rows=out,
                          types=plan.out_types)
        finally:
            self._stmt_lock.release_read()

    # -- write handlers ---------------------------------------------

    def _nonlane_busy(self, table: str) -> bool:
        """A full-path statement that can read `table` is in flight
        (statement-scoped when its table set is known, global
        otherwise)."""
        return bool(self._nonlane_active
                    or self._nonlane_tables.get(table))

    def _lane_write(self, plan: LanePlan, lits, session):
        from ..kv.concurrency import TxnAbortedError, TxnRetryError
        from ..kv.txn import DB as KVDB
        from ..kv.txn import Txn
        from .dml import retry_exhausted
        self._stmt_lock.acquire_read()
        try:
            if self._nonlane_busy(plan.table):
                # a full-path statement over this table is in flight:
                # its snapshot was taken after a flush, so new lane
                # writes must queue BEHIND it — take the full path
                # instead (re-checked under _lane_sync at commit time)
                raise ShapeIneligible("nonlane active")
            if any(f.table == plan.table for f in self.cdc_feeds) \
                    or any(th.is_alive() and tb == plan.table
                           for th, tb in self._cdc_threads.values()):
                # a changefeed on THIS table consumes commits from the
                # publish path; a deferred lane publish would starve
                # it. Re-checked HERE (not just at plan build): feeds
                # register asynchronously after CREATE CHANGEFEED
                # returns. Scoped per table, and dead feed threads
                # (failed/finished jobs) do not gate anything.
                raise ShapeIneligible("changefeed active")
            m = self._lane_mirror(plan.table)
            td = self.store.table(plan.table)
            schema = td.schema
            codec = td.codec
            last = None
            for _ in range(KVDB.MAX_ATTEMPTS):
                t = Txn(self.kv.store)
                try:
                    with self._lane_sync:
                        if self._nonlane_busy(plan.table):
                            raise ShapeIneligible("nonlane active")
                        res = self._lane_write_once(plan, lits, t, m,
                                                    td, schema, codec)
                        cts = t.commit()
                        tsi = cts.to_int()
                        op = res[1]
                        if op is not None:
                            with self._lane_lock:
                                self._lane_apply_mirror(m, op, tsi)
                                self._lane_pending.setdefault(
                                    plan.table, []).append((op, tsi))
                    return res[0]
                except (TxnRetryError, TxnAbortedError) as e:
                    t.rollback()
                    last = e
                except ShapeIneligible:
                    t.rollback()
                    raise
                except BaseException:
                    t.rollback()
                    raise
            raise retry_exhausted(last)
        finally:
            self._stmt_lock.release_read()

    @staticmethod
    def _lane_apply_mirror(m: TableMirror, op, tsi: int) -> None:
        kind = op[0]
        if kind == "put":
            row = op[2]
            m.put(row[m.pk], tsi, row)
        else:
            m.delete(op[2], tsi)

    def _lane_write_once(self, plan, lits, t, m, td, schema, codec):
        rtsi = t.meta.read_ts.to_int()
        if plan.kind == "insert":
            row = {}
            for cn, ref in zip(plan.ins_cols, plan.ins_lits):
                col = schema.column(cn)
                v = ref.get(lits)
                if v is None:
                    if not col.nullable:
                        raise EngineError(
                            f"null in non-null column {cn}")
                    row[cn] = None
                else:
                    row[cn] = self._lane_coerce(col, v)
            for col in schema.columns:
                if col.name not in row:
                    if not col.nullable:
                        raise EngineError(
                            f"null in non-null column {col.name}")
                    row[col.name] = None
            key = codec.key(row)
            if t.get(key) is not None or \
                    self._lane_lib.oltp_live(m.h, int(row[m.pk]),
                                             rtsi):
                raise EngineError(
                    f"duplicate key value "
                    f"{codec.pk_values(row)!r} violates primary key "
                    f"of {plan.table!r}")
            t.put(key, codec.encode_value(row))
            return (Result(row_count=1, tag="INSERT"),
                    ("put", key, row))
        pk_val = int(plan.pk_lit.get(lits))
        key = codec.key_from_pk((pk_val,))
        # the KV read both registers the read span and surfaces
        # conflicting intents (push/abort via the txn machinery)
        t.get(key)
        got = m.read(pk_val, rtsi)
        if got is None:
            tag = "UPDATE 0" if plan.kind == "update" else "DELETE 0"
            return (Result(row_count=0, tag=tag.split()[0]), None)
        if plan.kind == "delete":
            t.delete(key)
            return (Result(row_count=1, tag="DELETE"),
                    ("del", key, pk_val))
        vals, vld = got
        row = {}
        for i, cn in enumerate(m.cols):
            row[cn] = vals[i] if vld[i] else None
        for cn, ref in zip(plan.set_cols, plan.set_lits):
            v = ref.get(lits)
            col = schema.column(cn)
            if v is None:
                if not col.nullable:
                    raise EngineError(f"null in non-null column {cn}")
                row[cn] = None
            else:
                row[cn] = self._lane_coerce(col, v)
        t.put(key, codec.encode_value(row))
        return (Result(row_count=1, tag="UPDATE"), ("put", key, row))

    # -- batch windows (exec/oltpbatch.py drives these) -------------

    def _lane_read_batch(self, reqs) -> None:
        """One fused multi-key probe for a window of point reads:
        a single statement-gate acquisition, one read timestamp, and
        one native `multiread` per table instead of len(reqs) point
        reads. Each request's tscache span is still registered
        individually, so writers see exactly the spans the
        per-statement path would have left behind."""
        self._stmt_lock.acquire_read()
        try:
            read_ts = self.clock.now()
            rtsi = read_ts.to_int()
            tsc = self.kv.store.tscache
            groups: dict = {}
            for req in reqs:
                groups.setdefault(req.plan.table, []).append(req)
            for tname, group in groups.items():
                try:
                    m = self._lane_mirror(tname)
                except ShapeIneligible as e:
                    for req in group:
                        req.error = e
                    continue
                keys = []
                for req in group:
                    plan = req.plan
                    if plan.td is None:
                        plan.td = self.store.table(tname)
                        plan.codec = plan.td.codec
                    key = int(plan.pk_lit.get(req.lits))
                    tsc.add(Span(plan.codec.key_from_pk((key,))),
                            read_ts, None)
                    keys.append(key)
                vals, vld, fnd = m.multiread(keys, rtsi)
                ncols = m.ncols
                for i, req in enumerate(group):
                    plan = req.plan
                    rows = []
                    if fnd[i]:
                        base = i * ncols
                        rows.append(tuple(
                            dec(vals[base + p])
                            if vld[base + p] else None
                            for p, dec in plan.out_pairs))
                    if plan.limit_lit is not None:
                        rows = rows[:max(
                            int(plan.limit_lit.get(req.lits)), 0)]
                    req.result = Result(names=plan.out_names,
                                        rows=rows,
                                        types=plan.out_types)
        finally:
            self._stmt_lock.release_read()

    def _lane_write_batch(self, reqs) -> None:
        """Group commit for a window of single-row writes: the window
        splits into rounds with at most one write per (table, pk) —
        a second write to the same key must observe the first's
        committed value, which a shared transaction cannot give it —
        and each round commits as ONE kv transaction (one WAL-append
        analogue) while every waiter still gets its own Result or
        statement error."""
        self._stmt_lock.acquire_read()
        try:
            live = []
            for req in reqs:
                tname = req.plan.table
                if self._nonlane_busy(tname):
                    # a full-path statement over this table is in
                    # flight: its waiters fall back to the full path,
                    # same as the per-statement lane
                    req.error = ShapeIneligible("nonlane active")
                elif any(f.table == tname for f in self.cdc_feeds) \
                        or any(th.is_alive() and tb == tname
                               for th, tb in
                               self._cdc_threads.values()):
                    req.error = ShapeIneligible("changefeed active")
                else:
                    live.append(req)
            while live:
                seen: set = set()
                this_round, defer = [], []
                for req in live:
                    k = (req.plan.table, self._lane_req_pk(req))
                    if k in seen:
                        defer.append(req)
                    else:
                        seen.add(k)
                        this_round.append(req)
                self._lane_write_round(this_round)
                live = defer
        finally:
            self._stmt_lock.release_read()

    def _lane_req_pk(self, req):
        """Primary-key value a write request targets (dedup key for
        round-splitting). Uncoercible values pass through raw — the
        round surfaces the real statement error."""
        plan, lits = req.plan, req.lits
        if plan.kind == "insert":
            pk = self.store.table(plan.table).schema.primary_key[0]
            for cn, ref in zip(plan.ins_cols, plan.ins_lits):
                if cn == pk:
                    v = ref.get(lits)
                    try:
                        return int(v)
                    except (TypeError, ValueError):
                        return v
            return None
        return int(plan.pk_lit.get(lits))

    def _lane_write_round(self, reqs) -> None:
        from ..kv.concurrency import TxnAbortedError, TxnRetryError
        from ..kv.txn import DB as KVDB
        from ..kv.txn import Txn
        from ..kvserver.raft import GROUPCOMMIT
        from .dml import retry_exhausted
        ctx: dict = {}
        for req in reqs:
            tname = req.plan.table
            if tname not in ctx:
                m = self._lane_mirror(tname)
                td = self.store.table(tname)
                ctx[tname] = (m, td, td.schema, td.codec)
        last = None
        for _ in range(KVDB.MAX_ATTEMPTS):
            t = Txn(self.kv.store)
            try:
                with self._lane_sync:
                    if self._nonlane_active or any(
                            self._nonlane_tables.get(tn)
                            for tn in ctx):
                        raise ShapeIneligible("nonlane active")
                    outcomes = []
                    for req in reqs:
                        m, td, schema, codec = ctx[req.plan.table]
                        try:
                            res = self._lane_write_once(
                                req.plan, req.lits, t, m, td,
                                schema, codec)
                        except (EngineError, ShapeIneligible) as e:
                            # per-statement errors all raise BEFORE
                            # t.put, so the shared txn carries no
                            # trace of the failed request
                            outcomes.append((req, None, e))
                        else:
                            outcomes.append((req, res, None))
                    cts = t.commit()   # ONE commit for the round
                    tsi = cts.to_int()
                    nops = 0
                    with self._lane_lock:
                        for req, res, err in outcomes:
                            if res is None or res[1] is None:
                                continue
                            op = res[1]
                            self._lane_apply_mirror(
                                ctx[req.plan.table][0], op, tsi)
                            self._lane_pending.setdefault(
                                req.plan.table, []).append((op, tsi))
                            nops += 1
                if nops:
                    GROUPCOMMIT.bump(nops)
                for req, res, err in outcomes:
                    if err is not None:
                        req.error = err
                    else:
                        req.result = res[0]
                return
            except (TxnRetryError, TxnAbortedError) as e:
                t.rollback()
                last = e
            except ShapeIneligible:
                t.rollback()
                raise
            except BaseException:
                t.rollback()
                raise
        raise retry_exhausted(last)

    @staticmethod
    def _lane_coerce(col, v):
        f = col.type.family
        if f == Family.INT:
            return int(v)
        if f == Family.BOOL:
            return bool(v)
        if f == Family.DECIMAL and isinstance(v, int):
            return v * 10 ** col.type.scale
        raise ShapeIneligible(f"uncoercible {f}")

    # -- deferred publish -------------------------------------------

    def lane_flush(self, tables=None) -> None:
        """Publish queued lane writes to the columnstore. Caller holds
        the write side of the statement gate. ``tables`` limits the
        publish to those tables' queues (statement-scoped flush:
        engine.execute_stmt flushes only what the statement can read,
        so an analytic query never pays another table's upload)."""
        with self._lane_lock:
            if tables is None:
                pending = self._lane_pending
                self._lane_pending = {}
            else:
                pending = {}
                for t in tables:
                    e = self._lane_pending.pop(t, None)
                    if e:
                        pending[t] = e
        for table, entries in pending.items():
            entries.sort(key=lambda e: e[1])
            batches = []
            for op, tsi in entries:
                if batches and batches[-1][1] == tsi:
                    batches[-1][0].append(self._store_op(op))
                else:
                    batches.append(([self._store_op(op)], tsi))
            self.store.apply_committed_batch(table, batches)
            self._evict(table)
            m = self._lane_mirrors.get(table)
            if m is not None:
                m.synced_gen = self.store.table(table).generation

    @staticmethod
    def _store_op(op):
        if op[0] == "put":
            return ("put", op[1], op[2])
        return ("del", op[1])


def _decoder(ty):
    """Per-type storage-int -> client-value decoder."""
    from .stmtutil import _decode_scalar
    f = ty.family
    if f == Family.INT:
        return int
    if f == Family.BOOL:
        return bool
    return lambda v, _t=ty: _decode_scalar(v, True, _t, None)
