"""Composed device-resident execution of CTE / derived-table statements.

The row-path architecture (engine._exec_with_temps) materializes each
CTE body through the host: run the sub-program, pull its live rows over
the tunnel (~0.1-0.2s), insert into a temp columnstore table, re-upload
for the main program's scan, and re-plan per execution. That is the
right SLOW path (it feeds stats, join checks, and arbitrary consumers),
but a steady-state prepared statement re-executing against unchanged
base tables pays ~3 tunnel round trips + a re-plan for nothing.

This module captures the pieces of one successful slow-path execution
— the sub Prepared programs, the main Prepared program, and the temp
batch shapes the main was compiled against — and composes them into a
single device-resident pipeline:

    sub jfn -> glue (jitted: compact live rows into the temp batch
    shape, synthesize MVCC columns) -> main jfn -> one materialize

No host transfer happens between stages; the only sync is the final
result pull. The reference's analogue is a WithExpr spool feeding its
readers in-memory (sql/opt WithExpr; here the buffer never leaves HBM).

Validity: the composition is only used when every non-temp table's
generation is unchanged and the session holds no transaction — then
the sub's visible rows (and so the temp's row count and dictionary
contents) are identical to the captured run. Any drift, glue overflow,
or sub-program sentinel falls back to the slow path (the glue folds
sub sentinels and the live-count check into a __compact_overflow flag
the engine already knows how to honor).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.batch import _pow2
from .session import SENTINEL_COLUMNS as _SENTINELS

_DEAD_TS = np.int64(2 ** 62)


def make_glue(template, cname_to_oname: dict, dict_clip: dict,
              w2: int):
    """Jitted sub-output -> temp-scan-batch adapter.

    template: the captured device batch the main program was compiled
    against (names/dtypes/order are the jit pytree contract).
    cname_to_oname: temp stored column name -> sub output column name.
    dict_clip: temp column -> dictionary length (codes clipped like the
    slow path's ingest).
    w2: the temp batch's padded width (pow2, matches the capture run).
    Returns glue(b) -> (ColumnBatch, overflow_flag_scalar)."""
    names = list(template.names)
    dtypes = {nm: template.col(nm).dtype for nm in names}

    @jax.jit
    def glue(b):
        from ..ops.batch import ColumnBatch
        n = b.n
        sel = b.sel
        live_cnt = jnp.sum(sel.astype(jnp.int32))
        (idx,) = jnp.nonzero(sel, size=w2, fill_value=n)
        row_ok = idx < n
        idx_c = jnp.minimum(idx, n - 1).astype(jnp.int32)
        cols, valid = {}, {}
        for nm in names:
            if nm == "_mvcc_ts":
                cols[nm] = jnp.where(row_ok, jnp.int64(1),
                                     jnp.int64(_DEAD_TS))
                continue
            if nm == "_mvcc_del":
                cols[nm] = jnp.full((w2,), np.int64(2 ** 63 - 1),
                                    jnp.int64)
                continue
            oname = cname_to_oname[nm]
            d = jnp.take(b.col(oname), idx_c, axis=0)
            v = jnp.logical_and(jnp.take(b.col_valid(oname), idx_c),
                                row_ok)
            clip = dict_clip.get(nm)
            if clip is not None:
                d = jnp.clip(d.astype(jnp.int32), 0, max(clip - 1, 0))
            d = d.astype(dtypes[nm])
            cols[nm] = d
            valid[nm] = v
        overflow = live_cnt > w2
        for s in _SENTINELS:
            if b.has(s):
                overflow = jnp.logical_or(overflow, jnp.any(b.col(s)))
        return ColumnBatch.from_dict(cols, valid), overflow

    return glue


@dataclass
class _Stage:
    prep: object          # the sub's Prepared
    # one jitted adapter PER consuming alias: prune_scan_columns can
    # give two scans of the same CTE different column subsets, so
    # each alias gets a glue shaped to ITS captured template
    glues: list           # [(alias, glue_fn), ...]


@dataclass
class ComposedCTE:
    engine: object
    session: object
    base_gens: tuple      # ((table, generation), ...) — temps excluded
    stages: list
    main: object          # the main Prepared

    def valid(self) -> bool:
        if self.session.txn is not None or self.session.effects:
            return False
        store = self.engine.store
        for t, g in self.base_gens:
            td = store.tables.get(t)
            if td is None or td.generation != g:
                return False
        return True

    def dispatch(self, read_ts=None):
        """Launch the whole pipeline asynchronously; returns the final
        device batch (sentinel-annotated). Nothing blocks — a caller
        can pipeline several dispatches before syncing."""
        eng = self.engine
        ts = read_ts or eng._read_ts(self.session)
        tsv = np.int64(ts.to_int())
        one, zero = np.int32(1), np.int32(0)
        scans = dict(self.main.scans)
        flags = []
        for st in self.stages:
            sub_out = st.prep.jfn(st.prep.scans, tsv, one, zero)
            for a, glue in st.glues:
                batch, ovf = glue(sub_out)
                flags.append(ovf)
                scans[a] = batch
        out = self.main.jfn(scans, tsv, one, zero)
        flag = flags[0]
        for f in flags[1:]:
            flag = jnp.logical_or(flag, f)
        if out.has("__compact_overflow"):
            flag = jnp.logical_or(flag,
                                  jnp.any(out.col("__compact_overflow")))
        return out.with_column("__compact_overflow",
                               jnp.broadcast_to(flag, (out.n,)))

    def run(self, read_ts=None):
        out = self.dispatch(read_ts)
        return self.engine._materialize(out, self.main.meta)


def build_composition(engine, session, capture) -> ComposedCTE | None:
    """Assemble a ComposedCTE from one successful slow-path capture,
    or None when the shape can't compose (row-path temps, streaming,
    AS OF, temp-on-temp dependencies, fastpath mains)."""
    if (not capture or capture.get("disabled") or not capture["temps"]
            or not capture["preps"]):
        return None
    main = capture["preps"][-1]
    if main.stream is not None or main.as_of is not None:
        return None
    # parameterized programs (statement-shape plan cache) take their
    # literals as a 5th runtime arg; the composed dispatch is a fixed
    # 4-arg pipeline, so keep the slow path for those
    if any(getattr(p, "params", ()) for p in capture["preps"]):
        return None
    scan_tables = getattr(main, "scan_tables", None)
    if not scan_tables:
        return None
    temp_names = {t["tname"] for t in capture["temps"]}
    for t in capture["temps"]:
        p = t["prep"]
        if p.stream is not None or p.as_of is not None:
            return None
        if any(tb in temp_names for tb, _ in p.gens):
            return None  # temp scanning another temp: keep slow path
    base = {}
    for p in [main] + [t["prep"] for t in capture["temps"]]:
        for tb, g in p.gens:
            if tb in temp_names:
                continue
            if base.get(tb, g) != g:
                return None
            base[tb] = g
    stages = []
    temp_aliases = []
    for t in capture["temps"]:
        aliases = [a for a, tn in scan_tables.items()
                   if tn == t["tname"]]
        if not aliases:
            continue  # CTE declared but never scanned by the main
        meta = t["meta"]
        cname_to_oname = dict(zip(t["names"], meta.names))
        dict_clip = {}
        for cname, oname in cname_to_oname.items():
            d = meta.dictionaries.get(oname)
            if d is not None:
                dict_clip[cname] = len(d)
        w2 = max(_pow2(max(t["rows"], 1)), 1024)
        glues = []
        for a in aliases:
            template = main.scans.get(a)
            if template is None:
                return None
            if any(nm not in cname_to_oname
                   for nm in template.names
                   if nm not in ("_mvcc_ts", "_mvcc_del")):
                return None
            if w2 != template.n:
                return None  # shape drift vs main's compiled input
            glues.append((a, make_glue(template, cname_to_oname,
                                       dict_clip, w2)))
        stages.append(_Stage(prep=t["prep"], glues=glues))
        temp_aliases.extend(aliases)
    if not stages:
        return None
    # release the dropped temps' captured upload batches: the temp
    # tables were dropped (and their HBM reservation released) by
    # _exec_with_temps' cleanup, so holding the device arrays here
    # would keep untracked HBM resident — every composed dispatch
    # replaces these entries anyway
    for a in temp_aliases:
        main.scans[a] = None
    return ComposedCTE(engine=engine, session=session,
                       base_gens=tuple(sorted(base.items())),
                       stages=stages, main=main)
