"""Session, results, errors, prepared statements (connExecutor session state,
pkg/sql/conn_executor.go; prepared portals, pgwire/command_result.go).

Split out of exec/engine.py (round-2 VERDICT Weak #4); see that
module's docstring for the overall execution model."""


import datetime
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..kv.txn import Txn
from ..ops.batch import ColumnBatch
from ..sql import ast
from ..storage.hlc import Timestamp
from ..utils.settings import SessionVars

EPOCH_DATE = datetime.date(1970, 1, 1)
EPOCH_DT = datetime.datetime(1970, 1, 1)
class EngineError(Exception):
    pass


class HashCapacityExceeded(EngineError):
    """GROUP BY distinct-key count exceeded the device hash table.
    Prepared.run catches this and falls back to hash-partitioned
    re-execution (the spill path)."""


class TopKInexact(EngineError):
    """The fused top-k ORDER BY ... LIMIT cut crossed a primary-key
    tie group (compile.py topk_sort_limit_batch). Prepared.run
    catches this and replans with the full device sort."""


class CompactOverflow(EngineError):
    """A selection-compaction block held more selected rows than its
    capacity (compile.py compact_batch) — results would be missing
    rows. Prepared.run catches this and replans uncompacted."""


# The one registry of device error-sentinel column names. Every
# consumer (result materialization, CTE temp ingest, composed-CTE
# glue) derives from this so a new sentinel cannot be silently missed
# by one of them.
SENTINEL_COLUMNS = ("__ht_overflow", "__sum_overflow",
                    "__topk_inexact", "__compact_overflow")


@dataclass
class Result:
    """Decoded query result."""
    names: list[str] = field(default_factory=list)
    rows: list[tuple] = field(default_factory=list)
    row_count: int = 0  # for DML
    tag: str = "SELECT"
    types: list = field(default_factory=list)  # SQLTypes (SELECT only)

    def column(self, name: str) -> list:
        i = self.names.index(name)
        return [r[i] for r in self.rows]

    def __len__(self):
        return len(self.rows)


@dataclass(eq=False)  # identity-hashed: sessions live in a WeakSet
class Session:
    """Session state (the connExecutor's session data,
    sessiondatapb/session_data.go). An open explicit transaction holds
    a real kv.Txn: DML writes intents through it and buffers its
    scan-plane effects; COMMIT publishes them at the commit timestamp,
    ROLLBACK discards them (the reference's connExecutor txn state
    machine, conn_executor.go:1835)."""
    vars: SessionVars = field(default_factory=SessionVars)
    txn: Optional[Txn] = None
    # ordered (table, op) effects: ("put", key, row) | ("del", key)
    effects: list = field(default_factory=list)
    # a failed statement aborts the whole txn (postgres semantics:
    # "current transaction is aborted" until ROLLBACK) — this keeps
    # statements atomic without kv-level savepoints
    txn_aborted: bool = False
    # SET tracing = on: span recordings per statement, rendered by
    # SHOW TRACE FOR SESSION (the reference's session tracing)
    trace: list = field(default_factory=list)
    # currval() state: sequence name -> last nextval in this session
    seq_currval: dict = field(default_factory=dict)

    @property
    def in_txn(self) -> bool:
        return self.txn is not None

    @property
    def txn_read_ts(self) -> Optional[Timestamp]:
        return self.txn.meta.read_ts if self.txn is not None else None


@dataclass
class Prepared:
    """A planned+compiled SELECT bound to device-resident tables.

    ``dispatch()`` is asynchronous (returns the device-side output
    batch immediately, XLA-style); ``run()`` dispatches and
    materializes. The read timestamp is taken per execution and the
    bound device tables are re-resolved if any scanned table's
    generation moved (DML re-uploads), so a prepared statement sees
    current data under the session's isolation rules, like a pgwire
    portal re-executed after Bind."""

    engine: "Engine"
    session: "Session"
    stmt: "ast.Select"
    sql_text: str
    jfn: object
    scans: dict
    meta: object
    gens: tuple  # ((table, generation), ...) captured at prepare time
    # beyond-HBM paging: (alias, page_rows) of the streamed fact table
    stream: Optional[tuple] = None
    stream_cols: Optional[frozenset] = None
    # zone-map checks compiled from the streamed scan's pushed-down
    # predicates (exec/stream.extract_zone_preds): pages whose chunk
    # summaries cannot satisfy them never upload
    stream_zone: tuple = ()
    # AS OF SYSTEM TIME: fixed historical read timestamp
    as_of: Optional[Timestamp] = None
    # out-of-core tier (exec/spill.py): the planner's SpillPlan when
    # this statement executes as a partitioned external hash join or
    # an external merge sort; spill_cols is the build side's pruned
    # column set (the probe's rides stream_cols)
    spill: Optional[object] = None
    spill_cols: Optional[frozenset] = None
    # join-induced skipping (exec/joinfilter.py): JoinFilterSpecs
    # detected at prepare over the streamed/spilled probe alias; each
    # dispatch derives the build-side key summary at its read
    # timestamp and feeds it into the probe's zone predicates
    joinfilter: tuple = ()
    # statement-shape plan cache (exec/planparam.py): THIS statement's
    # literal values, riding each dispatch as runtime scalars into the
    # shared parameterized executable; () = unparameterized
    params: tuple = ()

    def _refresh(self) -> "Prepared":
        cur = tuple((t, self.engine.store.table(t).generation)
                    for t, _ in self.gens)
        if cur == self.gens:
            return self
        return self.engine._prepare_select(self.stmt, self.session,
                                           self.sql_text)

    def _adopt(self, p: "Prepared") -> None:
        """Copy a re-prepared statement's execution state into this
        handle (generation-refresh keeps the caller's object)."""
        self.jfn, self.scans, self.meta, self.gens = \
            p.jfn, p.scans, p.meta, p.gens
        self.stream, self.stream_cols = p.stream, p.stream_cols
        self.stream_zone = p.stream_zone
        self.spill, self.spill_cols = p.spill, p.spill_cols
        self.joinfilter = p.joinfilter
        self.params = p.params
        self.as_of = p.as_of  # keep guard + execution timestamps
        # consistent (interval forms re-resolve on refresh)

    def _join_filters(self, tsv) -> tuple:
        """Derive this dispatch's semi-join filters (join-induced
        data skipping, exec/joinfilter.py). ``SET join_filter =
        auto|on|off``: off is the bench A/B arm, on lifts auto's
        build-size cap."""
        if not self.joinfilter:
            return ()
        mode = self.session.vars.get("join_filter", "auto")
        if isinstance(mode, bool):
            mode = "on" if mode else "off"
        mode = str(mode).lower()
        if mode not in ("auto", "on"):
            return ()
        from . import joinfilter as jf
        out = []
        for spec in self.joinfilter:
            f = jf.derive(self.engine, spec, int(tsv), mode)
            if f is not None:
                out.append(f)
        return tuple(out)

    def dispatch(self, read_ts: Optional[Timestamp] = None,
                 nparts: int = 1, pid: int = 0) -> ColumnBatch:
        p = self._refresh()
        if p is not self:
            self._adopt(p)
        ts = read_ts or self.as_of or \
            self.engine._read_ts(self.session)
        # np scalar: a jnp.int64() upload would cost a blocking
        # host->device round trip before the query even dispatches.
        tsv = np.int64(ts.to_int())
        if self.spill is not None:
            if self.spill.kind != "join":
                raise EngineError(
                    "spill-sort statements materialize host-side; "
                    "use Prepared.run()")
            from .spill import run_spill_join
            return run_spill_join(self.engine, self, tsv)
        if self.stream is None:
            return self.jfn(self.scans, tsv, np.int32(nparts),
                            np.int32(pid), self.params)
        # paged execution through the prefetch pipeline: a bounded
        # background worker assembles+uploads page i+1 while the
        # device computes page i, and zone-pruned pages never move
        # (the double-buffering of the reference's byte-limited KV
        # paging, kv_batch_fetcher.go:191, plus its zone-map-style
        # span pruning). `streaming_pipeline = off` keeps the same
        # iterator synchronous (bench A/B + debugging).
        _alias, tname, page_rows = self.stream
        fns: _StreamFns = self.jfn
        state = None
        scans = dict(self.scans)
        pipeline = self.session.vars.get("streaming_pipeline",
                                         "on") != "off"
        zpreds = self.stream_zone
        filters = self._join_filters(tsv)
        if filters:
            from .joinfilter import zone_pred
            zpreds = zpreds + tuple(zone_pred(f) for f in filters)
        pages = self.engine._stream_pages(
            tname, self.stream_cols, page_rows,
            zone_preds=zpreds, pipeline=pipeline, read_ts=int(tsv))
        try:
            for page in pages:
                scans[_alias] = page
                s = fns.page(scans, tsv)
                state = s if state is None else fns.combine(state, s)
        finally:
            close = getattr(pages, "close", None)
            if close is not None:
                close()  # join the prefetch worker on any exit
        if state is None:
            # zone maps pruned EVERY page: run one never-visible
            # padding page so the aggregate still yields its empty
            # state (COUNT 0, NULL sums) instead of a shape error
            scans[_alias] = self.engine._page_source(
                tname, self.stream_cols, page_rows).empty_page()
            state = fns.page(scans, tsv)
        return fns.final(state)

    def warm(self, bucket: int = 0) -> None:
        """Compile this statement's streamed-page / spill-partition
        executables without touching real data (Engine.prewarm): run
        one never-visible padding batch at the journaled shape
        ``bucket`` through the page/combine/final pipeline — the
        empty-page path every all-pages-skipped execution already
        exercises, so the traced program is exactly the one real
        dispatches reuse."""
        import jax
        tsv = np.int64(self.engine._read_ts(self.session).to_int())
        scans = dict(self.scans)
        if self.spill is not None and self.spill.kind == "join":
            sp = self.spill
            psrc = self.engine._page_source(
                sp.table, self.stream_cols, sp.page_rows)
            bsrc = self.engine._page_source(
                sp.build_table, self.spill_cols, 1024)
            bpad = bucket or self.engine._row_bucket(1)
            scans[sp.build_alias] = bsrc.gather_batch(
                np.zeros(0, dtype=np.int64), bpad)
            scans[sp.alias] = psrc.empty_page()
            s = self.jfn.page(scans, tsv)
            s = self.jfn.combine(s, s)
            jax.block_until_ready(self.jfn.final(s))
            return
        if self.spill is not None:  # spill-sort: one per-run program
            sp = self.spill
            src = self.engine._page_source(
                sp.table, self.stream_cols, sp.page_rows)
            scans[sp.alias] = src.empty_page()
            jax.block_until_ready(self.jfn(scans, tsv))
            return
        if self.stream is not None:
            _alias, tname, page_rows = self.stream
            src = self.engine._page_source(
                tname, self.stream_cols, bucket or page_rows)
            scans[_alias] = src.empty_page()
            s = self.jfn.page(scans, tsv)
            s = self.jfn.combine(s, s)
            jax.block_until_ready(self.jfn.final(s))
            return
        jax.block_until_ready(self.dispatch())

    def run(self, read_ts: Optional[Timestamp] = None) -> "Result":
        tracer = self.engine.tracer
        p = self._refresh()
        if p is not self:
            self._adopt(p)
        if self.spill is not None and self.spill.kind == "sort":
            # the external merge sort's tail runs on the host (run
            # merge + decode in one pass), so there is no device
            # batch to materialize separately
            from .spill import run_spill_sort
            ts = read_ts or self.as_of or \
                self.engine._read_ts(self.session)
            with tracer.span("dispatch"):
                return run_spill_sort(self.engine, self,
                                      np.int64(ts.to_int()))
        from ..parallel.distagg import CollectiveFault
        try:
            with tracer.span("dispatch"):
                out = self.dispatch(read_ts)
            with tracer.span("materialize"):
                return self.engine._materialize(out, self.meta)
        except CollectiveFault:
            # an injected ICI fault lost this plan's collective
            # dispatch: retry gateway-local, the reference's DistSQL
            # fallback when remote flow setup fails (distsql_running)
            prev = self.session.vars.get("distsql", "auto")
            self.session.vars.set("distsql", "off")
            try:
                return self.engine._prepare_select(
                    self.stmt, self.session,
                    self.sql_text).run(read_ts)
            finally:
                self.session.vars.set("distsql", prev)
        except HashCapacityExceeded:
            # partition-and-recurse (the reference's disk spiller,
            # colexecdisk/disk_spiller.go:75, over HBM re-reads).
            # This recovery does NOT re-prepare, so a CTE capture in
            # progress would compose the overflowing program and pay
            # a doomed device pipeline on every steady-state re-run —
            # keep such statements on the slow path
            if self.engine._cte_capture is not None:
                self.engine._cte_capture["disabled"] = True
            try:
                return self.engine._run_partitioned(self, read_ts)
            except CompactOverflow:
                return self.engine._prepare_select(
                    self.stmt, self.session, self.sql_text,
                    no_compact=True).run(read_ts)
        except TopKInexact:
            # primary-key ties crossed the top-k candidate cut:
            # replan with the full (slow-to-compile, always-exact)
            # device sort
            return self.engine._prepare_select(
                self.stmt, self.session, self.sql_text,
                no_topk=True).run(read_ts)
        except CompactOverflow:
            # the stats-estimated selectivity undershot: replan with
            # the full-width masked pipeline (always exact)
            return self.engine._prepare_select(
                self.stmt, self.session, self.sql_text,
                no_compact=True).run(read_ts)


