"""Selector-driven pgwire front end: 10K sessions, threads ~ active.

The thread-per-connection front door (`pgwire._ThreadServer`) costs a
~8MB-stack thread per session whether or not it is doing anything — a
production front door parks tens of thousands of mostly-idle
connections. This module is the Theseus framing applied to scheduler
resources: never let an idle resource (a parked session) hold a scarce
one (a thread / GIL quantum).

Architecture — one event-loop thread owns every socket:

- ``selectors.DefaultSelector`` (epoll on Linux) watches the listener
  and every connection, all non-blocking. The loop's only jobs are
  accept, ``recv`` into per-session byte buffers, frame parsing, and
  timer sweeps — it NEVER executes SQL, authenticates, flushes
  replies, or takes an engine lock (enforced by graftlint's
  ``reactor-discipline`` rule).
- Complete frames land in a per-session queue. A session with queued
  frames and no worker gets ONE — workers come from a bounded
  ``ThreadPoolExecutor``, so thread count tracks *active statements*,
  not connections; an idle session's cost is one socket + one
  ``_Session`` record (O(1) memory, zero threads).
- Workers drive the exact same ``_Conn.process`` handlers as the
  thread front end, writing replies straight to the socket through a
  select-backed ``sendall`` that tolerates the non-blocking fd. One
  worker per session at a time, so reply ordering is preserved and
  the two front ends are bit-identical on the wire (the A/B lever).
- Multi-message operations that must read mid-handler (SCRAM's two
  SASL legs, cleartext password, COPY's data stream) block their
  WORKER on the session's frame queue via ``_QueueReader`` — never
  the loop.
- Sweeps: a connection that has not completed startup within
  ``server.startup_deadline_seconds`` is closed (slow-loris can't pin
  the front door); a session idle outside a transaction longer than
  ``server.idle_session_timeout`` is retired. Half-closed sockets
  (RST, FIN) surface as EOF/errors on the loop and tear down through
  one idempotent path — no handler thread left behind.
"""

from __future__ import annotations

import collections
import os
import select as _select
import selectors
import socket
import struct
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from . import pgwire as _pg

# GIL switch quantum to restore when sql.exec.switch_interval is 0
# (captured before anything changes it)
_DEFAULT_SWITCH_INTERVAL = sys.getswitchinterval()

# a worker blocked on a mid-handler read (COPY data, SASL leg) gives
# up after this long without a frame; the startup/idle sweeps usually
# retire the session first
_INLINE_READ_TIMEOUT = 3600.0

_RECV_CHUNK = 1 << 16


def apply_switch_interval(settings) -> None:
    """Arm sys.setswitchinterval from sql.exec.switch_interval
    (process-global — the GIL has one quantum; 0 restores the
    interpreter default). A sub-default quantum lets OLTP batch
    windows close while an analytic statement holds the GIL."""
    try:
        v = float(settings.get("sql.exec.switch_interval"))
    except Exception:
        return
    try:
        sys.setswitchinterval(v if v > 0 else _DEFAULT_SWITCH_INTERVAL)
    except (ValueError, OSError):
        pass


def _nb_sendall(sock: socket.socket, data: bytes,
                timeout: float = 30.0) -> None:
    """sendall for a non-blocking socket: spin send(), parking on
    select(write) when the kernel buffer is full. Worker-thread only —
    the event loop never writes more than a 1-byte startup reply."""
    view = memoryview(data)
    while view.nbytes:
        try:
            n = sock.send(view)
        except (BlockingIOError, InterruptedError):
            _, wl, _ = _select.select([], [sock], [], timeout)
            if not wl:
                raise ConnectionError("pgwire send timed out")
            continue
        view = view[n:]


class _QueueReader:
    """Drop-in for pgwire._Reader whose message() pops the session's
    frame queue (fed by the event loop) instead of recv()ing. Lets
    handlers that read mid-operation (COPY, SASL) run unchanged on
    worker threads."""

    def __init__(self, sess: "_Session"):
        self._sess = sess

    def message(self):
        s = self._sess
        with s.lk:
            while not s.frames:
                if s.eof or s.closed:
                    raise ConnectionError("client disconnected")
                if not s.cv.wait(timeout=_INLINE_READ_TIMEOUT):
                    raise ConnectionError("inline read timed out")
            return s.frames.popleft()

    def startup(self):  # pragma: no cover - loop owns startup framing
        raise _pg.ProtocolError("startup packets are parsed by the "
                                "reactor loop")


class _Session:
    """Per-connection reactor state: O(1) while idle."""

    __slots__ = ("sock", "fd", "buf", "framing", "frames", "lk", "cv",
                 "active", "eof", "closed", "ready", "t_conn", "t_last",
                 "conn")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.fd = sock.fileno()
        self.buf = bytearray()
        self.framing = "startup"       # -> "typed" after PROTO_V3
        self.frames: collections.deque = collections.deque()
        self.lk = threading.Lock()
        self.cv = threading.Condition(self.lk)
        self.active = False            # a worker owns this session now
        self.eof = False
        self.closed = False
        self.ready = False             # startup + auth completed
        self.t_conn = time.monotonic()
        self.t_last = self.t_conn
        self.conn = None               # pgwire._Conn


class ReactorServer:
    """The selector front end behind the PgServer facade."""

    def __init__(self, parent, host: str, port: int,
                 max_workers: int | None = None):
        self.parent = parent
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(512)
        self._lsock.setblocking(False)
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._lsock, selectors.EVENT_READ, None)
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)
        self._sessions: dict[int, _Session] = {}
        # sockets retired by workers, pending loop-side unregister +
        # close (fd lifecycle stays with the loop: closing a watched
        # fd from another thread races the selector)
        self._dead: collections.deque = collections.deque()
        self._stopping = False
        self._thread: threading.Thread | None = None
        if max_workers is None:
            max_workers = max(8, min(32, (os.cpu_count() or 4) * 2))
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="pgfront-worker")
        self._t_sweep = 0.0
        m = parent.engine.metrics
        m.func_gauge(
            "pgwire.sessions.connected",
            lambda: len(self._sessions),
            "pgwire sessions the reactor currently owns")
        m.func_gauge(
            "pgwire.sessions.active", self._count_active,
            "reactor sessions a worker thread is serving right now")
        m.func_gauge(
            "pgwire.sessions.idle",
            lambda: max(0, len(self._sessions) - self._count_active()),
            "reactor sessions parked with no thread (connected-active)")
        self._m_lag = m.histogram(
            "pgwire.reactor.loop_lag_seconds",
            "event-loop wake-batch processing time (s): how long a "
            "newly readable socket can wait behind one loop pass")

    def _count_active(self) -> int:
        try:
            return sum(1 for s in list(self._sessions.values())
                       if s.active)
        except RuntimeError:  # dict resized mid-scrape; scrape-only
            return 0

    @property
    def addr(self):
        return self._lsock.getsockname()[:2]

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="pgfront-loop", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stopping = True
        self._wakeup()
        if self._thread:
            self._thread.join(timeout=5)
        self._pool.shutdown(wait=False)
        for s in list(self._sessions.values()):
            with s.lk:
                s.eof = True
                s.closed = True
                s.cv.notify_all()
            try:
                s.sock.close()
            except OSError:
                pass
        self._sessions.clear()
        try:
            self._sel.close()
        except Exception:
            pass
        try:
            self._lsock.close()
        except OSError:
            pass
        os.close(self._wake_r)
        os.close(self._wake_w)

    def _wakeup(self):
        try:
            os.write(self._wake_w, b"x")
        except OSError:
            pass

    # -- event loop (the only thread that touches the selector) --------------

    def _loop(self):
        sel = self._sel
        while not self._stopping:
            try:
                events = sel.select(timeout=0.25)
            except OSError:
                if self._stopping:
                    return
                continue
            t0 = time.monotonic()
            self._reap_dead()
            for key, _mask in events:
                if self._stopping:
                    return
                if key.fileobj is self._lsock:
                    self._accept()
                elif key.fd == self._wake_r:
                    try:
                        os.read(self._wake_r, 4096)
                    except OSError:
                        pass
                else:
                    sess = self._sessions.get(key.fd)
                    if sess is not None:
                        self._readable(sess)
            if events:
                self._m_lag.observe(time.monotonic() - t0)
            self._sweep()

    def _reap_dead(self):
        while self._dead:
            sock = self._dead.popleft()
            try:
                self._sel.unregister(sock)
            except (KeyError, ValueError, OSError):
                pass
            try:
                sock.close()
            except OSError:
                pass

    def _accept(self):
        while True:
            try:
                sock, _addr = self._lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            try:
                sock.setblocking(False)
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            except OSError:
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            sess = _Session(sock)
            sess.conn = self.parent.new_conn(
                sock, reader=_QueueReader(sess),
                sendall=lambda d, _s=sock: _nb_sendall(_s, d))
            self._sessions[sess.fd] = sess
            self._sel.register(sock, selectors.EVENT_READ, sess)

    def _readable(self, sess: _Session):
        if sess.closed:
            return
        try:
            data = sess.sock.recv(_RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            # RST / half-close from the client side: same teardown as
            # an orderly FIN — never a leaked handler thread
            self._retire(sess)
            return
        if not data:
            self._retire(sess)
            return
        sess.t_last = time.monotonic()
        sess.buf += data
        try:
            self._parse(sess)
        except _pg.ProtocolError:
            self._retire(sess)

    # -- frame parsing (loop thread) ------------------------------------------

    def _parse(self, sess: _Session):
        buf = sess.buf
        while True:
            if sess.closed:
                return
            if sess.framing == "startup":
                if len(buf) < 4:
                    return
                (length,) = struct.unpack_from("!I", buf, 0)
                if length < 8 or length > 1 << 20:
                    raise _pg.ProtocolError(
                        f"bad startup length {length}")
                if len(buf) < length:
                    return
                body = bytes(buf[4:length])
                del buf[:length]
                if not self._startup_frame(sess, body):
                    return
            else:
                if len(buf) < 5:
                    return
                typ = bytes(buf[0:1])
                (length,) = struct.unpack_from("!I", buf, 1)
                if length < 4 or length > 1 << 28:
                    raise _pg.ProtocolError(
                        f"bad message length {length}")
                if len(buf) < 1 + length:
                    return
                body = bytes(buf[5:1 + length])
                del buf[:1 + length]
                self._enqueue(sess, typ, body)

    def _startup_frame(self, sess: _Session, body: bytes) -> bool:
        """One startup-phase packet; False = stop parsing this buffer
        (session closed or handed off)."""
        (code,) = struct.unpack_from("!I", body, 0)
        if code == _pg.SSL_REQUEST and self.parent.tls is not None:
            self._tls_handoff(sess)
            return False
        if code in (_pg.SSL_REQUEST, _pg.GSSENC_REQUEST):
            # deny and let the client retry cleartext on this conn; a
            # 1-byte reply into an empty socket buffer cannot
            # meaningfully block (anything else retires the conn)
            try:
                sess.sock.send(b"N")
            except OSError:
                self._retire(sess)
                return False
            return True
        if code == _pg.CANCEL_REQUEST:
            self._retire(sess)
            return False
        if code != _pg.PROTO_V3:
            # FATAL protocol error composed loop-side; single send,
            # best effort, then retire
            w = _pg._Writer(sess.sock, sendall=lambda d: None)
            w.error(f"unsupported protocol {code >> 16}."
                    f"{code & 0xFFFF}", code="0A000", severity="FATAL")
            try:
                sess.sock.send(bytes(w._buf))
            except OSError:
                pass
            self._retire(sess)
            return False
        params = {}
        parts = body[4:].split(b"\x00")
        for k, v in zip(parts[::2], parts[1::2]):
            if k:
                params[k.decode()] = v.decode()
        sess.framing = "typed"
        with sess.lk:
            sess.active = True
        self._pool.submit(self._run_startup, sess, params)
        return True

    def _enqueue(self, sess: _Session, typ: bytes, body: bytes):
        submit = False
        with sess.lk:
            sess.frames.append((typ, body))
            sess.cv.notify_all()
            if sess.ready and not sess.active:
                sess.active = True
                submit = True
        if submit:
            self._pool.submit(self._drain, sess)

    # -- worker side ----------------------------------------------------------

    def _run_startup(self, sess: _Session, params: dict):
        try:
            ok = sess.conn.finish_startup(params)
        except (ConnectionError, _pg.ProtocolError, OSError):
            ok = False
        except Exception:
            ok = False
        if not ok:
            self._teardown(sess)
            return
        sess.ready = True
        self._drain(sess)

    def _drain(self, sess: _Session):
        """Serve queued frames until the queue runs dry, then hand the
        session back to the loop (idle = no thread). Exactly one
        drain per session at a time (sess.active)."""
        while True:
            with sess.lk:
                if sess.closed:
                    sess.active = False
                    return
                if not sess.frames:
                    sess.active = False
                    if sess.eof:
                        break
                    return
                typ, body = sess.frames.popleft()
            try:
                alive = sess.conn.process(typ, body)
            except (ConnectionError, _pg.ProtocolError, OSError):
                alive = False
            except Exception:
                alive = False
            if not alive:
                break
        self._teardown(sess)

    def _teardown(self, sess: _Session):
        """Idempotent retirement: rollback any open txn, then hand the
        fd back to the loop for unregister+close. Runs on workers —
        rollback takes engine locks the loop must never touch."""
        with sess.lk:
            if sess.closed:
                return
            sess.closed = True
            sess.eof = True
            sess.cv.notify_all()
        conn = sess.conn
        if conn is not None and conn.session.txn is not None:
            try:
                conn.session.txn.rollback()
            except Exception:
                pass
        self._sessions.pop(sess.fd, None)
        self._dead.append(sess.sock)
        self._wakeup()

    # -- loop-side retirement & sweeps ----------------------------------------

    def _retire(self, sess: _Session):
        """Loop-side: stop watching now; delegate the engine-touching
        teardown to a worker unless one is already serving the session
        (it will observe eof and tear down itself)."""
        try:
            self._sel.unregister(sess.sock)
        except (KeyError, ValueError, OSError):
            pass
        with sess.lk:
            if sess.closed:
                return
            sess.eof = True
            sess.cv.notify_all()
            busy = sess.active
            if not busy:
                sess.active = True
        if not busy:
            self._pool.submit(self._teardown, sess)

    def _sweep(self):
        now = time.monotonic()
        if now - self._t_sweep < 0.25:
            return
        self._t_sweep = now
        try:
            stg = self.parent.engine.settings
            deadline = float(stg.get("server.startup_deadline_seconds"))
            idle = float(stg.get("server.idle_session_timeout"))
        except Exception:
            return
        if deadline <= 0 and idle <= 0:
            return
        for sess in list(self._sessions.values()):
            if sess.closed:
                continue
            if not sess.ready:
                # slow-loris guard: startup packet + auth must finish
                # inside the deadline or the conn is cut loose
                if deadline > 0 and now - sess.t_conn > deadline:
                    self._retire(sess)
                continue
            if idle > 0 and not sess.active and not sess.frames:
                conn = sess.conn
                in_txn = conn is not None and conn.session.in_txn
                if not in_txn and now - sess.t_last > idle:
                    self._retire(sess)

    # -- TLS ------------------------------------------------------------------

    def _tls_handoff(self, sess: _Session):
        """SSLRequest with TLS armed: this connection leaves the
        reactor and gets a dedicated thread running the blocking
        handlers over the wrapped socket (TLS framing on a
        non-blocking fd is not worth owning for a handful of
        encrypted conns; the 10K-session story is the plaintext
        pool behind a terminating proxy)."""
        try:
            self._sel.unregister(sess.sock)
        except (KeyError, ValueError, OSError):
            pass
        self._sessions.pop(sess.fd, None)
        sess.closed = True
        sock = sess.sock
        parent = self.parent

        def run():
            conn = None
            try:
                sock.setblocking(True)
                sock.sendall(b"S")
                tsock = parent.tls.wrap_socket(sock, server_side=True)
                conn = parent.new_conn(tsock)
                conn.serve()
            except (ConnectionError, _pg.ProtocolError, OSError):
                pass
            finally:
                if conn is not None and conn.session.txn is not None:
                    try:
                        conn.session.txn.rollback()
                    except Exception:
                        pass
                try:
                    sock.close()
                except OSError:
                    pass

        threading.Thread(target=run, name="pgfront-tls",
                         daemon=True).start()
