"""A minimal pure-Python PostgreSQL wire client ("the vendored
driver").

Round-3/4 asked for a real driver in CI; pg8000 is absent from the
image and the build has zero egress, so this is an independently
written client of the PUBLIC v3 protocol (startup, TLS upgrade,
cleartext + SCRAM-SHA-256 auth with server-signature verification,
simple and extended query, text and BINARY result decoding). It
shares no code with the server module — the point of the exercise is
that our server interoperates with a client written only from the
public protocol documentation, the way psql/pg8000 would.
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import secrets
import socket
import ssl as ssl_mod
import struct

_PG_EPOCH_DATE = datetime.date(2000, 1, 1)
_PG_EPOCH_DT = datetime.datetime(2000, 1, 1)

OID_BOOL, OID_INT8, OID_FLOAT8 = 16, 20, 701
OID_DATE, OID_TIMESTAMP, OID_JSONB = 1082, 1114, 3802


class PgError(Exception):
    def __init__(self, fields: dict):
        self.fields = fields
        super().__init__(fields.get("M", "server error"))

    @property
    def sqlstate(self):
        return self.fields.get("C")


class MiniClient:
    def __init__(self, host: str, port: int, user: str = "root",
                 password: str | None = None, database: str = "db",
                 tls: bool = False):
        self.sock = socket.create_connection((host, port), timeout=30)
        if tls:
            self.sock.sendall(struct.pack("!II", 8, 80877103))
            if self.sock.recv(1) != b"S":
                raise PgError({"M": "server refused TLS"})
            ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = ssl_mod.CERT_NONE
            self.sock = ctx.wrap_socket(self.sock)
        self.user = user
        self.password = password
        params = (f"user\x00{user}\x00database\x00{database}\x00"
                  "\x00").encode()
        head = struct.pack("!II", 8 + len(params), 196608)
        self.sock.sendall(head + params)
        self._auth_loop()
        self.parameters: dict[str, str] = {}
        self._ready()

    # -- framing -----------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            b = self.sock.recv(n - len(out))
            if not b:
                raise ConnectionError("server closed connection")
            out += b
        return out

    def _msg(self):
        typ = self._recv_exact(1)
        (ln,) = struct.unpack("!I", self._recv_exact(4))
        return typ, self._recv_exact(ln - 4)

    def _send(self, typ: bytes, body: bytes = b""):
        self.sock.sendall(typ + struct.pack("!I", len(body) + 4) + body)

    @staticmethod
    def _err_fields(body: bytes) -> dict:
        out = {}
        i = 0
        while i < len(body) and body[i] != 0:
            code = chr(body[i])
            j = body.index(0, i + 1)
            out[code] = body[i + 1:j].decode()
            i = j + 1
        return out

    # -- auth --------------------------------------------------------

    def _auth_loop(self):
        while True:
            typ, body = self._msg()
            if typ == b"E":
                raise PgError(self._err_fields(body))
            if typ != b"R":
                raise PgError({"M": f"unexpected {typ!r} during auth"})
            (code,) = struct.unpack_from("!I", body, 0)
            if code == 0:
                return
            if code == 3:      # cleartext
                self._send(b"p", (self.password or "").encode()
                           + b"\x00")
            elif code == 10:   # SASL
                mechs = body[4:].split(b"\x00")
                if b"SCRAM-SHA-256" not in mechs:
                    raise PgError({"M": "no supported SASL mechanism"})
                self._scram()
            else:
                raise PgError({"M": f"unsupported auth code {code}"})

    def _scram(self):
        cnonce = base64.b64encode(secrets.token_bytes(18)).decode()
        bare = f"n={self.user},r={cnonce}"
        first = "n,," + bare
        payload = (b"SCRAM-SHA-256\x00"
                   + struct.pack("!i", len(first)) + first.encode())
        self._send(b"p", payload)
        typ, body = self._msg()
        if typ == b"E":
            raise PgError(self._err_fields(body))
        (code,) = struct.unpack_from("!I", body, 0)
        if code != 11:
            raise PgError({"M": f"expected SASLContinue, got {code}"})
        server_first = body[4:].decode()
        attrs = dict(kv.split("=", 1) for kv in server_first.split(","))
        snonce, salt, iters = (attrs["r"],
                               base64.b64decode(attrs["s"]),
                               int(attrs["i"]))
        if not snonce.startswith(cnonce):
            raise PgError({"M": "server nonce does not extend ours"})
        salted = hashlib.pbkdf2_hmac(
            "sha256", (self.password or "").encode(), salt, iters)
        ck = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored = hashlib.sha256(ck).digest()
        without_proof = "c=" + base64.b64encode(b"n,,").decode() \
            + ",r=" + snonce
        auth_msg = (bare + "," + server_first + ","
                    + without_proof).encode()
        csig = hmac.new(stored, auth_msg, hashlib.sha256).digest()
        proof = bytes(a ^ b for a, b in zip(ck, csig))
        final = without_proof + ",p=" + base64.b64encode(proof).decode()
        self._send(b"p", final.encode())
        typ, body = self._msg()
        if typ == b"E":
            raise PgError(self._err_fields(body))
        (code,) = struct.unpack_from("!I", body, 0)
        if code != 12:
            raise PgError({"M": f"expected SASLFinal, got {code}"})
        fattrs = dict(kv.split("=", 1)
                      for kv in body[4:].decode().split(","))
        sk = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        want = hmac.new(sk, auth_msg, hashlib.sha256).digest()
        if base64.b64decode(fattrs["v"]) != want:
            # a MITM or a server that never knew the verifier
            raise PgError({"M": "server signature mismatch"})

    def _ready(self):
        while True:
            typ, body = self._msg()
            if typ == b"Z":
                return
            if typ == b"E":
                raise PgError(self._err_fields(body))
            if typ == b"S":
                k = body.split(b"\x00")
                self.parameters[k[0].decode()] = k[1].decode()
            # K (BackendKeyData), N (notice): ignored

    # -- decoding ----------------------------------------------------

    @staticmethod
    def _decode_text(raw: bytes, oid: int):
        s = raw.decode()
        if oid == OID_BOOL:
            return s == "t"
        if oid == OID_INT8 or oid in (21, 23):
            return int(s)
        if oid == OID_FLOAT8:
            return float(s)
        return s

    @staticmethod
    def _decode_binary(raw: bytes, oid: int):
        if oid == OID_BOOL:
            return raw != b"\x00"
        if oid == OID_INT8:
            return struct.unpack("!q", raw)[0]
        if oid == OID_FLOAT8:
            return struct.unpack("!d", raw)[0]
        if oid == OID_DATE:
            return _PG_EPOCH_DATE + datetime.timedelta(
                days=struct.unpack("!i", raw)[0])
        if oid == OID_TIMESTAMP:
            return _PG_EPOCH_DT + datetime.timedelta(
                microseconds=struct.unpack("!q", raw)[0])
        if oid == OID_JSONB:
            import json
            return json.loads(raw[1:].decode())
        return raw.decode()

    def _collect(self):
        cols, rows, tag = [], [], None
        err = None
        while True:
            typ, body = self._msg()
            if typ == b"T":
                (n,) = struct.unpack_from("!H", body, 0)
                off = 2
                cols = []
                for _ in range(n):
                    j = body.index(0, off)
                    name = body[off:j].decode()
                    off = j + 1
                    _t, _a, oid, _sz, _m, fmt = struct.unpack_from(
                        "!IhIhih", body, off)
                    off += 18
                    cols.append((name, oid, fmt))
            elif typ == b"D":
                (n,) = struct.unpack_from("!H", body, 0)
                off = 2
                row = []
                for i in range(n):
                    (ln,) = struct.unpack_from("!i", body, off)
                    off += 4
                    if ln < 0:
                        row.append(None)
                        continue
                    raw = body[off:off + ln]
                    off += ln
                    name, oid, fmt = cols[i]
                    row.append(self._decode_binary(raw, oid) if fmt
                               else self._decode_text(raw, oid))
                rows.append(tuple(row))
            elif typ == b"C":
                tag = body.rstrip(b"\x00").decode()
            elif typ == b"E":
                err = PgError(self._err_fields(body))
            elif typ == b"Z":
                if err is not None:
                    raise err
                return [c[0] for c in cols], rows, tag
            # 1/2/3/n/s (parse/bind/close complete, nodata,
            # suspended), N: skipped

    # -- queries -----------------------------------------------------

    def query(self, sql: str):
        """Simple-protocol query -> (names, rows, tag)."""
        self._send(b"Q", sql.encode() + b"\x00")
        return self._collect()

    def query_binary(self, sql: str, params: list | None = None,
                     param_oids: list | None = None):
        """Extended protocol: Parse/Bind/Execute with BINARY result
        format requested for every column."""
        params = params or []
        oids = param_oids or [OID_INT8 if isinstance(p, int)
                              else 0 for p in params]
        parse = bytearray(b"\x00" + sql.encode() + b"\x00")
        parse += struct.pack("!H", len(oids))
        for o in oids:
            parse += struct.pack("!I", o)
        self._send(b"P", bytes(parse))
        bind = bytearray(b"\x00\x00")       # unnamed portal + stmt
        bind += struct.pack("!H", 1) + struct.pack("!H", 0)  # text params
        bind += struct.pack("!H", len(params))
        for p in params:
            if p is None:
                bind += struct.pack("!i", -1)
            else:
                t = str(p).encode()
                bind += struct.pack("!i", len(t)) + t
        bind += struct.pack("!HH", 1, 1)    # ALL results binary
        self._send(b"B", bytes(bind))
        self._send(b"D", b"P\x00")
        self._send(b"E", b"\x00" + struct.pack("!i", 0))
        self._send(b"S")
        return self._collect()

    def close(self):
        try:
            self._send(b"X")
        except OSError:
            pass
        self.sock.close()
