"""Internal time-series database: metrics stored in the KV plane.

The analogue of pkg/ts (ts/db.go:91 DB, :214 StoreData): every node
periodically snapshots its metric registry into the KV store itself —
samples at a fine resolution are appended to hourly "slabs" keyed by
(resolution, metric, slab start), and a maintenance pass rolls old
fine-resolution slabs up to a coarse resolution and prunes beyond the
retention horizon (the reference's ts maintenance queue). Queries
read slabs and downsample server-side, which is what backs the DB
console graphs.

Layout:  /ts/<res_s>/<metric>/<slab_start_s>  ->  json [[offset_s, value], ...]
"""

from __future__ import annotations

import json
import time
from typing import Optional

TS_PREFIX = b"/ts/"
FINE_RES_S = 10          # sample resolution (reference: 10s)
COARSE_RES_S = 300       # rollup resolution (reference: 30m; 5m here)
SLAB_S = 3600            # one KV entry holds an hour of samples


def _slab_key(res_s: int, metric: str, slab_start: int) -> bytes:
    return (TS_PREFIX + str(res_s).encode() + b"/" + metric.encode()
            + b"/" + str(slab_start).zfill(12).encode())


class TimeSeriesDB:
    def __init__(self, kv, metrics, now_s=None):
        self.kv = kv              # kv.txn.DB
        self.metrics = metrics    # utils.metric.MetricRegistry
        self.now_s = now_s or time.time

    # -- write path ----------------------------------------------------------
    def record(self) -> int:
        """Snapshot every scalar metric into its current fine slab.
        Counter/gauge values are stored as-is (cumulative counters are
        rate()-ed at query time, like Prometheus)."""
        now = int(self.now_s())
        samples = []
        for name, m in self.metrics.snapshot().items():
            v = m if isinstance(m, (int, float)) else None
            if v is None and isinstance(m, dict):
                continue  # histograms are not stored (quantiles are
                # derived live; the reference stores summary gauges)
            if v is not None:
                samples.append((name, float(v)))
        if not samples:
            return 0
        slab_start = now - now % SLAB_S
        offset = now - slab_start

        def fn(t):
            for name, v in samples:
                key = _slab_key(FINE_RES_S, name, slab_start)
                raw = t.get(key)
                slab = json.loads(raw.decode()) if raw else []
                if slab and slab[-1][0] == offset:
                    slab[-1][1] = v
                else:
                    slab.append([offset, v])
                t.put(key, json.dumps(slab).encode())
        self.kv.txn(fn)
        return len(samples)

    # -- read path -----------------------------------------------------------
    def query(self, metric: str, start_s: int, end_s: int,
              downsample_s: int = FINE_RES_S, agg: str = "avg",
              rate: bool = False) -> list[tuple[int, float]]:
        """Samples of `metric` in [start_s, end_s), bucketed to
        `downsample_s` with avg/min/max/sum aggregation; rate=True
        returns the per-second derivative (for cumulative counters),
        clamped at 0 across resets."""
        pts: list[tuple[int, float]] = []
        for res in (FINE_RES_S, COARSE_RES_S):
            lo = start_s - start_s % SLAB_S
            klo = _slab_key(res, metric, lo)
            khi = _slab_key(res, metric, end_s)
            for _k, v in self.kv.scan(klo, khi + b"\xff"):
                slab_start = int(_k.rsplit(b"/", 1)[1])
                for off, val in json.loads(v.decode()):
                    ts = slab_start + off
                    if start_s <= ts < end_s:
                        pts.append((ts, val))
        pts.sort()
        # dedup (a timestamp present in both resolutions): fine wins
        dedup: dict[int, float] = {}
        for ts, val in pts:
            dedup.setdefault(ts, val)
        pts = sorted(dedup.items())
        if rate:
            rated = []
            for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
                dt = t1 - t0
                if dt > 0:
                    rated.append((t1, max(0.0, (v1 - v0) / dt)))
            pts = rated
        if downsample_s <= FINE_RES_S:
            return pts
        buckets: dict[int, list[float]] = {}
        for ts, val in pts:
            buckets.setdefault(ts - ts % downsample_s, []).append(val)
        fn = {"avg": lambda xs: sum(xs) / len(xs), "min": min,
              "max": max, "sum": sum}.get(agg)
        if fn is None:
            raise ValueError(f"unknown downsampler {agg!r}")
        return [(b, fn(xs)) for b, xs in sorted(buckets.items())]

    def list_metrics(self) -> list[str]:
        names = set()
        for k, _v in self.kv.scan(TS_PREFIX,
                                  TS_PREFIX + b"\xff"):
            parts = k[len(TS_PREFIX):].split(b"/")
            if len(parts) == 3:
                names.add(parts[1].decode())
        return sorted(names)

    # -- maintenance (rollup + prune) ----------------------------------------
    def maintain(self, retention_fine_s: int = 6 * 3600,
                 retention_coarse_s: int = 30 * 24 * 3600) -> dict:
        """One ts-maintenance pass: roll fine slabs older than the
        fine retention up into the coarse resolution (avg per coarse
        bucket), then delete them; prune coarse slabs beyond the
        coarse retention. Returns counts."""
        now = int(self.now_s())
        fine_cut = now - retention_fine_s
        coarse_cut = now - retention_coarse_s
        rolled = pruned = 0
        prefix = TS_PREFIX + str(FINE_RES_S).encode() + b"/"
        for k, v in list(self.kv.scan(prefix, prefix + b"\xff")):
            parts = k[len(prefix):].split(b"/")
            metric, slab_start = parts[0].decode(), int(parts[1])
            if slab_start + SLAB_S > fine_cut:
                continue  # still within fine retention
            buckets: dict[int, list[float]] = {}
            for off, val in json.loads(v.decode()):
                ts = slab_start + off
                buckets.setdefault(ts - ts % COARSE_RES_S,
                                   []).append(val)

            def fn(t, k=k, metric=metric, buckets=buckets):
                for b, xs in sorted(buckets.items()):
                    ck = _slab_key(COARSE_RES_S, metric,
                                   b - b % SLAB_S)
                    raw = t.get(ck)
                    slab = json.loads(raw.decode()) if raw else []
                    off = b - (b - b % SLAB_S)
                    if not any(o == off for o, _ in slab):
                        slab.append([off, sum(xs) / len(xs)])
                        slab.sort()
                        t.put(ck, json.dumps(slab).encode())
                t.delete(k)
            self.kv.txn(fn)
            rolled += 1
        cprefix = TS_PREFIX + str(COARSE_RES_S).encode() + b"/"
        for k, _v in list(self.kv.scan(cprefix, cprefix + b"\xff")):
            slab_start = int(k.rsplit(b"/", 1)[1])
            if slab_start + SLAB_S <= coarse_cut:
                self.kv.txn(lambda t, k=k: t.delete(k))
                pruned += 1
        return {"rolled_up": rolled, "pruned": pruned}
