"""PostgreSQL wire protocol v3 — the SQL API surface.

The analogue of the reference's pgwire server (pkg/sql/pgwire/server.go:685
``ServeConn``; per-connection loop pkg/sql/pgwire/conn.go:280 ``serveImpl``).
Scope: startup handshake (plus SSLRequest denial), trust auth, the simple
query protocol (Query -> RowDescription/DataRow/CommandComplete), the
extended protocol (Parse/Bind/Describe/Execute/Close/Sync) with text and
binary parameter binding and row-limited Execute with portal suspension,
and error reporting with SQLSTATE codes. Each connection owns an engine
Session, so transaction state (idle / open / aborted) is per-connection
exactly like the reference's connExecutor, and is reported in
ReadyForQuery.

Round 5 closes the round-3/4 auth asks: SCRAM-SHA-256 (RFC 5802/7677
SASL exchange, the reference's default auth method,
pkg/sql/pgwire/auth_methods.go:69), TLS upgrade, COPY both directions,
and binary RESULT encoding (int8/float8/bool/date/timestamp/jsonb per
the public wire formats; Bind result-format codes honored per column).
The framing below is from the public PostgreSQL protocol
documentation, not from the reference tree.
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac as hmac_mod
import re
import secrets
import socket
import socketserver
import struct
import threading

from ..exec.engine import Engine, EngineError, Result, Session

PROTO_V3 = 196608          # 3.0
SSL_REQUEST = 80877103
CANCEL_REQUEST = 80877102
GSSENC_REQUEST = 80877104

# type OIDs (public pg catalog numbers)
OID_BOOL = 16
OID_INT8 = 20
OID_FLOAT8 = 701
OID_TEXT = 25
OID_DATE = 1082
OID_TIMESTAMP = 1114
OID_JSONB = 3802


class ProtocolError(Exception):
    pass


class PreparedBudgetError(Exception):
    """Session exceeded server.prepared_statement_budget (53400)."""


# -- SCRAM-SHA-256 (RFC 5802/7677; the reference's default auth
# method, pkg/sql/pgwire/auth_methods.go:69) --------------------------

def scram_verifier(password: str, salt: bytes | None = None,
                   iterations: int = 4096) -> dict:
    """Server-side verifier: the server never stores the password,
    only (salt, i, StoredKey, ServerKey) — exactly what CRDB keeps in
    system.users as a SCRAM hash."""
    salt = salt or secrets.token_bytes(16)
    salted = hashlib.pbkdf2_hmac("sha256", password.encode(), salt,
                                 iterations)
    ck = hmac_mod.new(salted, b"Client Key", hashlib.sha256).digest()
    sk = hmac_mod.new(salted, b"Server Key", hashlib.sha256).digest()
    return {"salt": salt, "i": iterations,
            "stored_key": hashlib.sha256(ck).digest(),
            "server_key": sk}


def _scram_attrs(msg: str) -> dict:
    return dict(kv.split("=", 1) for kv in msg.split(","))


def _sqlstate(exc: Exception) -> str:
    from ..utils.admission import AdmissionRejected
    from ..utils.mon import MemoryQuotaError

    msg = str(exc)
    if isinstance(exc, CopyDataError):
        return "22P02"  # invalid_text_representation
    if isinstance(exc, PreparedBudgetError):
        return "53400"  # configuration_limit_exceeded
    if isinstance(exc, AdmissionRejected):
        # admission queue full / load shed: the clean front-door
        # rejection clients should retry with backoff
        return "53300"  # too_many_connections
    if "restart transaction" in msg:
        return "40001"  # serialization_failure
    if "transaction is aborted" in msg:
        return "25P02"  # in_failed_sql_transaction
    if isinstance(exc, MemoryQuotaError):
        return "53200"  # out_of_memory
    if isinstance(exc, EngineError):
        return "42601" if "parse" in msg.lower() else "XX000"
    return "XX000"


def _infer_oid(rows, col: int) -> int:
    """Type OID from the first non-null value in column ``col``."""
    for row in rows:
        v = row[col]
        if v is None:
            continue
        if isinstance(v, bool):
            return OID_BOOL
        if isinstance(v, int):
            return OID_INT8
        if isinstance(v, float):
            return OID_FLOAT8
        if isinstance(v, datetime.datetime):
            return OID_TIMESTAMP
        if isinstance(v, datetime.date):
            return OID_DATE
        if isinstance(v, dict):
            return OID_JSONB
        return OID_TEXT
    return OID_TEXT


def _encode_text(v) -> bytes | None:
    """Text-format result encoding (format code 0)."""
    if v is None:
        return None
    if isinstance(v, bool):
        return b"t" if v else b"f"
    if isinstance(v, float):
        return repr(v).encode()
    if isinstance(v, dict):
        import json
        return json.dumps(v, sort_keys=True,
                          separators=(",", ":")).encode()
    if isinstance(v, list):
        # pg array_out text via the canonical encoder (quoting rules
        # for elements containing , { } " \ or spaces)
        from ..sql import datum as dtm
        from ..sql.types import BOOL, FLOAT8, INT8, STRING
        elem = STRING
        for x in v:
            if x is None:
                continue
            if isinstance(x, bool):
                elem = BOOL
            elif isinstance(x, int):
                elem = INT8
            elif isinstance(x, float):
                elem = FLOAT8
            break
        return dtm.canon_array(v, elem).encode()
    return str(v).encode()


_PG_EPOCH_DATE = datetime.date(2000, 1, 1)
_PG_EPOCH_DT = datetime.datetime(2000, 1, 1)


def _encode_binary(v, oid: int) -> bytes | None:
    """Binary-format result encoding (format code 1) for the common
    wire types; anything else falls back to its utf8 text bytes (the
    binary representation of text/varchar IS the text)."""
    if v is None:
        return None
    if oid == OID_BOOL:
        return b"\x01" if v else b"\x00"
    if oid == OID_INT8:
        return struct.pack("!q", int(v))
    if oid == OID_FLOAT8:
        return struct.pack("!d", float(v))
    if oid == OID_DATE and isinstance(v, datetime.date):
        return struct.pack("!i", (v - _PG_EPOCH_DATE).days)
    if oid == OID_TIMESTAMP and isinstance(v, datetime.datetime):
        d = v - _PG_EPOCH_DT
        micros = (d.days * 86_400_000_000 + d.seconds * 1_000_000
                  + d.microseconds)
        return struct.pack("!q", micros)
    if oid == OID_JSONB:
        return b"\x01" + (_encode_text(v) or b"")
    return _encode_text(v)


_COPY_RE = re.compile(
    r"copy\s+(?P<table>[a-zA-Z_][\w.]*)\s*"
    r"(?:\((?P<cols>[^)]*)\))?\s*"
    r"(?P<dir>from|to)\s+(?:stdin|stdout)"
    r"(?:\s+with)?(?:\s*\(?\s*format\s+text\s*\)?)?\s*$",
    re.IGNORECASE)


def _copy_text(v) -> str:
    """pg COPY text-format output encoding for one value."""
    if v is None:
        return "\\N"
    if isinstance(v, bool):
        return "t" if v else "f"
    s = _encode_text(v).decode()
    return (s.replace("\\", "\\\\").replace("\t", "\\t")
            .replace("\n", "\\n").replace("\r", "\\r"))


_COPY_UNESCAPE = {"t": "\t", "n": "\n", "r": "\r", "\\": "\\"}


def _copy_unescape(f: str) -> str:
    # single-pass: sequential replace() corrupts a literal backslash
    # followed by t/n/r ('a\\tb' on the wire means backslash + t)
    if "\\" not in f:
        return f
    out = []
    i, n = 0, len(f)
    while i < n:
        c = f[i]
        if c == "\\" and i + 1 < n:
            out.append(_COPY_UNESCAPE.get(f[i + 1], f[i + 1]))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _copy_parse_line(line: bytes, ncols: int) -> list:
    fields = line.decode().split("\t")
    if len(fields) != ncols:
        raise ProtocolError(
            f"COPY row has {len(fields)} columns, expected {ncols}")
    return [None if f == "\\N" else _copy_unescape(f) for f in fields]


class CopyDataError(Exception):
    """Bad field content in COPY text data (sqlstate 22P02)."""


_COPY_INT_RE = re.compile(r"[+-]?[0-9]+")
# pg numeric/float text: decimal with optional exponent, or the
# special values NaN/Infinity (case-insensitive)
_COPY_SPECIAL_FLOAT_RE = re.compile(r"[+-]?(nan|inf(inity)?)",
                                    re.IGNORECASE)
_COPY_FLOAT_RE = re.compile(
    r"[+-]?([0-9]+(\.[0-9]*)?|\.[0-9]+)([eE][+-]?[0-9]+)?"
    r"|[+-]?(nan|inf(inity)?)", re.IGNORECASE)


def _copy_check_numeric(v: str, is_float: bool, col: str) -> str:
    """Validate a COPY text field bound for a numeric column host-side.

    pg text format only accepts \\N as NULL — the literal text 'NULL'
    for an int column is invalid input, never SQL NULL — and a
    malformed token must fail with invalid-input-syntax, not be
    interpolated into the INSERT. Explicit regexes, not int()/float():
    Python accepts '1_000' and Unicode digits, which pg rejects (and
    which must never reach the interpolated INSERT).
    """
    # pg's int4in/float8in trim surrounding ASCII whitespace before
    # parsing ('  42' is valid input); the strict charset check runs
    # on the trimmed token (round-4 advisor, low)
    v = v.strip(" \t\r\n")
    pat = _COPY_FLOAT_RE if is_float else _COPY_INT_RE
    if not pat.fullmatch(v):
        kind = "type numeric" if is_float else "type int"
        raise CopyDataError(
            f"invalid input syntax for {kind}: {v!r} in column {col}")
    return v


def _copy_sql_literal(v, numeric: bool) -> str:
    """One VALUES literal for a COPY field. Quoting is decided by the
    TARGET COLUMN's type, not by sniffing the text — 'nan'/'inf'
    float-parse but are strings when the column says so."""
    if v is None:
        return "NULL"
    if numeric:
        # NaN/Infinity are valid pg float text but not bare SQL
        # tokens — the engine accepts them as quoted literals
        if _COPY_SPECIAL_FLOAT_RE.fullmatch(v):
            return "'" + v + "'"
        return v
    return "'" + v.replace("'", "''") + "'"


def split_statements(buf: str) -> list[str]:
    """Split a simple-Query string on top-level semicolons.

    Respects single-quoted literals (with '' escapes) and double-quoted
    identifiers; pgwire's simple query protocol allows multiple
    statements per message.
    """
    out, cur, i, n = [], [], 0, len(buf)
    quote = None
    while i < n:
        c = buf[i]
        if quote:
            cur.append(c)
            if c == quote:
                if quote == "'" and i + 1 < n and buf[i + 1] == "'":
                    cur.append(buf[i + 1])
                    i += 1
                else:
                    quote = None
        elif c in ("'", '"'):
            quote = c
            cur.append(c)
        elif c == ";":
            s = "".join(cur).strip()
            if s:
                out.append(s)
            cur = []
        else:
            cur.append(c)
        i += 1
    s = "".join(cur).strip()
    if s:
        out.append(s)
    return out


class _Writer:
    """Typed pgwire backend-message writer over a socket.

    ``sendall`` injects the flush primitive: the reactor front end
    hands workers a select-backed sendall that is safe on its
    non-blocking sockets; the thread front end keeps the plain
    blocking ``socket.sendall``.
    """

    def __init__(self, sock: socket.socket, sendall=None):
        self._sock = sock
        self._sendall = sendall or sock.sendall
        self._buf = bytearray()

    def msg(self, typ: bytes, payload: bytes = b""):
        self._buf += typ + struct.pack("!I", len(payload) + 4) + payload

    def flush(self):
        if self._buf:
            self._sendall(bytes(self._buf))
            self._buf.clear()

    # -- concrete messages ---------------------------------------------------
    def auth_ok(self):
        self.msg(b"R", struct.pack("!I", 0))

    def auth_sasl(self, mechs: list[str]):
        body = struct.pack("!I", 10) + b"".join(
            m.encode() + b"\x00" for m in mechs) + b"\x00"
        self.msg(b"R", body)

    def auth_sasl_continue(self, data: bytes):
        self.msg(b"R", struct.pack("!I", 11) + data)

    def auth_sasl_final(self, data: bytes):
        self.msg(b"R", struct.pack("!I", 12) + data)

    def auth_cleartext(self):
        """AuthenticationCleartextPassword (auth.go's password method;
        SCRAM is the reference default, cleartext its fallback — and
        ours, since the wire is already plaintext without TLS)."""
        self.msg(b"R", struct.pack("!I", 3))

    def copy_in_response(self, ncols: int):
        self.msg(b"G", struct.pack("!bH", 0, ncols)
                 + struct.pack(f"!{ncols}H", *([0] * ncols)))

    def copy_out_response(self, ncols: int):
        self.msg(b"H", struct.pack("!bH", 0, ncols)
                 + struct.pack(f"!{ncols}H", *([0] * ncols)))

    def copy_data(self, data: bytes):
        self.msg(b"d", data)

    def copy_done(self):
        self.msg(b"c")

    def parameter_status(self, key: str, val: str):
        self.msg(b"S", key.encode() + b"\x00" + val.encode() + b"\x00")

    def backend_key_data(self, pid: int, secret: int):
        self.msg(b"K", struct.pack("!II", pid & 0xFFFFFFFF, secret))

    def ready_for_query(self, status: bytes):
        self.msg(b"Z", status)
        self.flush()

    def row_description(self, names, oids, fmts=None):
        p = bytearray(struct.pack("!H", len(names)))
        fmts = fmts or [0] * len(names)
        for name, oid, fmt in zip(names, oids, fmts):
            p += name.encode() + b"\x00"
            p += struct.pack("!IhIhih", 0, 0, oid, -1, -1, fmt)
        self.msg(b"T", bytes(p))

    def data_row(self, encoded: list[bytes | None]):
        p = bytearray(struct.pack("!H", len(encoded)))
        for e in encoded:
            if e is None:
                p += struct.pack("!i", -1)
            else:
                p += struct.pack("!I", len(e)) + e
        self.msg(b"D", bytes(p))

    def command_complete(self, tag: str):
        self.msg(b"C", tag.encode() + b"\x00")

    def empty_query(self):
        self.msg(b"I")

    def no_data(self):
        self.msg(b"n")

    def parse_complete(self):
        self.msg(b"1")

    def bind_complete(self):
        self.msg(b"2")

    def close_complete(self):
        self.msg(b"3")

    def portal_suspended(self):
        self.msg(b"s")

    def parameter_description(self, oids):
        self.msg(b"t", struct.pack("!H", len(oids)) +
                 b"".join(struct.pack("!I", o) for o in oids))

    def error(self, message: str, code: str = "XX000",
              severity: str = "ERROR"):
        p = (b"S" + severity.encode() + b"\x00" +
             b"V" + severity.encode() + b"\x00" +
             b"C" + code.encode() + b"\x00" +
             b"M" + message.encode() + b"\x00" + b"\x00")
        self.msg(b"E", p)


class _Reader:
    def __init__(self, sock: socket.socket):
        self._sock = sock

    def _exactly(self, n: int) -> bytes:
        chunks = []
        while n:
            b = self._sock.recv(n)
            if not b:
                raise ConnectionError("client disconnected")
            chunks.append(b)
            n -= len(b)
        return b"".join(chunks)

    def startup(self) -> tuple[int, dict]:
        (length,) = struct.unpack("!I", self._exactly(4))
        if length < 8 or length > 1 << 20:
            raise ProtocolError(f"bad startup length {length}")
        body = self._exactly(length - 4)
        (code,) = struct.unpack("!I", body[:4])
        params = {}
        if code == PROTO_V3:
            parts = body[4:].split(b"\x00")
            for k, v in zip(parts[::2], parts[1::2]):
                if k:
                    params[k.decode()] = v.decode()
        return code, params

    def message(self) -> tuple[bytes, bytes]:
        typ = self._exactly(1)
        (length,) = struct.unpack("!I", self._exactly(4))
        if length < 4 or length > 1 << 28:
            raise ProtocolError(f"bad message length {length}")
        return typ, self._exactly(length - 4)


def _cstr(b: bytes, off: int) -> tuple[str, int]:
    end = b.index(b"\x00", off)
    return b[off:end].decode(), end + 1


def _scan_placeholders(sql: str):
    """Yield (start, end, index) for every $N outside string literals
    and quoted identifiers."""
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c == "-" and i + 1 < n and sql[i + 1] == "-":
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
        elif c == "/" and i + 1 < n and sql[i + 1] == "*":
            j = sql.find("*/", i + 2)
            i = n if j < 0 else j + 2
        elif c == "'":
            i += 1
            while i < n:
                if sql[i] == "'":
                    if i + 1 < n and sql[i + 1] == "'":
                        i += 2
                        continue
                    break
                i += 1
            i += 1
        elif c == '"':
            i = sql.find('"', i + 1)
            i = n if i < 0 else i + 1
        elif c == "$" and i + 1 < n and sql[i + 1].isdigit():
            j = i + 1
            while j < n and sql[j].isdigit():
                j += 1
            yield i, j, int(sql[i + 1:j])
            i = j
        else:
            i += 1


def _count_placeholders(sql: str) -> int:
    return max((idx for _s, _e, idx in _scan_placeholders(sql)),
               default=0)


def _decode_param(raw: bytes | None, fmt: int, oid: int) -> str:
    """One bound parameter -> SQL literal text. Text format re-quotes;
    binary format decodes the common wire types (int2/4/8, float8,
    bool, text) by declared oid."""
    if raw is None:
        return "NULL"
    if fmt == 1:   # binary — parenthesized like the text path, or a
        # negative value forms a '--' comment in the spliced SQL
        if oid == OID_INT8:
            return "(%d)" % struct.unpack("!q", raw)[0]
        if oid == 21 and len(raw) == 2:    # int2
            return "(%d)" % struct.unpack("!h", raw)[0]
        if oid == 23 and len(raw) == 4:    # int4
            return "(%d)" % struct.unpack("!i", raw)[0]
        if oid == OID_FLOAT8 and len(raw) == 8:
            return "(%s)" % repr(struct.unpack("!d", raw)[0])
        if oid == OID_BOOL and len(raw) == 1:
            return "TRUE" if raw[0] else "FALSE"
        s = raw.decode("utf-8")            # text-like payloads
    else:
        s = raw.decode("utf-8")
    if oid in (OID_INT8, 21, 23):
        return "(%d)" % int(s)        # validate AND parenthesize:
        # splicing raw text would let '-1' form a '--' comment or a
        # crafted payload inject statement text
    if oid in (OID_FLOAT8, 700, 1700):
        return "(%s)" % repr(float(s))
    if oid == OID_BOOL:
        low = s.lower()
        if low in ("t", "true", "1", "on", "yes"):
            return "TRUE"
        if low in ("f", "false", "0", "off", "no"):
            return "FALSE"
        raise EngineError(
            f"invalid input syntax for type boolean: {s!r}")
    return "'" + s.replace("'", "''") + "'"


def _bind_params(sql: str, oids: list, body: bytes, off: int):
    """Decode a Bind message's format codes + parameter values and
    substitute them into the SQL as literals. The statement then rides
    the normal parse/plan path — the reference binds placeholders into
    the AST instead (sql/pgwire/conn.go + planner placeholders); text
    substitution trades plan-cache hits across distinct values for a
    much smaller surface, and is what several pg poolers/proxies do."""
    (nfmt,) = struct.unpack_from("!H", body, off)
    off += 2
    fmts = []
    for _ in range(nfmt):
        (f,) = struct.unpack_from("!H", body, off)
        fmts.append(f)
        off += 2
    (nvals,) = struct.unpack_from("!H", body, off)
    off += 2
    vals = []
    for _ in range(nvals):
        (ln,) = struct.unpack_from("!i", body, off)
        off += 4
        if ln < 0:
            vals.append(None)
        else:
            vals.append(body[off:off + ln])
            off += ln
    lits = []
    for i, raw in enumerate(vals):
        fmt = fmts[i] if i < len(fmts) else (fmts[0] if len(fmts) == 1
                                             else 0)
        oid = oids[i] if i < len(oids) else 0
        lits.append(_decode_param(raw, fmt, oid))
    # result-format codes (0=text 1=binary): recorded on the portal
    # and honored per column at Execute time
    (nrfmt,) = struct.unpack_from("!H", body, off)
    off += 2
    rfmts = []
    for _ in range(nrfmt):
        (rf,) = struct.unpack_from("!H", body, off)
        off += 2
        rfmts.append(rf)
    # splice back-to-front so offsets stay valid
    spots = sorted(_scan_placeholders(sql), reverse=True)
    for s, e, idx in spots:
        if idx < 1 or idx > len(lits):
            raise EngineError(
                f"there is no parameter ${idx}")
        sql = sql[:s] + lits[idx - 1] + sql[e:]
    return sql, off, rfmts


class _Conn:
    """One client connection: the serveImpl loop (conn.go:280)."""

    def __init__(self, sock: socket.socket, engine: Engine, conn_id: int,
                 version: str, auth: dict | None = None,
                 tls=None, auth_method: str = "cleartext",
                 scram_users: dict | None = None,
                 reader=None, sendall=None):
        self.sock = sock
        self.engine = engine
        self.conn_id = conn_id
        self.version = version
        self.auth = auth
        self.auth_method = auth_method
        self.scram_users = scram_users or {}
        self.tls = tls  # ssl.SSLContext or None
        # the reactor front end injects a frame-queue reader and a
        # non-blocking-safe sendall; every protocol handler below is
        # shared verbatim between front ends (the bit-for-bit A/B)
        self.r = reader if reader is not None else _Reader(sock)
        self.w = _Writer(sock, sendall=sendall)
        self.session: Session = engine.session()
        # extended-protocol state: prepared statements (sql, declared
        # param oids) + bound portals (sql with params substituted,
        # plus any suspended result for row-limited Execute)
        self.stmts: dict[str, tuple] = {}
        self.portals: dict[str, dict] = {}
        self._errored = False  # skip-until-Sync after extended-proto error

    # -- helpers -------------------------------------------------------------
    def _txn_status(self) -> bytes:
        if self.session.txn_aborted:
            return b"E"
        return b"T" if self.session.in_txn else b"I"

    def _complete_tag(self, res: Result) -> str:
        if res.tag == "INSERT":
            return f"INSERT 0 {res.row_count}"
        if res.tag in ("UPDATE", "DELETE"):
            return f"{res.tag} {res.row_count}"
        if res.names:  # any row-returning statement
            return f"{res.tag} {len(res.rows)}"
        return res.tag

    def _send_result(self, res: Result, describe: bool = True):
        if res.names:
            oids = [_infer_oid(res.rows, i) for i in range(len(res.names))]
            if describe:
                self.w.row_description(res.names, oids)
            for row in res.rows:
                self.w.data_row([_encode_text(v) for v in row])
        self.w.command_complete(self._complete_tag(res))

    def _send_portal(self, p: dict, max_rows: int):
        """Row-limited portal execution: emit up to max_rows, then
        PortalSuspended; a later Execute on the same portal resumes
        where it stopped (pg portal suspension semantics)."""
        res = p["pending"]
        oids = p.get("oids")
        if oids is None:
            oids = p["oids"] = [_infer_oid(res.rows, i)
                                for i in range(len(res.names))]
        rf = p.get("rfmts") or []
        if len(rf) == 1:
            fmts = rf * len(res.names)
        elif len(rf) == len(res.names):
            fmts = rf
        else:
            fmts = [0] * len(res.names)
        if res.names and not p["described"]:
            self.w.row_description(res.names, oids, fmts)
            p["described"] = True
        rows = res.rows
        start = p["cursor"]
        end = len(rows) if max_rows <= 0 else min(len(rows),
                                                  start + max_rows)
        for row in rows[start:end]:
            self.w.data_row([
                _encode_binary(v, oid) if f == 1 else _encode_text(v)
                for v, oid, f in zip(row, oids, fmts)])
        p["cursor"] = end
        if end < len(rows):
            self.w.portal_suspended()
            return
        tag = self._complete_tag(res)
        self.w.command_complete(tag)
        del p["pending"]
        p["completed"] = True
        p["tag"] = tag

    def _execute(self, sql: str) -> Result:
        return self.engine.execute(sql, self.session)

    def _auth_fail(self, msg: str, code: str = "28P01") -> bool:
        self.w.error(msg, code=code, severity="FATAL")
        self.w.flush()
        return False

    def _auth_scram(self) -> bool:
        """RFC 5802/7677 SASL exchange (server side). Channel binding
        is not offered (gs2 'p=' is refused; 'n'/'y' accepted), like
        running the reference without tls-scram channel binding."""
        v = self.scram_users.get(self.user)
        self.w.auth_sasl(["SCRAM-SHA-256"])
        self.w.flush()
        typ, body = self.r.message()
        if typ != b"p":
            return self._auth_fail("expected SASL response", "08P01")
        mech, off = _cstr(body, 0)
        if mech != "SCRAM-SHA-256":
            return self._auth_fail(
                f"unsupported SASL mechanism {mech!r}", "28000")
        (ln,) = struct.unpack_from("!i", body, off)
        off += 4
        client_first = body[off:off + ln].decode()
        if client_first.startswith("p="):
            return self._auth_fail(
                "channel binding is not supported", "28000")
        if ",," not in client_first:
            return self._auth_fail("malformed client-first", "08P01")
        i = client_first.index(",,")
        gs2, bare = client_first[:i + 2], client_first[i + 2:]
        try:
            cnonce = _scram_attrs(bare)["r"]
        except (KeyError, ValueError):
            return self._auth_fail("malformed client-first", "08P01")
        if v is None:
            # unknown user: mimic a real exchange against a throwaway
            # verifier so usernames are not enumerable by timing shape
            v = scram_verifier(secrets.token_hex(8))
        snonce = cnonce + base64.b64encode(
            secrets.token_bytes(18)).decode()
        server_first = (f"r={snonce},"
                        f"s={base64.b64encode(v['salt']).decode()},"
                        f"i={v['i']}")
        self.w.auth_sasl_continue(server_first.encode())
        self.w.flush()
        typ, body = self.r.message()
        if typ != b"p":
            return self._auth_fail("expected SASL response", "08P01")
        client_final = body.decode()
        try:
            fattrs = _scram_attrs(client_final)
            proof = base64.b64decode(fattrs["p"])
            chan = base64.b64decode(fattrs["c"]).decode()
        except (KeyError, ValueError):
            return self._auth_fail("malformed client-final", "08P01")
        if fattrs.get("r") != snonce or chan != gs2:
            return self._auth_fail(
                "SCRAM nonce/channel mismatch", "28P01")
        without_proof = client_final[:client_final.rindex(",p=")]
        auth_msg = (bare + "," + server_first + ","
                    + without_proof).encode()
        csig = hmac_mod.new(v["stored_key"], auth_msg,
                            hashlib.sha256).digest()
        client_key = bytes(a ^ b for a, b in zip(proof, csig))
        if len(proof) != 32 or hashlib.sha256(client_key).digest() \
                != v["stored_key"] or \
                self.auth.get(self.user) is None:
            return self._auth_fail(
                f"password authentication failed for user "
                f"{self.user!r}")
        ssig = hmac_mod.new(v["server_key"], auth_msg,
                            hashlib.sha256).digest()
        self.w.auth_sasl_final(
            b"v=" + base64.b64encode(ssig))
        return True

    # -- protocol phases -----------------------------------------------------
    def handshake(self) -> bool:
        while True:
            code, params = self.r.startup()
            if code == SSL_REQUEST and self.tls is not None:
                # TLS upgrade (the reference's maybeUpgradeToSecureConn,
                # pgwire/server.go): accept, wrap, and continue the
                # startup over the encrypted stream
                self.sock.sendall(b"S")
                self.sock = self.tls.wrap_socket(self.sock,
                                                 server_side=True)
                self.r = _Reader(self.sock)
                self.w = _Writer(self.sock)
                continue
            if code in (SSL_REQUEST, GSSENC_REQUEST):
                self.sock.sendall(b"N")  # not supported; retry cleartext
                continue
            if code == CANCEL_REQUEST:
                return False
            if code != PROTO_V3:
                self.w.error(f"unsupported protocol {code >> 16}."
                             f"{code & 0xFFFF}", code="0A000",
                             severity="FATAL")
                self.w.flush()
                return False
            break
        return self.finish_startup(params)

    def finish_startup(self, params: dict) -> bool:
        """Authentication + session announcements for an accepted
        PROTO_V3 startup. Split from handshake() so the reactor front
        end — which parses startup packets on the event loop — can run
        just this phase on a worker thread."""
        self.user = params.get("user", "root")
        if self.auth is not None:
            if self.auth_method == "scram-sha-256":
                if not self._auth_scram():
                    return False
            else:
                # password gate (auth.go): the user must be known and
                # the cleartext password must match; anything else is
                # a FATAL 28P01 before any SQL is reachable
                self.w.auth_cleartext()
                self.w.flush()
                typ, body = self.r.message()
                if typ != b"p":
                    self.w.error("expected password message",
                                 code="08P01", severity="FATAL")
                    self.w.flush()
                    return False
                pw, _ = _cstr(body, 0)
                if self.auth.get(self.user) != pw:
                    self.w.error(
                        f"password authentication failed for user "
                        f"{self.user!r}", code="28P01",
                        severity="FATAL")
                    self.w.flush()
                    return False
        self.w.auth_ok()
        self.w.parameter_status("server_version", "13.0 cockroach-tpu "
                                + self.version)
        self.w.parameter_status("client_encoding", "UTF8")
        self.w.parameter_status("DateStyle", "ISO")
        self.w.parameter_status("integer_datetimes", "on")
        self.w.backend_key_data(self.conn_id, 0)
        self.w.ready_for_query(self._txn_status())
        return True

    def serve(self):
        if not self.handshake():
            return
        from ..utils import log
        log.info(log.SESSIONS, "client session opened user=%s",
                 getattr(self, "user", "?"))
        while True:
            typ, body = self._next_message()
            if typ is None:          # idle-session timeout
                return
            if not self.process(typ, body):
                return

    def _next_message(self):
        """Blocking read of the next frame, honoring
        server.idle_session_timeout while the session sits idle
        OUTSIDE a transaction (a session holding a txn open keeps its
        locks on purpose; pg's idle_session_timeout has the same
        carve-out via idle_in_transaction_session_timeout). Returns
        (None, None) when the idle deadline fires."""
        try:
            idle = float(self.engine.settings.get(
                "server.idle_session_timeout"))
        except Exception:
            idle = 0.0
        if idle <= 0 or self.session.in_txn:
            return self.r.message()
        try:
            self.sock.settimeout(idle)
            return self.r.message()
        except (socket.timeout, TimeoutError):
            return None, None
        finally:
            try:
                self.sock.settimeout(None)
            except OSError:
                pass

    def process(self, typ: bytes, body: bytes) -> bool:
        """Dispatch one frontend message; False = Terminate. Both
        front ends funnel through here — the thread loop above and
        the reactor's worker drain (server/pgfront.py) — so replies
        are byte-identical by construction."""
        if typ == b"X":          # Terminate
            return False
        if typ == b"Q":
            self._simple_query(body)
        elif typ in (b"P", b"B", b"D", b"E", b"C", b"H", b"S"):
            self._extended(typ, body)
        elif typ == b"F":        # function call: unsupported
            self.w.error("function call protocol unsupported",
                         code="0A000")
            self.w.ready_for_query(self._txn_status())
        else:
            self.w.error(f"unknown frontend message {typ!r}",
                         code="08P01")
            self.w.ready_for_query(self._txn_status())
        return True

    def _simple_query(self, body: bytes):
        sql, _ = _cstr(body, 0)
        m = _COPY_RE.match(sql.strip().rstrip(";"))
        if m is not None:
            try:
                self._copy(m)
            except Exception as e:
                self.w.error(str(e), code=_sqlstate(e))
            self.w.ready_for_query(self._txn_status())
            return
        stmts = split_statements(sql)
        if not stmts:
            self.w.empty_query()
            self.w.ready_for_query(self._txn_status())
            return
        for s in stmts:
            try:
                res = self._execute(s)
            except Exception as e:  # engine errors end the message batch
                self.w.error(str(e), code=_sqlstate(e))
                break
            self._send_result(res)
        self.w.ready_for_query(self._txn_status())

    # -- COPY (conn.go's processCopy; text format only) ----------------------
    def _copy_columns(self, table: str, collist: str | None) -> list[str]:
        schema = self.engine.store.table(table).schema
        if collist:
            return [c.strip() for c in collist.split(",")]
        return [c.name for c in schema.columns]

    def _copy(self, m):
        table = m.group("table")
        cols = self._copy_columns(table, m.group("cols"))
        if m.group("dir").lower() == "to":
            self._copy_out(table, cols)
        else:
            self._copy_in(table, cols)

    def _copy_out(self, table: str, cols: list[str]):
        res = self._execute(
            f"SELECT {', '.join(cols)} FROM {table}")
        self.w.copy_out_response(len(cols))
        for row in res.rows:
            line = "\t".join(_copy_text(v) for v in row)
            self.w.copy_data(line.encode() + b"\n")
        self.w.copy_done()
        self.w.command_complete(f"COPY {len(res.rows)}")

    def _copy_in(self, table: str, cols: list[str]):
        # resolve the schema BEFORE CopyInResponse: an unknown column
        # must error while the client is still in query mode — after
        # the response the client streams CopyData and any raise that
        # skips the drain loop desyncs the protocol
        from ..sql.types import Family
        schema = self.engine.store.table(table).schema
        numeric = [schema.column(c).type.family in
                   (Family.INT, Family.FLOAT, Family.DECIMAL)
                   for c in cols]
        is_float = [schema.column(c).type.family in
                    (Family.FLOAT, Family.DECIMAL) for c in cols]
        self.w.copy_in_response(len(cols))
        self.w.flush()
        buf = b""
        rows: list[list[str | None]] = []
        failed = None
        # A bad row must NOT abort the receive loop: pg keeps consuming
        # CopyData until CopyDone/CopyFail, then reports the error —
        # bailing early desyncs the protocol (the leftover frames would
        # be read as unknown frontend messages by serve()).
        parse_err: Exception | None = None
        while True:
            typ, body = self.r.message()
            if typ == b"d":
                if parse_err is not None:
                    continue         # drain only; first error wins
                buf += body
                # CopyData chunks can split mid-line: keep the tail
                while True:
                    nl = buf.find(b"\n")
                    if nl < 0:
                        break
                    line, buf = buf[:nl], buf[nl + 1:]
                    if line == b"\\.":
                        continue
                    if not line:
                        continue
                    try:
                        r = _copy_parse_line(line, len(cols))
                        for i, v in enumerate(r):
                            if v is not None and numeric[i]:
                                r[i] = _copy_check_numeric(
                                    v, is_float[i], cols[i])
                        rows.append(r)
                    except Exception as e:
                        parse_err = e
                        break
            elif typ == b"c":        # CopyDone
                break
            elif typ == b"f":        # CopyFail
                failed, _ = _cstr(body, 0)
                break
            elif typ in (b"H", b"S"):
                self.w.flush()
            else:
                raise ProtocolError(
                    f"unexpected message {typ!r} during COPY")
        if failed is not None:
            self.w.error(f"COPY failed: {failed}", code="57014")
            return
        if parse_err is not None:
            raise parse_err
        inserted = 0
        # batches through the normal INSERT path (constraints and
        # indexes apply), wrapped in ONE transaction so a mid-COPY
        # failure leaves nothing behind — pg's COPY is atomic per
        # statement
        BATCH = 1000
        own_txn = not self.session.in_txn
        if own_txn:
            self._execute("BEGIN")
        try:
            for lo in range(0, len(rows), BATCH):
                chunk = rows[lo:lo + BATCH]
                values = ", ".join(
                    "(" + ", ".join(
                        _copy_sql_literal(v, numeric[i])
                        for i, v in enumerate(r)) + ")"
                    for r in chunk)
                self._execute(
                    f"INSERT INTO {table} ({', '.join(cols)}) "
                    f"VALUES {values}")
                inserted += len(chunk)
            if own_txn:
                self._execute("COMMIT")
        except Exception:
            if own_txn:
                self._execute("ROLLBACK")
            raise
        self.w.command_complete(f"COPY {inserted}")

    def _extended(self, typ: bytes, body: bytes):
        # after an error, discard everything until Sync
        if self._errored and typ != b"S":
            return
        try:
            if typ == b"P":           # Parse
                name, off = _cstr(body, 0)
                sql, off = _cstr(body, off)
                (nparams,) = struct.unpack_from("!H", body, off)
                off += 2
                oids = []
                for _ in range(nparams):
                    (o,) = struct.unpack_from("!I", body, off)
                    oids.append(o)
                    off += 4
                # placeholders present but undeclared: count $N in the
                # text so Describe can report them (oid 0 = unknown)
                n_ph = _count_placeholders(sql)
                while len(oids) < n_ph:
                    oids.append(0)
                if name and name not in self.stmts:
                    # named statements are session-lifetime server
                    # memory; cap them so one session cannot grow the
                    # server unboundedly (the unnamed statement
                    # replaces itself and stays exempt)
                    try:
                        budget = int(self.engine.settings.get(
                            "server.prepared_statement_budget"))
                    except Exception:
                        budget = 0
                    if budget and len(self.stmts) >= budget:
                        raise PreparedBudgetError(
                            f"prepared statement budget ({budget}) "
                            f"exhausted; DEALLOCATE or Close unused "
                            f"statements")
                self.stmts[name] = (sql, oids)
                self.w.parse_complete()
            elif typ == b"B":         # Bind
                portal, off = _cstr(body, 0)
                stmt, off = _cstr(body, off)
                if stmt not in self.stmts:
                    raise EngineError(f"unknown prepared statement "
                                      f"{stmt!r}")
                sql, oids = self.stmts[stmt]
                sql, off, rfmts = _bind_params(sql, oids, body, off)
                self.portals[portal] = {"sql": sql, "rfmts": rfmts}
                self.w.bind_complete()
            elif typ == b"D":         # Describe
                kind, sql_name = body[:1], _cstr(body, 1)[0]
                src = self.portals if kind == b"P" else self.stmts
                if sql_name not in src:
                    raise EngineError(f"unknown {kind!r} {sql_name!r}")
                if kind == b"S":
                    self.w.parameter_description(self.stmts[sql_name][1])
                # row shape is only known post-execution here; NoData
                # keeps drivers on the simple path (they re-describe
                # from the result's RowDescription we emit on Execute)
                self.w.no_data()
            elif typ == b"E":         # Execute
                portal, off = _cstr(body, 0)
                if portal not in self.portals:
                    raise EngineError(f"unknown portal {portal!r}")
                (max_rows,) = struct.unpack_from("!i", body, off)
                p = self.portals[portal]
                if p.get("completed"):
                    # executing a completed portal returns no further
                    # rows (pg portal semantics) — never re-runs DML
                    self.w.command_complete(p["tag"])
                elif "pending" not in p:
                    res = self._execute(p["sql"])
                    p["pending"] = res
                    p["cursor"] = 0
                    p["described"] = False
                    self._send_portal(p, max_rows)
                else:
                    self._send_portal(p, max_rows)
            elif typ == b"C":         # Close
                kind, name = body[:1], _cstr(body, 1)[0]
                (self.portals if kind == b"P" else self.stmts).pop(
                    name, None)
                self.w.close_complete()
            elif typ == b"H":         # Flush
                self.w.flush()
            elif typ == b"S":         # Sync
                self._errored = False
                self.w.ready_for_query(self._txn_status())
        except Exception as e:
            self._errored = True
            self.w.error(str(e), code=_sqlstate(e))
            self.w.flush()


class PgServer:
    """The pgwire front door: listener + connection dispatch.

    Two interchangeable front ends behind one facade, selected by the
    ``server.pgwire_frontend`` cluster setting (or the ``frontend=``
    override):

    - ``reactor`` (default): one selector event loop owns every
      socket; idle sessions hold no thread and O(1) buffer memory; a
      bounded worker pool sized by *active statements* runs the
      protocol handlers (server/pgfront.py).
    - ``threads``: the legacy thread-per-connection
      socketserver.ThreadingTCPServer — the reference accepts in
      (*Server).AcceptClients (pkg/server/server.go:1915) and serves
      each conn on a goroutine; a thread per conn is that analogue.

    Both front ends drive the same ``_Conn`` protocol handlers, so
    replies are bit-identical — the A/B lever for the 1K/10K-session
    bench rungs.
    """

    def __init__(self, engine: Engine, host: str = "127.0.0.1",
                 port: int = 0, version: str = "0.2.0",
                 auth: dict | None = None,
                 certs_dir: str | None = None,
                 auth_method: str = "cleartext",
                 frontend: str | None = None):
        self.engine = engine
        self.version = version
        self.auth = auth  # user -> cleartext password; None = insecure
        self.auth_method = auth_method
        # SCRAM verifiers derived once: the serving path never sees
        # the password (auth_methods.go:69; RFC 5802)
        self.scram_users = ({u: scram_verifier(pw)
                             for u, pw in (auth or {}).items()}
                            if auth_method == "scram-sha-256" else {})
        self.tls = None
        if certs_dir is not None:
            import os
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(
                os.path.join(certs_dir, "node.crt"),
                os.path.join(certs_dir, "node.key"))
            self.tls = ctx
        self._next_id = [0]
        if frontend is None:
            try:
                frontend = str(engine.settings.get(
                    "server.pgwire_frontend"))
            except Exception:
                frontend = "threads"
        self.frontend = frontend
        if frontend == "reactor":
            from .pgfront import ReactorServer
            self._impl = ReactorServer(self, host, port)
        else:
            self._impl = _ThreadServer(self, host, port)

    def new_conn(self, sock: socket.socket, reader=None,
                 sendall=None) -> _Conn:
        """One _Conn with the next conn id; both front ends funnel
        connection construction through here."""
        self._next_id[0] += 1
        return _Conn(sock, self.engine, self._next_id[0], self.version,
                     auth=self.auth, tls=self.tls,
                     auth_method=self.auth_method,
                     scram_users=self.scram_users,
                     reader=reader, sendall=sendall)

    @property
    def addr(self) -> tuple[str, int]:
        return self._impl.addr

    def start(self):
        from . import pgfront
        # the r18 residue lever: a sub-default GIL switch quantum lets
        # OLTP batch windows close under analytic load (process-global;
        # see sql.exec.switch_interval). Armed here + on change.
        pgfront.apply_switch_interval(self.engine.settings)
        self.engine.settings.on_change(
            lambda n, v: pgfront.apply_switch_interval(
                self.engine.settings)
            if n == "sql.exec.switch_interval" else None)
        self._impl.start()
        return self

    def stop(self):
        self._impl.stop()


class _ThreadServer:
    """Thread-per-connection front end (the pre-reactor default)."""

    def __init__(self, parent: PgServer, host: str, port: int):
        outer = parent

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # OLTP responses are one small packet per statement;
                # with cross-session batch windows a session's reply
                # can gate another session's window, so Nagle's 40ms
                # delayed-ACK interaction would land straight on the
                # fused lane's p99 (the reference sets TCP_NODELAY on
                # every pgwire conn for the same reason)
                try:
                    self.request.setsockopt(socket.IPPROTO_TCP,
                                            socket.TCP_NODELAY, 1)
                except OSError:
                    pass
                conn = outer.new_conn(self.request)
                try:
                    conn.serve()
                except (ConnectionError, ProtocolError, OSError):
                    pass
                finally:
                    if conn.session.txn is not None:
                        conn.session.txn.rollback()

        class Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Srv((host, port), Handler)
        self._thread: threading.Thread | None = None

    @property
    def addr(self) -> tuple[str, int]:
        return self._server.server_address[:2]

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.05},
            name="pgwire-accept", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
