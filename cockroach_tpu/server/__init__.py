"""Node server + pgwire SQL API (reference: pkg/server, pkg/sql/pgwire)."""

from .node import Node, NodeConfig
from .pgwire import PgServer

__all__ = ["Node", "NodeConfig", "PgServer"]
