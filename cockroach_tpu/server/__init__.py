"""Node server + pgwire SQL API (reference: pkg/server, pkg/sql/pgwire)."""

# Lazy exports (PEP 562): `python -m cockroach_tpu.server.hostd` must
# reach jax.distributed.initialize BEFORE anything touches a JAX
# backend, and the eager `from .node import Node` chain imports the
# whole engine (whose kernel modules trace jnp at import time).
__all__ = ["Node", "NodeConfig", "PgServer"]

_EXPORTS = {"Node": ("cockroach_tpu.server.node", "Node"),
            "NodeConfig": ("cockroach_tpu.server.node", "NodeConfig"),
            "PgServer": ("cockroach_tpu.server.pgwire", "PgServer")}


def __getattr__(name):
    try:
        mod, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib
    return getattr(importlib.import_module(mod), attr)
