"""Per-host dispatcher process for a multi-host pod (round 15).

``python -m cockroach_tpu.server.hostd --process-id I
--num-processes N --coordinator H:P`` joins the pod rendezvous
(parallel/multihost.py), builds this host's engine with its OWN shard
of the generated tables (host-owned TableReader placement: host i
holds rows ``[i*R/N, (i+1)*R/N)`` of lineitem, dimension tables
replicated), wires a framed SocketTransport to every peer via the
coordinator KV store, and then:

- host 0 (the gateway) runs the requested statements through a
  ``Gateway`` whose ``merge_fanout`` arranges the partial-agg streams
  into the host merge tree, and prints ONE JSON line of results +
  per-host metrics to stdout;
- every other host pumps its transport, serving SetupFlow /
  merge-tree traffic, until the gateway posts the ``done`` key.

The CPU tier-1 harness (tests/test_multihost.py) and
``bench.py multihost_child`` both spawn this entry point on
localhost; on a real pod the same command line runs once per host
with the coordinator pointing at host 0. Fault modes (--fault) let
the cross-host ladder tests kill a dispatcher or drop a merge link
deterministically.

``--elastic`` (round 16) switches to the DYNAMIC pod: no
jax.distributed, no fixed --num-processes. Host 0 founds the pod
(serves the socket KV coordinator, writes its address to
--kv-addr-file), waits for --initial-hosts members, bootstraps the
shard-lease table, and runs the statement loop; every other host
points --kv-addr at the coordinator and either joins the founding
set or — with --late-join — joins a RUNNING pod, streaming its new
shards from their live owners before the lease flip. --drain-after
makes a worker exit in an orderly drain mid-run, and --mem-fault
injects membership-plane faults (delayed heartbeats, stale-epoch
lease claims) for the churn ladder.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from cockroach_tpu.parallel import multihost

# combine-exact aggregate statements for the merge-tree ladder: Q1's
# AVGs are float folds (order-dependent -> flat fan-in by design), so
# the "groupby" rung is the Q1 pricing summary restricted to its
# exact sums + count
GROUPBY_SQL = (
    "SELECT l_returnflag, l_linestatus, "
    "sum(l_quantity) AS sum_qty, "
    "sum(l_extendedprice) AS sum_base_price, "
    "count(*) AS count_order "
    "FROM lineitem WHERE l_shipdate <= DATE '1998-09-02' "
    "GROUP BY l_returnflag, l_linestatus "
    "ORDER BY l_returnflag, l_linestatus")

_METRIC_KEYS = ("shuffle.bytes.", "exec.multihost.", "distsql.flows",
                "exec.movement.exchange", "exec.agg.adaptive",
                "cluster.membership.", "exec.lease.",
                "exec.movement.rebalance", "distsql.degrade.",
                "distsql.failover.")


def _queries():
    from cockroach_tpu.models import tpch
    return {"q6": tpch.Q6, "groupby": GROUPBY_SQL, "join": tpch.Q14}


def _jsonable(v):
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, (int, float, str)) or v is None:
        return v
    return str(v)      # Decimal/date render exactly; tests compare str


def _metric_slice(eng) -> dict:
    try:
        snap = eng.metrics.snapshot()
    except Exception:
        return {}
    return {k: v for k, v in snap.items()
            if isinstance(v, (int, float))
            and any(k.startswith(p) for p in _METRIC_KEYS)}


def _build_engine(pid: int, nprocs: int, rows: int):
    """This host's engine over its OWN contiguous shard of lineitem
    (host-owned TableReader placement); dimension tables replicated."""
    from cockroach_tpu.exec.engine import Engine
    from cockroach_tpu.models import tpch
    eng = Engine()
    eng.execute(tpch.DDL["lineitem"])
    eng.execute(tpch.DDL["part"])
    li = tpch.gen_lineitem(0.01, rows=rows)
    lo, hi = pid * rows // nprocs, (pid + 1) * rows // nprocs
    ts = eng.clock.now()
    eng.store.insert_columns(
        "lineitem", {k: v[lo:hi] for k, v in li.items()}, ts)
    eng.store.insert_columns("part", tpch.gen_part(0.01), ts)
    return eng


def _wire_transport(eng, topo, fault: str):
    """SocketTransport to every peer, addresses exchanged through the
    coordinator KV store."""
    from cockroach_tpu.rpc.context import FaultInjector, SocketTransport
    injector = None
    if fault == "drop-link" and topo.process_id == topo.num_processes - 1:
        # the highest host drops every frame toward its merge parent:
        # the parent's merge wait (or the gateway's idle deadline)
        # must turn that silence into FlowUnavailable, not a hang
        injector = FaultInjector(seed=topo.process_id)
        parent = topo.parent()
        injector.set_rule(topo.process_id,
                          0 if parent is None else parent, drop=1.0)
    transport = SocketTransport(topo.process_id, injector=injector)
    try:
        transport.attach_metrics(eng.metrics)
    except Exception:
        pass
    host, port = transport.addr
    multihost.publish_flow_addr(host, port)
    for pid, addr in multihost.peer_flow_addrs().items():
        if pid != topo.process_id:
            transport.connect(pid, addr)
    multihost.register_teardown(transport.close)
    return transport


def _await_done() -> None:
    """Dead-dispatcher host: no serving, just wait for the gateway to
    finish so the pod tears down in one coordinated wave."""
    while True:
        try:
            multihost.kv_get("done", timeout_s=0.5)
            return
        except Exception:
            time.sleep(0.01)


def _serve(transport) -> None:
    """Worker-host pump loop: deliver flow traffic until the gateway
    posts the done key (polled so a frame never waits on the poll)."""
    while True:
        moved = transport.deliver_all()
        if moved or transport.pending():
            continue
        try:
            multihost.kv_get("done", timeout_s=0.2)
            return
        except Exception:
            time.sleep(0.005)


def _run_gateway(eng, transport, topo, args) -> dict:
    from cockroach_tpu.distsql.node import DistSQLNode, Gateway
    own = DistSQLNode(0, eng, transport)
    gw = Gateway(own, list(range(topo.num_processes)),
                 replicated_tables={"part"},
                 flow_timeout=args.flow_timeout,
                 merge_fanout=args.fanout)
    out = {"hosts": topo.num_processes, "rows": args.rows,
           "fanout": args.fanout, "results": {}, "timings": {}}
    names = [q for q in args.queries.split(",") if q]
    qs = _queries()
    for name in names:
        best = None
        try:
            # repeat > 1 is the bench's warm-timing lever: the first
            # run pays plan/XLA compilation on every host, later runs
            # measure the flow itself; best-of keeps the rate honest
            for _ in range(max(1, args.repeat)):
                t0 = time.monotonic()
                res = gw.run(qs[name])
                dt = time.monotonic() - t0
                best = dt if best is None else min(best, dt)
        except Exception as e:     # noqa: BLE001 — the harness asserts
            # on this shape: a dead dispatcher must yield a clean,
            # typed error line, never a hang or a traceback on stdout
            out["results"][name] = {
                "error": f"{type(e).__name__}: {e}"}
            continue
        out["results"][name] = {
            "names": list(res.names),
            "rows": [[_jsonable(v) for v in r] for r in res.rows]}
        out["timings"][name] = {"elapsed_s": best,
                                "rows_per_s": args.rows / best}
    return out


def _gather_peer_metrics(topo) -> dict:
    out = {}
    for pid in range(1, topo.num_processes):
        try:
            out[str(pid)] = json.loads(
                multihost.kv_get(f"hostmetrics/{pid}", timeout_s=20.0))
        except Exception:
            out[str(pid)] = None    # died mid-run (fault ladder)
    return out


# ---------------------------------------------------------------------------
# elastic pod (round 16): dynamic membership + shard leases
# ---------------------------------------------------------------------------

def _elastic_recover(rows: int, nshards: int):
    """Deterministic shard regeneration — the durable-storage stand-in
    every elastic host agrees on: shard s of lineitem is rows
    [s*R/NSH, (s+1)*R/NSH) of the seeded generator."""
    from cockroach_tpu.models import tpch
    li = tpch.gen_lineitem(0.01, rows=rows)

    def recover(table: str, sid: int) -> dict:
        assert table == "lineitem", table
        lo = sid * rows // nshards
        hi = (sid + 1) * rows // nshards
        return {k: v[lo:hi] for k, v in li.items()}
    return recover


def _install_mem_faults(args) -> None:
    if args.mem_fault == "none":
        return
    f = multihost.MembershipFaults(
        heartbeat_delay_s=(args.liveness_window * 2.0
                           if args.mem_fault == "delayed-heartbeat"
                           else 0.0),
        stale_epoch_claims=(args.mem_fault == "stale-epoch"),
        hosts=(args.process_id,))
    multihost.install_membership_faults(f)


def _elastic_serve(transport, pod, refresh_peers, drain_after: float):
    """Elastic worker pump: flow traffic + idle-time lease reconcile,
    until the gateway posts ``done`` (or our drain deadline lands)."""
    drain_at = (time.monotonic() + drain_after
                if drain_after > 0 else None)
    while True:
        refresh_peers()
        moved = transport.deliver_all()
        if pod.node is None or not pod.node._producing:
            try:
                pod.reconcile()
            except Exception:   # noqa: BLE001 — coordinator may be
                return          # gone: the pod is tearing down
        if drain_at is not None and time.monotonic() > drain_at:
            pod.drain_pod()
            return
        if moved or transport.pending():
            continue
        if multihost.kv_try_get("done"):
            return
        time.sleep(0.005)


def _elastic_main(args) -> int:
    from cockroach_tpu.distsql import leases as L
    from cockroach_tpu.distsql.node import DistSQLNode, Gateway
    from cockroach_tpu.exec.engine import Engine
    from cockroach_tpu.models import tpch
    from cockroach_tpu.rpc.context import SocketTransport
    from cockroach_tpu.storage.hlc import Timestamp

    hid = args.process_id
    founder = not args.kv_addr
    eng = Engine()
    eng.execute(tpch.DDL["lineitem"])
    eng.execute(tpch.DDL["part"])
    eng.store.insert_columns("part", tpch.gen_part(0.01),
                             Timestamp(1, 0))
    mem = multihost.init_elastic(
        hid, kv_addr=args.kv_addr, serve_kv=founder,
        fanout=max(1, args.fanout), metrics=eng.metrics,
        heartbeat_interval=args.heartbeat_interval,
        liveness_window=args.liveness_window)
    if founder and args.kv_addr_file:
        with open(args.kv_addr_file, "w") as f:
            f.write(multihost.elastic_kv_addr())
    _install_mem_faults(args)

    transport = SocketTransport(hid)
    try:
        transport.attach_metrics(eng.metrics)
    except Exception:
        pass
    host, port = transport.addr
    multihost.kv_set(f"flowaddr/{hid}", f"{host}:{port}")
    multihost.register_teardown(transport.close)
    node = DistSQLNode(hid, eng, transport)
    keeper = L.ShardKeeper(eng)
    keeper.register_table("lineitem", tpch.DDL["lineitem"])
    leases = L.ShardLeases(mem, metrics=eng.metrics)
    pod = L.ElasticPod(hid, mem, leases, keeper, node=node,
                       recover=_elastic_recover(args.rows,
                                                args.nshards))

    known = {hid}

    def refresh_peers() -> None:
        for sid, raw in multihost.kv_list("flowaddr/").items():
            pid = int(sid)
            if pid not in known and raw:
                h, _, p = raw.rpartition(":")
                transport.connect(pid, (h, int(p)))
                known.add(pid)

    if not founder:
        mem.start_heartbeat()
        if args.late_join:
            refresh_peers()
            pod.join_pod(timeout_s=args.flow_timeout)
        else:
            mem.join()
        _elastic_serve(transport, pod, refresh_peers,
                       args.drain_after)
        try:
            multihost.kv_set(f"hostmetrics/{hid}",
                             json.dumps(_metric_slice(eng)))
        except Exception:
            pass
        time.sleep(0.2)
        eng.close()
        return 0

    # founder = gateway: wait for the founding member set, bootstrap
    # the lease table, then run the statement loop under churn
    mem.join()
    mem.start_heartbeat()
    deadline = time.monotonic() + args.flow_timeout
    while len(mem.view().live) < args.initial_hosts:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"elastic pod: {len(mem.view().live)} of "
                f"{args.initial_hosts} founding hosts joined")
        time.sleep(0.01)
    owners = sorted(mem.view().live)[:args.initial_hosts]
    pod.bootstrap("lineitem", tpch.DDL["lineitem"], args.nshards,
                  owners)
    refresh_peers()
    gw = Gateway(node, pod.data_nodes(),
                 replicated_tables={"part"},
                 flow_timeout=args.flow_timeout,
                 merge_fanout=args.fanout, elastic=pod)
    out = {"hosts": args.initial_hosts, "rows": args.rows,
           "fanout": args.fanout, "elastic": True,
           "results": {}, "timings": {}}
    qs = _queries()
    names = [q for q in args.queries.split(",") if q]
    for name in names:
        best, rows_out, consistent = None, None, True
        try:
            for _ in range(max(1, args.repeat)):
                refresh_peers()
                pod.maybe_reconcile()
                t0 = time.monotonic()
                res = gw.run(qs[name])
                dt = time.monotonic() - t0
                best = dt if best is None else min(best, dt)
                got = [[_jsonable(v) for v in r] for r in res.rows]
                if rows_out is None:
                    rows_out = got
                elif got != rows_out:
                    consistent = False
                if args.statement_gap > 0:
                    time.sleep(args.statement_gap)
        except Exception as e:  # noqa: BLE001 — harness asserts shape
            out["results"][name] = {
                "error": f"{type(e).__name__}: {e}"}
            continue
        out["results"][name] = {"names": list(res.names),
                                "rows": rows_out,
                                "runs": max(1, args.repeat),
                                "consistent": consistent}
        out["timings"][name] = {"elapsed_s": best,
                                "rows_per_s": args.rows / best}
    from cockroach_tpu.server.node import membership_status
    out["membership"] = membership_status()
    out["metrics"] = {"0": _metric_slice(eng)}
    multihost.kv_set("done", "1")
    for pid in sorted(int(s) for s in
                      multihost.kv_list("flowaddr/").keys()):
        if pid == hid:
            continue
        try:
            out["metrics"][str(pid)] = json.loads(
                multihost.kv_get(f"hostmetrics/{pid}", timeout_s=5.0))
        except Exception:
            out["metrics"][str(pid)] = None   # died / drained early
    print(json.dumps(out), flush=True)
    eng.close()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cockroach_tpu.server.hostd")
    ap.add_argument("--process-id", type=int, default=0)
    ap.add_argument("--num-processes", type=int, default=1)
    ap.add_argument("--coordinator", default="")
    ap.add_argument("--fanout", type=int,
                    default=multihost.DEFAULT_FANOUT,
                    help="merge-tree fanout; 0 = flat fan-in (A/B)")
    ap.add_argument("--rows", type=int, default=600)
    ap.add_argument("--queries", default="q6,groupby,join")
    ap.add_argument("--repeat", type=int, default=1,
                    help="runs per query; timings keep the best "
                    "(warm) one — the bench's compile-exclusion lever")
    ap.add_argument("--flow-timeout", type=float, default=60.0)
    ap.add_argument("--fault", default="none",
                    choices=["none", "dispatcher-death", "drop-link"])
    # -- elastic pod (round 16) ------------------------------------
    ap.add_argument("--elastic", action="store_true",
                    help="dynamic-membership pod: shard leases, "
                    "online join/drain, statement failover")
    ap.add_argument("--kv-addr", default="",
                    help="elastic coordinator host:port (empty = "
                    "found the pod and serve the KV)")
    ap.add_argument("--kv-addr-file", default="",
                    help="founder writes its coordinator address "
                    "here for late joiners")
    ap.add_argument("--nshards", type=int, default=8)
    ap.add_argument("--initial-hosts", type=int, default=2,
                    help="founder bootstraps leases once this many "
                    "members joined")
    ap.add_argument("--late-join", action="store_true",
                    help="join a RUNNING pod: stream shards from "
                    "live owners, then flip")
    ap.add_argument("--drain-after", type=float, default=0.0,
                    help="worker drains out of the pod after this "
                    "many seconds (0 = never)")
    ap.add_argument("--statement-gap", type=float, default=0.0,
                    help="sleep between gateway statements (gives "
                    "churn a window to land mid-run)")
    ap.add_argument("--heartbeat-interval", type=float, default=0.1)
    ap.add_argument("--liveness-window", type=float, default=1.0)
    ap.add_argument("--mem-fault", default="none",
                    choices=["none", "delayed-heartbeat",
                             "stale-epoch"])
    args = ap.parse_args(argv)

    if args.elastic:
        return _elastic_main(args)

    topo = multihost.init_distributed(
        coordinator=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
        fanout=max(1, args.fanout))
    eng = _build_engine(topo.process_id, topo.num_processes, args.rows)
    transport = _wire_transport(eng, topo, args.fault)
    multihost.barrier("ready")
    dead = (args.fault == "dispatcher-death"
            and topo.process_id == topo.num_processes - 1)
    if dead:
        # kill the SERVING plane, not the process: closing the
        # listener drops every inbound SetupFlow/merge frame exactly
        # like a crashed dispatcher, while the jax.distributed client
        # stays up (an os._exit here would trip the coordination
        # service's heartbeat and abort every surviving peer — the
        # control plane dying is a different fault than the data
        # plane dying, and this mode tests the latter)
        transport.close()

    if topo.is_gateway:
        out = _run_gateway(eng, transport, topo, args)
        out["metrics"] = {"0": _metric_slice(eng)}
        multihost.kv_set("done", "1")
        out["metrics"].update(_gather_peer_metrics(topo))
        print(json.dumps(out), flush=True)
    else:
        from cockroach_tpu.distsql.node import DistSQLNode
        DistSQLNode(topo.process_id, eng, transport)
        if dead:
            _await_done()
        else:
            _serve(transport)
        multihost.kv_set(f"hostmetrics/{topo.process_id}",
                         json.dumps(_metric_slice(eng)))
        # give the gateway a beat to read our metrics before the
        # coordinator (process 0) tears the KV store down
        time.sleep(0.2)
    eng.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
