"""Node lifecycle: assemble subsystems and serve clients.

The analogue of the reference's server package (pkg/server/server.go:203
``NewServer`` wires rpc/gossip/kv/sql together; ``PreStart``
server.go:1213 boots them in dependency order; ``AcceptClients``
server.go:1915 opens the pgwire listener). Here a Node owns the
columnstore scan plane, the HLC clock, the transactional KV plane
(inside Engine), cluster settings, and the pgwire server; ``start()``
brings them up and returns once the SQL listener is bound.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import __version__
from ..exec.engine import Engine
from ..storage.columnstore import ColumnStore
from ..storage.hlc import Clock
from ..utils.settings import Settings
from .pgwire import PgServer


@dataclass
class NodeConfig:
    listen_host: str = "127.0.0.1"
    listen_port: int = 0          # 0 = ephemeral (tests); CLI default 26257
    mesh: object = None           # optional device mesh for DistSQL
    load_tpch_sf: float | None = None  # demo mode: preload TPC-H tables


class Node:
    def __init__(self, config: NodeConfig | None = None):
        self.config = config or NodeConfig()
        self.clock = Clock()
        self.store = ColumnStore()
        self.settings = Settings()
        self.engine = Engine(store=self.store, clock=self.clock,
                             settings=self.settings,
                             mesh=self.config.mesh)
        self.pg: PgServer | None = None
        self._started = False

    @property
    def sql_addr(self) -> tuple[str, int]:
        assert self.pg is not None, "node not started"
        return self.pg.addr

    def start(self) -> "Node":
        if self._started:
            return self
        if self.config.load_tpch_sf is not None:
            from ..models import tpch
            tpch.load(self.engine, sf=self.config.load_tpch_sf)
        self.pg = PgServer(self.engine, self.config.listen_host,
                           self.config.listen_port,
                           version=__version__).start()
        self._started = True
        return self

    def stop(self):
        if self.pg is not None:
            self.pg.stop()
        self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
