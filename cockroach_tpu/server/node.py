"""Node lifecycle: assemble subsystems and serve clients.

The analogue of the reference's server package (pkg/server/server.go:203
``NewServer`` wires rpc/gossip/kv/sql together; ``PreStart``
server.go:1213 boots them in dependency order; ``AcceptClients``
server.go:1915 opens the pgwire listener). Here a Node owns the
columnstore scan plane, the HLC clock, the transactional KV plane
(inside Engine), cluster settings, and the pgwire server; ``start()``
brings them up and returns once the SQL listener is bound.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import __version__
from ..exec.engine import Engine
from ..storage.columnstore import ColumnStore
from ..storage.hlc import Clock
from ..utils.settings import Settings
from .pgwire import PgServer


@dataclass
class NodeConfig:
    listen_host: str = "127.0.0.1"
    listen_port: int = 0          # 0 = ephemeral (tests); CLI default 26257
    http_port: int | None = 0     # status/metrics; None disables
    mesh: object = None           # optional device mesh for DistSQL
    load_tpch_sf: float | None = None  # demo mode: preload TPC-H tables
    # cluster fabric: this node's id + RPC port, and peer addresses to
    # join ({node_id: (host, port)}); None disables the fabric
    node_id: int = 1
    rpc_port: int | None = None
    join: dict | None = None
    gossip_interval: float = 0.2
    # tests: a shared rpc.FaultInjector (seeded nemesis schedule for
    # the socket fabric); None = faults off
    fault_injector: object = None
    # background maintenance loop: orphaned-job adoption + MVCC GC
    # passes (the store queues / job registry adoption loops of the
    # reference); None disables
    maintenance_interval: float | None = None
    # raft-replicated data plane: a kvserver.Cluster shared by the
    # nodes of one logical cluster. With this set, the node's SQL
    # engine serves DML/catalog/jobs from replicated ranges
    # (kv/rangekv.py) instead of a node-local store — several Nodes
    # handed the same Cluster serve the same data (VERDICT r3 #1c)
    cluster: object = None
    # pgwire password gate: {user: cleartext password}; None = insecure
    # mode (the reference's --insecure), every user accepted
    auth: dict | None = None
    # TLS: directory holding node.crt/node.key (cli.py `cert` creates
    # them); None serves plaintext only
    certs_dir: str | None = None


# -- cluster-wide status fan-out ----------------------------------------
# The payload builders are module-level so ANY NetCluster participant
# can serve them over the fabric's "status" RPC — including engines
# embedded in tests or tools that never construct a Node. A Node wires
# its own engine in via enable_cluster_status() below.

def _tracez_payload(engine) -> dict:
    """The /debug/tracez body: the slow-statement ring (engine
    docstring; span in wire format)."""
    return {"traces": list(engine.slow_traces)}


def _statements_payload(engine) -> dict:
    """The /_status/statements body. Carries the raw totals and the
    log2 latency-bucket array alongside the derived means/quantiles,
    so a fan-out merge can recombine fingerprints exactly instead of
    averaging averages."""
    return {"statements": [{
        "fingerprint": s.fingerprint,
        "count": s.count,
        "total_latency_s": s.total_latency_s,
        "mean_latency_s": s.mean_latency_s,
        "max_latency_s": s.max_latency_s,
        # p50/p95/p99 from the log2-bucketed latency distribution
        # (utils/sqlstats.py; same observations as the means)
        "p50_latency_s": s.p50_latency_s,
        "p95_latency_s": s.p95_latency_s,
        "p99_latency_s": s.p99_latency_s,
        "latency_buckets": list(s.latency_buckets),
        # compile-vs-execute split (exec/coldstart.py per-thread XLA
        # compile attribution): high mean_compile_s with low
        # mean_exec_s means the fix is cache/prewarm, not the plan
        "total_compile_s": s.total_compile_s,
        "mean_compile_s": s.mean_compile_s,
        "mean_exec_s": s.mean_exec_s,
        "total_rows": s.total_rows,
        "failures": s.failures,
    } for s in engine.sqlstats.all()]}


def _tenants_payload(engine) -> dict:
    """The /_status/tenants body: application_name-keyed resource
    rollups (device-seconds, bytes moved, HBM high-water) from the
    always-on statement profile plane (exec/profile.py)."""
    return {"tenants": [t.to_wire()
                        for t in engine.sqlstats.tenants()]}


def membership_status() -> dict:
    """The /_status/membership body: this host's view of the elastic
    pod — epoch'd live set, per-member state/incarnation, heartbeat
    suspects, and the shard-lease assignment at the current epoch
    (read through the epoch-guarded LeaseView, never the raw lease
    records). ``{"elastic": false}`` when the pod is static or
    single-process (pkg/server/status.go NodesLiveness analogue)."""
    from cockroach_tpu.parallel import multihost
    mem = multihost.membership()
    if mem is None:
        return {"elastic": False}
    view = mem.view()
    out = {
        "elastic": True,
        "host_id": mem.host_id,
        "incarnation": mem.incarnation,
        "epoch": view.epoch,
        "live": sorted(view.live),
        "members": {str(h): dict(view.members.get(str(h), {}))
                    for h in view.live},
        "suspects": sorted(mem.suspects(view.live)),
        "expelled": bool(mem.expelled()),
    }
    try:
        from cockroach_tpu.distsql.leases import ShardLeases
        lv = ShardLeases(mem).view_at(view.epoch)
        out["leases"] = {
            t: {str(s): o for s, o in sorted(lv.assignment(t).items())}
            for t in sorted(lv.assignments)}
    except Exception:   # noqa: BLE001 — lease table may not exist yet
        out["leases"] = {}
    return out


def register_status_sources(cluster, engine) -> None:
    """Expose this engine's tracez/statements/tenants payloads to
    peers over the NetCluster "status" RPC (the server side of
    ?cluster=1)."""
    cluster.status_handlers["tracez"] = \
        lambda: _tracez_payload(engine)
    cluster.status_handlers["statements"] = \
        lambda: _statements_payload(engine)
    cluster.status_handlers["tenants"] = \
        lambda: _tenants_payload(engine)


def _fanout_status(cluster, what: str,
                   timeout: float) -> tuple[dict, bool]:
    """Collect `what` payloads from every live peer. Liveness-gated
    (a node the cluster already believes dead costs nothing), each
    peer bounded by `timeout`; any skipped/failed peer marks the
    result partial instead of failing the scrape."""
    results: dict[int, dict] = {}
    partial = False
    live = set(cluster.live_peers())
    with cluster._mu:
        known = sorted(cluster._peers)
    for nid in known:
        if nid == cluster.node_id:
            continue
        if nid not in live:
            partial = True
            continue
        try:
            results[nid] = cluster.call(nid, "status",
                                        {"what": what},
                                        timeout=timeout)
        except Exception:
            partial = True
    return results, partial


def _merge_tracez(own_id: int, local: dict, remote: dict,
                  partial: bool) -> dict:
    traces = [dict(t, node=own_id) for t in local["traces"]]
    for nid, payload in sorted(remote.items()):
        traces.extend(dict(t, node=nid)
                      for t in payload.get("traces", []))
    return {"traces": traces, "cluster": True, "partial": partial,
            "nodes": sorted([own_id, *remote])}


def _merge_statements(own_id: int, local: dict, remote: dict,
                      partial: bool) -> dict:
    """Per-fingerprint exact merge: sum the totals and bucket arrays,
    take the max of maxes, then re-derive means and quantiles from
    the combined values."""
    from ..utils.metric import buckets_quantile
    merged: dict[str, dict] = {}

    def fold(payload):
        for s in payload.get("statements", []):
            m = merged.get(s["fingerprint"])
            if m is None:
                merged[s["fingerprint"]] = dict(s)
                continue
            m["count"] += s["count"]
            m["total_latency_s"] += s["total_latency_s"]
            m["total_compile_s"] += s["total_compile_s"]
            m["total_rows"] += s["total_rows"]
            m["failures"] += s["failures"]
            m["max_latency_s"] = max(m["max_latency_s"],
                                     s["max_latency_s"])
            m["latency_buckets"] = [
                a + b for a, b in zip(m["latency_buckets"],
                                      s["latency_buckets"])]

    fold(local)
    for _, payload in sorted(remote.items()):
        fold(payload)
    for m in merged.values():
        n = m["count"] or 1
        m["mean_latency_s"] = m["total_latency_s"] / n
        m["mean_compile_s"] = m["total_compile_s"] / n
        m["mean_exec_s"] = max(0.0, m["mean_latency_s"]
                               - m["mean_compile_s"])
        for q, k in ((0.50, "p50_latency_s"), (0.95, "p95_latency_s"),
                     (0.99, "p99_latency_s")):
            m[k] = buckets_quantile(m["latency_buckets"], q)
    stmts = sorted(merged.values(),
                   key=lambda m: -m["total_latency_s"])
    return {"statements": stmts, "cluster": True, "partial": partial,
            "nodes": sorted([own_id, *remote])}


def _merge_tenants(own_id: int, local: dict, remote: dict,
                   partial: bool) -> dict:
    """Per-tenant exact merge: counters and seconds sum across nodes;
    hbm_bytes_held is a per-node high-water, so the cluster view takes
    the max (the tenant held at most that much on any one node)."""
    merged: dict[str, dict] = {}

    def fold(payload):
        for t in payload.get("tenants", []):
            m = merged.get(t["app_name"])
            if m is None:
                merged[t["app_name"]] = dict(t)
                continue
            for k in ("statements", "failures", "rows",
                      "device_seconds", "bytes_moved",
                      "stall_seconds"):
                m[k] += t[k]
            m["hbm_bytes_held"] = max(m["hbm_bytes_held"],
                                      t["hbm_bytes_held"])

    fold(local)
    for _, payload in sorted(remote.items()):
        fold(payload)
    tenants = sorted(merged.values(),
                     key=lambda m: -m["device_seconds"])
    return {"tenants": tenants, "cluster": True, "partial": partial,
            "nodes": sorted([own_id, *remote])}


class Node:
    def __init__(self, config: NodeConfig | None = None):
        self.config = config or NodeConfig()
        self.clock = Clock()
        self.store = ColumnStore()
        self.settings = Settings()
        self.engine = Engine(store=self.store, clock=self.clock,
                             settings=self.settings,
                             mesh=self.config.mesh,
                             cluster=self.config.cluster)
        if self.config.cluster is not None:
            self.clock = self.engine.clock  # one HLC per cluster
        from ..jobs import IMPORT_JOB, ImportResumer
        # share the engine's registry (schema-change/changefeed/backup/
        # restore/ttl resumers pre-registered) so the maintenance loop
        # can adopt ANY orphaned job type
        self.jobs = self.engine.jobs
        self.jobs.session_id = f"node-{self.config.node_id}"
        self.jobs.register(IMPORT_JOB, lambda: ImportResumer(self.engine))
        self.pg: PgServer | None = None
        self._http = None
        self.rpc = None
        self.gossip = None
        self._gossip_stop = None
        self._started = False
        # internal time-series DB: metrics recorded into the KV plane
        # by the maintenance loop (pkg/ts analogue, server/ts.py)
        from .ts import TimeSeriesDB
        self.tsdb = TimeSeriesDB(self.engine.kv, self.engine.metrics)
        # cluster-wide status fan-out: the NetCluster serving this
        # node's tracez/statements to peers (enable_cluster_status)
        self._status_cluster = None

    @property
    def sql_addr(self) -> tuple[str, int]:
        assert self.pg is not None, "node not started"
        return self.pg.addr

    @property
    def http_addr(self) -> tuple[str, int]:
        assert self._http is not None, "status server not started"
        return self._http.server_address[:2]

    def _start_status_server(self):
        """Status/metrics HTTP endpoint (pkg/server/status: /healthz,
        /_status/vars Prometheus text)."""
        import http.server
        import json
        import threading

        node = self

        class H(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                from urllib.parse import parse_qs, urlparse
                path = urlparse(self.path).path
                qs = parse_qs(urlparse(self.path).query)
                if path in ("/metrics", "/_status/vars"):
                    body = node.engine.metrics.to_prometheus().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path.startswith("/ts/query"):
                    q = qs
                    try:
                        pts = node.tsdb.query(
                            q["name"][0],
                            int(q.get("start", ["0"])[0]),
                            int(q.get("end", [str(2**62)])[0]),
                            downsample_s=int(
                                q.get("downsample", ["10"])[0]),
                            agg=q.get("agg", ["avg"])[0],
                            rate=q.get("rate", ["0"])[0] == "1")
                        body = json.dumps(pts).encode()
                    except (KeyError, ValueError) as ex:
                        self.send_response(400)
                        self.end_headers()
                        self.wfile.write(str(ex).encode())
                        return
                    ctype = "application/json"
                elif path == "/ts/metrics":
                    body = json.dumps(
                        node.tsdb.list_metrics()).encode()
                    ctype = "application/json"
                elif path == "/healthz":
                    body = json.dumps({
                        "status": "ok",
                        "version": __version__,
                        "tables": len(node.store.tables),
                        "hbm_used_bytes": node.engine.hbm.used,
                    }).encode()
                    ctype = "application/json"
                elif path == "/_status/nodes":
                    # `cockroach node status` backing (pkg/server/
                    # status.go Nodes): this node + its fabric view
                    mon = getattr(node, "peer_monitor", None)
                    peers = {}
                    if mon is not None:
                        ids = set(mon.misses) | set(mon.rtt_ns)
                        peers = {str(p): {
                            "healthy": mon.healthy(p),
                            "rtt_ns": mon.rtt_ns.get(p),
                            "clock_offset_ns": mon.offset_ns.get(p),
                        } for p in sorted(ids)}
                    body = json.dumps({
                        "node_id": node.config.node_id,
                        "version": __version__,
                        "sql_addr": list(node.sql_addr),
                        "tables": sorted(node.store.tables),
                        "peers": peers,
                    }).encode()
                    ctype = "application/json"
                elif path == "/_status/membership":
                    # elastic-pod membership + shard leases as this
                    # host sees them (epoch'd view, suspects,
                    # epoch-guarded lease assignment)
                    body = json.dumps(membership_status()).encode()
                    ctype = "application/json"
                elif path == "/_status/statements":
                    # per-fingerprint statement stats (pkg/server
                    # /statements.go Statements endpoint); ?cluster=1
                    # fans out to live peers and merges fingerprints
                    payload = _statements_payload(node.engine)
                    c = node._status_cluster
                    if qs.get("cluster", ["0"])[0] == "1" \
                            and c is not None:
                        timeout = float(
                            qs.get("timeout", ["2.0"])[0])
                        remote, part = _fanout_status(
                            c, "statements", timeout)
                        payload = _merge_statements(
                            c.node_id, payload, remote, part)
                    body = json.dumps(payload).encode()
                    ctype = "application/json"
                elif path == "/debug/tracez":
                    # ring buffer of recent slow-statement trace
                    # recordings (threshold via the cluster setting
                    # sql.trace.slow_statement.threshold; the tracez
                    # snapshot page of the reference); ?cluster=1
                    # concatenates every live peer's ring, node-tagged
                    payload = _tracez_payload(node.engine)
                    c = node._status_cluster
                    if qs.get("cluster", ["0"])[0] == "1" \
                            and c is not None:
                        timeout = float(
                            qs.get("timeout", ["2.0"])[0])
                        remote, part = _fanout_status(
                            c, "tracez", timeout)
                        payload = _merge_tracez(
                            c.node_id, payload, remote, part)
                    body = json.dumps(payload).encode()
                    ctype = "application/json"
                elif path == "/_status/tenants":
                    # application_name-keyed resource rollups from the
                    # statement profile plane; ?cluster=1 sums tenants
                    # across every live peer (hbm high-water maxes)
                    payload = _tenants_payload(node.engine)
                    c = node._status_cluster
                    if qs.get("cluster", ["0"])[0] == "1" \
                            and c is not None:
                        timeout = float(
                            qs.get("timeout", ["2.0"])[0])
                        remote, part = _fanout_status(
                            c, "tenants", timeout)
                        payload = _merge_tenants(
                            c.node_id, payload, remote, part)
                    body = json.dumps(payload).encode()
                    ctype = "application/json"
                elif path == "/_status/stmtdiag":
                    # pending diagnostics requests + completed bundle
                    # summaries (POST here arms a fingerprint)
                    body = json.dumps(
                        node.engine.stmtdiag.summary()).encode()
                    ctype = "application/json"
                elif path.startswith("/_status/stmtdiag/"):
                    # one completed bundle by id
                    try:
                        bid = int(path.rsplit("/", 1)[1])
                    except ValueError:
                        self.send_response(400)
                        self.end_headers()
                        return
                    b = node.engine.stmtdiag.get(bid)
                    if b is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    body = json.dumps(b, default=str).encode()
                    ctype = "application/json"
                elif path == "/_debug/ranges":
                    # `cockroach debug` analogue: range descriptors +
                    # leaseholders when this node serves a cluster
                    c = node.config.cluster
                    if c is None:
                        body = json.dumps({"ranges": []}).encode()
                    else:
                        rngs = []
                        for rid, desc in sorted(
                                c.descriptors.items()):
                            rngs.append({
                                "range_id": rid,
                                "start": desc.start_key.decode(
                                    "latin1"),
                                "end": desc.end_key.decode("latin1"),
                                "replicas": list(desc.replicas),
                                "leaseholder": c.leaseholder(rid),
                            })
                        body = json.dumps({"ranges": rngs}).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                from urllib.parse import urlparse
                path = urlparse(self.path).path
                if path != "/_status/stmtdiag":
                    self.send_response(404)
                    self.end_headers()
                    return
                # arm a statement fingerprint: the next matching
                # execution captures a diagnostics bundle. Body:
                # {"sql": "..."} or {"fingerprint": "..."}
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if "fingerprint" in req:
                        out = node.engine.stmtdiag.arm(
                            str(req["fingerprint"]),
                            is_fingerprint=True)
                    else:
                        out = node.engine.stmtdiag.arm(
                            str(req["sql"]))
                except (KeyError, ValueError) as ex:
                    self.send_response(400)
                    self.end_headers()
                    self.wfile.write(str(ex).encode())
                    return
                body = json.dumps(out).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class Srv(http.server.ThreadingHTTPServer):
            daemon_threads = True

        self._http = Srv((self.config.listen_host,
                          self.config.http_port), H)
        threading.Thread(target=self._http.serve_forever,
                         name="status-http", daemon=True).start()

    def _start_fabric(self):
        """RPC listener + gossip loop (pkg/rpc, pkg/gossip): cluster
        settings set on any node converge on all of them."""
        import threading

        from ..rpc import Gossip, SocketTransport
        from ..rpc.gossip import wire_settings

        cfg = self.config
        self.rpc = SocketTransport(cfg.node_id, cfg.listen_host,
                                   cfg.rpc_port,
                                   injector=cfg.fault_injector)
        peers = [cfg.node_id]
        for nid, addr in (cfg.join or {}).items():
            self.rpc.connect(nid, tuple(addr))
            peers.append(nid)
        self.gossip = Gossip(cfg.node_id, self.rpc, peers=peers)
        # fabric liveness: heartbeats + per-peer breakers + clock-skew
        # checks ride the same loop (pkg/rpc/heartbeat.go analogue)
        from ..rpc.heartbeat import PeerMonitor
        self.peer_monitor = PeerMonitor(cfg.node_id, self.rpc)
        # extensible fabric dispatch: gossip consumes its own payloads
        # (handle() returns False otherwise); other subsystems add
        # themselves under a message "kind" without clobbering gossip
        self.rpc_handlers: dict[str, object] = {}

        def dispatch(frm, msg):
            if self.peer_monitor.handle(frm, msg):
                return
            if self.gossip.handle(frm, msg):
                return
            kind = msg.get("kind") if isinstance(msg, dict) else None
            h = self.rpc_handlers.get(kind)
            if h is not None:
                h(frm, msg)

        self.rpc.register(cfg.node_id, dispatch)
        wire_settings(self.gossip, self.settings)
        self.gossip.add_info(f"node:{cfg.node_id}:sql_addr",
                             list(self.sql_addr))
        self._gossip_stop = threading.Event()
        rpc, gossip, stop = self.rpc, self.gossip, self._gossip_stop

        monitor = self.peer_monitor

        def loop():
            # locals, not self.*: stop() nulls the attributes while
            # this thread may still be mid-tick
            while not stop.is_set():
                gossip.tick()
                monitor.tick()
                rpc.deliver_all()
                stop.wait(cfg.gossip_interval)

        self._gossip_thread = threading.Thread(target=loop,
                                               name="gossip", daemon=True)
        self._gossip_thread.start()

    def connect_peer(self, node_id: int, rpc_addr) -> None:
        """Late join: learn a peer after startup."""
        assert self.rpc is not None
        self.rpc.connect(node_id, tuple(rpc_addr))
        if node_id not in self.gossip.peers:
            self.gossip.peers.append(node_id)

    def enable_cluster_status(self, cluster=None) -> "Node":
        """Join the cluster-wide status plane: serve this node's
        tracez/statements to peers over `cluster`'s fabric and honor
        ?cluster=1 on the HTTP endpoints by fanning out over it.
        Default: the NodeConfig's cluster (auto-called by start()
        when that is a NetCluster)."""
        c = cluster if cluster is not None else self.config.cluster
        if c is None or not hasattr(c, "status_handlers"):
            return self
        register_status_sources(c, self.engine)
        self._status_cluster = c
        return self

    def start(self) -> "Node":
        if self._started:
            return self
        self.enable_cluster_status()
        if self.config.load_tpch_sf is not None:
            from ..models import tpch
            tpch.load(self.engine, sf=self.config.load_tpch_sf)
        self.pg = PgServer(self.engine, self.config.listen_host,
                           self.config.listen_port,
                           version=__version__,
                           auth=self.config.auth,
                           certs_dir=self.config.certs_dir).start()
        if self.config.http_port is not None:
            self._start_status_server()
        if self.config.rpc_port is not None:
            self._start_fabric()
        if self.config.maintenance_interval is not None:
            self._start_maintenance()
        self._started = True
        from ..utils import log
        log.structured(log.OPS, "node_start",
                       node_id=self.config.node_id,
                       sql_addr="%s:%d" % self.pg.addr)
        return self

    def _start_maintenance(self):
        """Adopt orphaned jobs (registry.go:1508 adoption loop) and run
        MVCC GC passes (mvcc_gc_queue) on a background cadence."""
        import threading

        self._maint_stop = threading.Event()

        def loop():
            while not self._maint_stop.wait(
                    self.config.maintenance_interval):
                try:
                    self.jobs.adopt_and_run_all()
                except Exception:
                    pass  # job failures land in their records
                for name in list(self.engine.store.tables):
                    if name.startswith("__"):
                        continue
                    try:
                        self.engine.run_gc(name)
                    except Exception:
                        pass
                try:
                    # abandoned-intent sweep (intentresolver analogue):
                    # clears intents of crashed coordinators so reads
                    # never pay a push for them
                    self.engine.kv.store.intent_resolver.clean_span()
                except Exception:
                    pass
                if self.engine.cluster is not None:
                    try:
                        # aged-out aborted txn records (gc/gc.go)
                        self.engine.cluster.gc_txn_records()
                    except Exception:
                        pass
                try:
                    # metric samples into the KV-backed time-series DB
                    # + its rollup/prune pass (pkg/ts maintenance).
                    # Fine-slab retention follows the cluster setting
                    # (timeseries.storage.resolution_10s.ttl analogue)
                    self.tsdb.record()
                    self.run_ts_maintenance()
                except Exception:
                    pass

        self._maint_thread = threading.Thread(target=loop, daemon=True)
        self._maint_thread.start()

    def run_ts_maintenance(self) -> None:
        """One tsdb rollup/prune pass with the fine-slab retention
        taken from the ``timeseries.retention.seconds`` cluster
        setting (factored out of the maintenance loop so tests can
        tick it synchronously)."""
        try:
            fine_s = int(self.settings.get(
                "timeseries.retention.seconds"))
        except Exception:
            fine_s = 6 * 3600
        self.tsdb.maintain(retention_fine_s=fine_s)

    def stop(self):
        if getattr(self, "_maint_stop", None) is not None:
            self._maint_stop.set()
            self._maint_thread.join(timeout=5)
            self._maint_stop = None
        if self._gossip_stop is not None:
            self._gossip_stop.set()
            self._gossip_thread.join(timeout=5)
        if self.rpc is not None:
            self.rpc.close()
            self.rpc = None
        if self.pg is not None:
            self.pg.stop()
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
            self._http = None
        if self._started:
            from ..utils import log
            log.structured(log.OPS, "node_stop",
                           node_id=self.config.node_id)
        self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
