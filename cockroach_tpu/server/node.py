"""Node lifecycle: assemble subsystems and serve clients.

The analogue of the reference's server package (pkg/server/server.go:203
``NewServer`` wires rpc/gossip/kv/sql together; ``PreStart``
server.go:1213 boots them in dependency order; ``AcceptClients``
server.go:1915 opens the pgwire listener). Here a Node owns the
columnstore scan plane, the HLC clock, the transactional KV plane
(inside Engine), cluster settings, and the pgwire server; ``start()``
brings them up and returns once the SQL listener is bound.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import __version__
from ..exec.engine import Engine
from ..storage.columnstore import ColumnStore
from ..storage.hlc import Clock
from ..utils.settings import Settings
from .pgwire import PgServer


@dataclass
class NodeConfig:
    listen_host: str = "127.0.0.1"
    listen_port: int = 0          # 0 = ephemeral (tests); CLI default 26257
    http_port: int | None = 0     # status/metrics; None disables
    mesh: object = None           # optional device mesh for DistSQL
    load_tpch_sf: float | None = None  # demo mode: preload TPC-H tables
    # cluster fabric: this node's id + RPC port, and peer addresses to
    # join ({node_id: (host, port)}); None disables the fabric
    node_id: int = 1
    rpc_port: int | None = None
    join: dict | None = None
    gossip_interval: float = 0.2
    # tests: a shared rpc.FaultInjector (seeded nemesis schedule for
    # the socket fabric); None = faults off
    fault_injector: object = None
    # background maintenance loop: orphaned-job adoption + MVCC GC
    # passes (the store queues / job registry adoption loops of the
    # reference); None disables
    maintenance_interval: float | None = None
    # raft-replicated data plane: a kvserver.Cluster shared by the
    # nodes of one logical cluster. With this set, the node's SQL
    # engine serves DML/catalog/jobs from replicated ranges
    # (kv/rangekv.py) instead of a node-local store — several Nodes
    # handed the same Cluster serve the same data (VERDICT r3 #1c)
    cluster: object = None
    # pgwire password gate: {user: cleartext password}; None = insecure
    # mode (the reference's --insecure), every user accepted
    auth: dict | None = None
    # TLS: directory holding node.crt/node.key (cli.py `cert` creates
    # them); None serves plaintext only
    certs_dir: str | None = None


class Node:
    def __init__(self, config: NodeConfig | None = None):
        self.config = config or NodeConfig()
        self.clock = Clock()
        self.store = ColumnStore()
        self.settings = Settings()
        self.engine = Engine(store=self.store, clock=self.clock,
                             settings=self.settings,
                             mesh=self.config.mesh,
                             cluster=self.config.cluster)
        if self.config.cluster is not None:
            self.clock = self.engine.clock  # one HLC per cluster
        from ..jobs import IMPORT_JOB, ImportResumer
        # share the engine's registry (schema-change/changefeed/backup/
        # restore/ttl resumers pre-registered) so the maintenance loop
        # can adopt ANY orphaned job type
        self.jobs = self.engine.jobs
        self.jobs.session_id = f"node-{self.config.node_id}"
        self.jobs.register(IMPORT_JOB, lambda: ImportResumer(self.engine))
        self.pg: PgServer | None = None
        self._http = None
        self.rpc = None
        self.gossip = None
        self._gossip_stop = None
        self._started = False
        # internal time-series DB: metrics recorded into the KV plane
        # by the maintenance loop (pkg/ts analogue, server/ts.py)
        from .ts import TimeSeriesDB
        self.tsdb = TimeSeriesDB(self.engine.kv, self.engine.metrics)

    @property
    def sql_addr(self) -> tuple[str, int]:
        assert self.pg is not None, "node not started"
        return self.pg.addr

    @property
    def http_addr(self) -> tuple[str, int]:
        assert self._http is not None, "status server not started"
        return self._http.server_address[:2]

    def _start_status_server(self):
        """Status/metrics HTTP endpoint (pkg/server/status: /healthz,
        /_status/vars Prometheus text)."""
        import http.server
        import json
        import threading

        node = self

        class H(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path in ("/metrics", "/_status/vars"):
                    body = node.engine.metrics.to_prometheus().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path.startswith("/ts/query"):
                    from urllib.parse import parse_qs, urlparse
                    q = parse_qs(urlparse(self.path).query)
                    try:
                        pts = node.tsdb.query(
                            q["name"][0],
                            int(q.get("start", ["0"])[0]),
                            int(q.get("end", [str(2**62)])[0]),
                            downsample_s=int(
                                q.get("downsample", ["10"])[0]),
                            agg=q.get("agg", ["avg"])[0],
                            rate=q.get("rate", ["0"])[0] == "1")
                        body = json.dumps(pts).encode()
                    except (KeyError, ValueError) as ex:
                        self.send_response(400)
                        self.end_headers()
                        self.wfile.write(str(ex).encode())
                        return
                    ctype = "application/json"
                elif self.path == "/ts/metrics":
                    body = json.dumps(
                        node.tsdb.list_metrics()).encode()
                    ctype = "application/json"
                elif self.path == "/healthz":
                    body = json.dumps({
                        "status": "ok",
                        "version": __version__,
                        "tables": len(node.store.tables),
                        "hbm_used_bytes": node.engine.hbm.used,
                    }).encode()
                    ctype = "application/json"
                elif self.path == "/_status/nodes":
                    # `cockroach node status` backing (pkg/server/
                    # status.go Nodes): this node + its fabric view
                    mon = getattr(node, "peer_monitor", None)
                    peers = {}
                    if mon is not None:
                        ids = set(mon.misses) | set(mon.rtt_ns)
                        peers = {str(p): {
                            "healthy": mon.healthy(p),
                            "rtt_ns": mon.rtt_ns.get(p),
                            "clock_offset_ns": mon.offset_ns.get(p),
                        } for p in sorted(ids)}
                    body = json.dumps({
                        "node_id": node.config.node_id,
                        "version": __version__,
                        "sql_addr": list(node.sql_addr),
                        "tables": sorted(node.store.tables),
                        "peers": peers,
                    }).encode()
                    ctype = "application/json"
                elif self.path == "/_status/statements":
                    # per-fingerprint statement stats (pkg/server
                    # /statements.go Statements endpoint)
                    body = json.dumps({"statements": [{
                        "fingerprint": s.fingerprint,
                        "count": s.count,
                        "mean_latency_s": s.mean_latency_s,
                        "max_latency_s": s.max_latency_s,
                        # compile-vs-execute split (exec/coldstart.py
                        # per-thread XLA compile attribution): high
                        # mean_compile_s with low mean_exec_s means
                        # the fix is cache/prewarm, not the plan
                        "total_compile_s": s.total_compile_s,
                        "mean_compile_s": s.mean_compile_s,
                        "mean_exec_s": s.mean_exec_s,
                        "total_rows": s.total_rows,
                        "failures": s.failures,
                    } for s in node.engine.sqlstats.all()]}).encode()
                    ctype = "application/json"
                elif self.path == "/debug/tracez":
                    # ring buffer of recent slow-statement trace
                    # recordings (threshold via the cluster setting
                    # sql.trace.slow_statement.threshold; the tracez
                    # snapshot page of the reference)
                    body = json.dumps({"traces": list(
                        node.engine.slow_traces)}).encode()
                    ctype = "application/json"
                elif self.path == "/_debug/ranges":
                    # `cockroach debug` analogue: range descriptors +
                    # leaseholders when this node serves a cluster
                    c = node.config.cluster
                    if c is None:
                        body = json.dumps({"ranges": []}).encode()
                    else:
                        rngs = []
                        for rid, desc in sorted(
                                c.descriptors.items()):
                            rngs.append({
                                "range_id": rid,
                                "start": desc.start_key.decode(
                                    "latin1"),
                                "end": desc.end_key.decode("latin1"),
                                "replicas": list(desc.replicas),
                                "leaseholder": c.leaseholder(rid),
                            })
                        body = json.dumps({"ranges": rngs}).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class Srv(http.server.ThreadingHTTPServer):
            daemon_threads = True

        self._http = Srv((self.config.listen_host,
                          self.config.http_port), H)
        threading.Thread(target=self._http.serve_forever,
                         name="status-http", daemon=True).start()

    def _start_fabric(self):
        """RPC listener + gossip loop (pkg/rpc, pkg/gossip): cluster
        settings set on any node converge on all of them."""
        import threading

        from ..rpc import Gossip, SocketTransport
        from ..rpc.gossip import wire_settings

        cfg = self.config
        self.rpc = SocketTransport(cfg.node_id, cfg.listen_host,
                                   cfg.rpc_port,
                                   injector=cfg.fault_injector)
        peers = [cfg.node_id]
        for nid, addr in (cfg.join or {}).items():
            self.rpc.connect(nid, tuple(addr))
            peers.append(nid)
        self.gossip = Gossip(cfg.node_id, self.rpc, peers=peers)
        # fabric liveness: heartbeats + per-peer breakers + clock-skew
        # checks ride the same loop (pkg/rpc/heartbeat.go analogue)
        from ..rpc.heartbeat import PeerMonitor
        self.peer_monitor = PeerMonitor(cfg.node_id, self.rpc)
        # extensible fabric dispatch: gossip consumes its own payloads
        # (handle() returns False otherwise); other subsystems add
        # themselves under a message "kind" without clobbering gossip
        self.rpc_handlers: dict[str, object] = {}

        def dispatch(frm, msg):
            if self.peer_monitor.handle(frm, msg):
                return
            if self.gossip.handle(frm, msg):
                return
            kind = msg.get("kind") if isinstance(msg, dict) else None
            h = self.rpc_handlers.get(kind)
            if h is not None:
                h(frm, msg)

        self.rpc.register(cfg.node_id, dispatch)
        wire_settings(self.gossip, self.settings)
        self.gossip.add_info(f"node:{cfg.node_id}:sql_addr",
                             list(self.sql_addr))
        self._gossip_stop = threading.Event()
        rpc, gossip, stop = self.rpc, self.gossip, self._gossip_stop

        monitor = self.peer_monitor

        def loop():
            # locals, not self.*: stop() nulls the attributes while
            # this thread may still be mid-tick
            while not stop.is_set():
                gossip.tick()
                monitor.tick()
                rpc.deliver_all()
                stop.wait(cfg.gossip_interval)

        self._gossip_thread = threading.Thread(target=loop,
                                               name="gossip", daemon=True)
        self._gossip_thread.start()

    def connect_peer(self, node_id: int, rpc_addr) -> None:
        """Late join: learn a peer after startup."""
        assert self.rpc is not None
        self.rpc.connect(node_id, tuple(rpc_addr))
        if node_id not in self.gossip.peers:
            self.gossip.peers.append(node_id)

    def start(self) -> "Node":
        if self._started:
            return self
        if self.config.load_tpch_sf is not None:
            from ..models import tpch
            tpch.load(self.engine, sf=self.config.load_tpch_sf)
        self.pg = PgServer(self.engine, self.config.listen_host,
                           self.config.listen_port,
                           version=__version__,
                           auth=self.config.auth,
                           certs_dir=self.config.certs_dir).start()
        if self.config.http_port is not None:
            self._start_status_server()
        if self.config.rpc_port is not None:
            self._start_fabric()
        if self.config.maintenance_interval is not None:
            self._start_maintenance()
        self._started = True
        from ..utils import log
        log.structured(log.OPS, "node_start",
                       node_id=self.config.node_id,
                       sql_addr="%s:%d" % self.pg.addr)
        return self

    def _start_maintenance(self):
        """Adopt orphaned jobs (registry.go:1508 adoption loop) and run
        MVCC GC passes (mvcc_gc_queue) on a background cadence."""
        import threading

        self._maint_stop = threading.Event()

        def loop():
            while not self._maint_stop.wait(
                    self.config.maintenance_interval):
                try:
                    self.jobs.adopt_and_run_all()
                except Exception:
                    pass  # job failures land in their records
                for name in list(self.engine.store.tables):
                    if name.startswith("__"):
                        continue
                    try:
                        self.engine.run_gc(name)
                    except Exception:
                        pass
                try:
                    # abandoned-intent sweep (intentresolver analogue):
                    # clears intents of crashed coordinators so reads
                    # never pay a push for them
                    self.engine.kv.store.intent_resolver.clean_span()
                except Exception:
                    pass
                if self.engine.cluster is not None:
                    try:
                        # aged-out aborted txn records (gc/gc.go)
                        self.engine.cluster.gc_txn_records()
                    except Exception:
                        pass
                try:
                    # metric samples into the KV-backed time-series DB
                    # + its rollup/prune pass (pkg/ts maintenance)
                    self.tsdb.record()
                    self.tsdb.maintain()
                except Exception:
                    pass

        self._maint_thread = threading.Thread(target=loop, daemon=True)
        self._maint_thread.start()

    def stop(self):
        if getattr(self, "_maint_stop", None) is not None:
            self._maint_stop.set()
            self._maint_thread.join(timeout=5)
            self._maint_stop = None
        if self._gossip_stop is not None:
            self._gossip_stop.set()
            self._gossip_thread.join(timeout=5)
        if self.rpc is not None:
            self.rpc.close()
            self.rpc = None
        if self.pg is not None:
            self.pg.stop()
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
            self._http = None
        if self._started:
            from ..utils import log
            log.structured(log.OPS, "node_stop",
                           node_id=self.config.node_id)
        self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
