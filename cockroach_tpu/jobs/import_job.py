"""Resumable bulk IMPORT into the columnstore.

The analogue of the reference's IMPORT (pkg/sql/importer: distributed
AddSSTable ingestion, checkpointed through the jobs system). Data
arrives chunk-at-a-time from a deterministic generator (seeded
synthetic columns here; a CSV reader is a drop-in generator), each
chunk lands as one sealed columnstore chunk, and progress checkpoints
after every chunk.

Exactly-once across crashes WITHOUT transactional coupling between the
scan-plane ingest and the jobs record: the job records the table's
baseline row count when it first starts, so on resume the number of
chunks already ingested is recomputed from the store itself
((row_count - baseline) // chunk_rows) rather than trusted from the
possibly-stale checkpoint. A crash between ingest and checkpoint
therefore never double-ingests (cf. AddSSTable's idempotent keyed
ranges, backupccl checkpoint loop backup_job.go:230-266).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from .registry import JobContext, _CrashForTesting

IMPORT_JOB = "IMPORT"


def synthetic_chunk(seed: int, chunk_index: int, chunk_rows: int,
                    columns: dict) -> dict:
    """Deterministic per-chunk columns: chunk i is identical no matter
    when or where it is generated (resume safety). ``columns`` maps
    name -> ("int" | "float" | dict-size int for coded strings)."""
    rng = np.random.default_rng((seed << 20) ^ chunk_index)
    out = {}
    for name, kind in columns.items():
        if kind == "int":
            out[name] = rng.integers(0, 1 << 30,
                                     size=chunk_rows).astype(np.int64)
        elif kind == "float":
            out[name] = rng.random(chunk_rows)
        else:  # coded string column with `kind` distinct values
            out[name] = rng.integers(0, int(kind),
                                     size=chunk_rows).astype(np.int32)
    return out


class ImportResumer:
    """payload: {table, total_rows, chunk_rows, seed, columns}
    progress: {baseline_rows, chunks_done}"""

    def __init__(self, engine,
                 chunk_generator: Optional[Callable] = None,
                 crash_after_chunk: Optional[int] = None):
        self.engine = engine
        self.generate = chunk_generator or synthetic_chunk
        self.crash_after_chunk = crash_after_chunk

    def resume(self, ctx: JobContext) -> None:
        p = ctx.payload
        table = p["table"]
        total = int(p["total_rows"])
        chunk_rows = int(p["chunk_rows"])
        n_chunks = (total + chunk_rows - 1) // chunk_rows
        store = self.engine.store
        td = store.table(table)

        prog = ctx.progress()
        if "baseline_rows" not in prog:
            prog = {"baseline_rows": td.row_count, "chunks_done": 0}
            ctx.checkpoint(prog, fraction=0.0)
        # exactly-once: recompute what actually landed in the store —
        # the checkpoint may be one chunk behind a crash. The final
        # chunk may be partial, so "everything arrived" must be tested
        # by row count, not by dividing by the full chunk size.
        baseline = int(prog["baseline_rows"])
        done_rows = td.row_count - baseline
        done = n_chunks if done_rows >= total else done_rows // chunk_rows

        for ci in range(done, n_chunks):
            ctx.check_cancel()
            rows = min(chunk_rows, total - ci * chunk_rows)
            cols = self.generate(int(p["seed"]), ci, rows, p["columns"])
            store.insert_columns(table, cols, self.engine.clock.now())
            if (self.crash_after_chunk is not None
                    and ci >= self.crash_after_chunk):
                raise _CrashForTesting()
            ctx.checkpoint({"baseline_rows": baseline,
                            "chunks_done": ci + 1},
                           fraction=(ci + 1) / n_chunks)

    def on_fail_or_cancel(self, ctx: JobContext) -> None:
        # imported chunks stay (MVCC tombstoning a partial import is
        # round-3 work, as is the reference's RESTORE-style cleanup)
        pass
