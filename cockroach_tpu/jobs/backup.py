"""BACKUP / RESTORE jobs: table data to/from a backup directory.

The analogue of pkg/ccl/backupccl: BACKUP writes per-table data files
plus a manifest; running the same BACKUP INTO an existing directory
appends an INCREMENTAL layer capturing only the MVCC window since the
previous backup (new/updated rows + deleted keys). RESTORE replays the
full layer then each incremental in order. Both run as durable jobs
with per-table checkpoints (backup_job.go:230-266's checkpointing
loop), so a crashed backup resumes without redoing finished tables.

Data files are .npz column bundles — the storage-native stand-in for
the reference's exported SSTs (a backup file format is an
implementation detail; what the tests pin down is the window algebra
and resume semantics).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from ..storage.columnstore import MAX_TS_INT
from ..storage.hlc import Timestamp
from .registry import JobContext

BACKUP_JOB = "backup"
RESTORE_JOB = "restore"

MANIFEST = "BACKUP_MANIFEST.json"


def _load_manifest(dest: str) -> dict:
    path = os.path.join(dest, MANIFEST)
    if not os.path.exists(path):
        return {"layers": []}
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def _save_manifest(dest: str, m: dict) -> None:
    path = os.path.join(dest, MANIFEST)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(m, f, sort_keys=True, indent=1)
    os.replace(tmp, path)  # atomic: a torn manifest is unreadable


class BackupResumer:
    """payload: {tables, dest}; progress: {end_ts, tables_done}."""

    def __init__(self, engine, crash_after_table: Optional[int] = None):
        self.engine = engine
        self.crash_after_table = crash_after_table

    def resume(self, ctx: JobContext) -> None:
        p = ctx.payload
        dest = p["dest"]
        os.makedirs(dest, exist_ok=True)
        store = self.engine.store
        manifest = _load_manifest(dest)
        prev_end = manifest["layers"][-1]["end_ts"] \
            if manifest["layers"] else 0
        prog = ctx.progress()
        # the backup timestamp is fixed ONCE (at first run) so a
        # resumed backup stays a consistent snapshot
        end_ts = int(prog.get("end_ts") or
                     self.engine.clock.now().to_int())
        done = set(prog.get("tables_done", []))
        if "end_ts" not in prog:
            ctx.checkpoint({"end_ts": end_ts, "tables_done": []})

        layer_id = len(manifest["layers"])
        layer = {"start_ts": prev_end, "end_ts": end_ts, "tables": {}}
        for i, table in enumerate(p["tables"]):
            ctx.check_cancel()
            fname = f"l{layer_id}_{table}.npz"
            if table not in done:
                self._export_table(table, prev_end, end_ts,
                                   os.path.join(dest, fname))
                done.add(table)
                if (self.crash_after_table is not None
                        and len(done) > self.crash_after_table):
                    from .registry import _CrashForTesting
                    raise _CrashForTesting()
                ctx.checkpoint({"end_ts": end_ts,
                                "tables_done": sorted(done)},
                               fraction=len(done) / len(p["tables"]))
            desc = self.engine.catalog.get_by_name(table)
            layer["tables"][table] = {
                "file": fname,
                "descriptor": desc.encode().decode()
                if desc is not None else None,
            }
        manifest["layers"].append(layer)
        _save_manifest(dest, manifest)
        # the incremental chain needs every version since end_ts to
        # survive GC until the NEXT layer runs: move the chain's
        # protection record forward (pkg/kv/kvserver/protectedts)
        pts = self.engine.protectedts
        for rec_id, _ts, _tables, meta in pts.records():
            if meta == dest:
                pts.release(rec_id)
        pts.protect(end_ts, p["tables"], meta=dest)

    def _export_table(self, table: str, lo: int, hi: int,
                      path: str) -> None:
        """One table's MVCC window (lo, hi]: rows live at hi that were
        written in the window, plus keys deleted in the window."""
        store = self.engine.store
        store.seal(table)
        td = store.table(table)
        codec = td.codec
        cols: dict[str, list] = {c.name: [] for c in td.schema.columns}
        valid: dict[str, list] = {c.name: [] for c in td.schema.columns}
        rowids: list[int] = []
        # deletions are recorded as PRIMARY KEY tuples, not raw key
        # bytes: the restored table gets a fresh table id, so byte keys
        # would never match (keys are re-derived by the restore codec)
        deleted: list[str] = []
        put_pks: set[str] = set()
        n = 0
        for chunk in td.chunks:
            for ri in range(chunk.n):
                wts = int(chunk.mvcc_ts[ri])
                dts = int(chunk.mvcc_del[ri])
                if lo < wts <= hi and dts > hi:
                    row = store.extract_row(td, chunk, ri)
                    for c in td.schema.columns:
                        v = row.get(c.name)
                        cols[c.name].append(v)
                        valid[c.name].append(v is not None)
                    rowids.append(int(chunk.rowid[ri]))
                    put_pks.add(json.dumps(list(codec.pk_values(row))))
                    n += 1
                elif wts <= lo and lo < dts <= hi:
                    row = store.extract_row(td, chunk, ri)
                    deleted.append(json.dumps(
                        list(codec.pk_values(row))))
        # a version superseded by an UPDATE in the same window is not a
        # user deletion: its pk is re-put at the newer version, and the
        # restore applies puts before deletes
        deleted = [d for d in deleted if d not in put_pks]
        arrays: dict[str, np.ndarray] = {}
        for c in td.schema.columns:
            arrays[f"d_{c.name}"] = np.asarray(cols[c.name],
                                               dtype=object)
            arrays[f"v_{c.name}"] = np.asarray(valid[c.name],
                                               dtype=bool)
        arrays["__deleted"] = np.asarray(deleted, dtype=object)
        arrays["__rowid"] = np.asarray(rowids, dtype=np.int64)
        arrays["__n"] = np.asarray([n])
        np.savez_compressed(path, **arrays, allow_pickle=True)

    def on_fail_or_cancel(self, ctx: JobContext) -> None:
        pass  # partial data files are ignored without a manifest entry


class RestoreResumer:
    """payload: {tables, src}; progress: {tables_done}."""

    def __init__(self, engine):
        self.engine = engine

    def resume(self, ctx: JobContext) -> None:
        from ..catalog import TableDescriptor
        p = ctx.payload
        src = p["src"]
        manifest = _load_manifest(src)
        if not manifest["layers"]:
            raise ValueError(f"no backup found in {src!r}")
        done = set(ctx.progress().get("tables_done", []))
        tables = p["tables"] or sorted(
            manifest["layers"][0]["tables"].keys())
        for table in tables:
            ctx.check_cancel()
            if table in done:
                continue
            self._restore_table(table, manifest, src)
            done.add(table)
            ctx.checkpoint({"tables_done": sorted(done)},
                           fraction=len(done) / len(tables))

    def _restore_table(self, table: str, manifest: dict,
                       src: str) -> None:
        from ..catalog import TableDescriptor
        from ..sql import ast
        eng = self.engine
        first = manifest["layers"][0]["tables"].get(table)
        if first is None:
            raise ValueError(f"table {table!r} not in backup")
        if table in eng.store.tables:
            raise ValueError(f"table {table!r} already exists")
        desc = TableDescriptor.decode(first["descriptor"].encode())
        schema = desc.public_schema()
        created = eng.catalog.create_table(
            TableDescriptor.from_schema(schema))
        schema.table_id = created.id
        eng.store.create_table(schema)
        ts = eng.clock.now()
        for layer in manifest["layers"]:
            entry = layer["tables"].get(table)
            if entry is None:
                continue
            self._apply_layer(table, os.path.join(src, entry["file"]),
                              ts)
        # preserved rowids must not collide with future inserts
        td = eng.store.table(table)
        top = max((int(c.rowid.max()) for c in td.chunks if c.n),
                  default=0)
        td.next_rowid = max(td.next_rowid, top + 1)

    def _apply_layer(self, table: str, path: str,
                     ts: Timestamp) -> None:
        from ..sql.rowenc import ROWID
        store = self.engine.store
        td = store.table(table)
        codec = td.codec
        with np.load(path, allow_pickle=True) as z:
            n = int(z["__n"][0])
            ops: list = []
            if n:
                names = [c.name for c in td.schema.columns]
                rowids = z["__rowid"]
                for i in range(n):
                    row = {}
                    for cn in names:
                        if bool(z[f"v_{cn}"][i]):
                            v = z[f"d_{cn}"][i]
                            row[cn] = v.item() if hasattr(v, "item") \
                                else v
                    row[ROWID] = int(rowids[i])
                    ops.append(("put", codec.key(row), row))
            for pk_json in z["__deleted"]:
                pk = tuple(json.loads(str(pk_json)))
                ops.append(("del", codec.key_from_pk(pk)))
            if ops:
                store.apply_committed(table, ops, ts)

    def on_fail_or_cancel(self, ctx: JobContext) -> None:
        pass
