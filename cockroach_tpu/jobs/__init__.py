"""Jobs: durable registry + checkpoint/resume (reference: pkg/jobs)."""

from .import_job import IMPORT_JOB, ImportResumer, synthetic_chunk
from .registry import (CANCELED, FAILED, PENDING, RUNNING, SUCCEEDED,
                       JobCanceled, JobContext, JobRecord, JobsError,
                       Registry)
from .schemachange import SCHEMA_CHANGE_JOB, SchemaChangeResumer

__all__ = ["Registry", "JobRecord", "JobContext", "JobsError",
           "JobCanceled", "ImportResumer", "IMPORT_JOB",
           "synthetic_chunk", "PENDING", "RUNNING", "SUCCEEDED",
           "FAILED", "CANCELED", "SCHEMA_CHANGE_JOB",
           "SchemaChangeResumer"]
