"""Durable jobs: registry, leasing, checkpointed resume.

The analogue of the reference's jobs system (pkg/jobs/registry.go:1317
``Resumer{Resume,OnFailOrCancel}``; adoption/leasing registry.go:1508;
progress persistence progress.go). Job records are JSON rows in the
transactional KV plane under /System/jobs/<id>, so claims are
serializable txns and progress checkpoints survive the death of the
node running the job: a new registry (same store, new session) adopts
any job whose lease lapsed and resumes it from its last checkpoint.

Single-process scope for now: adoption is driven by explicit
``adopt_and_run_all()`` / ``run_job()`` calls (a Node wires these to a
background loop); multi-node adoption arrives with the cluster fabric.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..kv.txn import DB as KVDB

JOBS_PREFIX = b"/System/jobs/"

PENDING = "pending"
RUNNING = "running"
SUCCEEDED = "succeeded"
FAILED = "failed"
CANCELED = "canceled"
CANCEL_REQUESTED = "cancel-requested"


class JobsError(Exception):
    pass


class JobCanceled(JobsError):
    """Raised inside a Resumer by ctx.check_cancel()."""


class LeaseLostError(JobsError):
    """The job's lease moved to another session: a pre-empted runner
    must stop instead of clobbering the adopter's progress."""


@dataclass
class JobRecord:
    id: int
    type: str
    payload: dict
    status: str = PENDING
    progress: dict = field(default_factory=dict)
    lease_owner: str = ""
    lease_expires: float = 0.0   # unix seconds; 0 = unleased
    error: str = ""
    fraction_completed: float = 0.0

    def encode(self) -> bytes:
        return json.dumps(self.__dict__, sort_keys=True).encode()

    @staticmethod
    def decode(raw: bytes) -> "JobRecord":
        return JobRecord(**json.loads(raw.decode()))


def _job_key(job_id: int) -> bytes:
    return JOBS_PREFIX + f"{job_id:016d}".encode()


class JobContext:
    """What a Resumer sees while running (the jobs.Job handle)."""

    def __init__(self, registry: "Registry", record: JobRecord):
        self._registry = registry
        self.job_id = record.id
        self.payload = dict(record.payload)
        self._progress = dict(record.progress)

    def progress(self) -> dict:
        return dict(self._progress)

    def checkpoint(self, progress: dict,
                   fraction: Optional[float] = None) -> None:
        """Persist progress NOW (cf. backupccl's checkpoint loop,
        backup_job.go:230-266 — ours is synchronous per call). Raises
        LeaseLostError if another session adopted the job meanwhile —
        the slow runner must abandon, not overwrite the adopter."""
        self._registry._update(self.job_id, progress=dict(progress),
                               fraction=fraction,
                               expect_owner=self._registry.session_id)
        self._progress = dict(progress)

    def check_cancel(self) -> None:
        rec = self._registry.job(self.job_id)
        if rec.status == CANCEL_REQUESTED:
            raise JobCanceled(f"job {self.job_id} canceled")


class Registry:
    """Create, claim, run, and observe jobs against one KV store."""

    def __init__(self, db: KVDB, session_id: str = "node-1",
                 lease_seconds: float = 10.0,
                 now: Callable[[], float] = time.time):
        self.db = db
        self.session_id = session_id
        self.lease_seconds = lease_seconds
        self.now = now
        self._resumers: dict[str, Callable[[], object]] = {}
        self._next_id_hint = 1

    # -- registration --------------------------------------------------------
    def register(self, job_type: str, factory: Callable[[], object]) -> None:
        """factory() -> object with resume(ctx) and (optionally)
        on_fail_or_cancel(ctx) — the Resumer interface
        (jobs/registry.go:1317,1336)."""
        self._resumers[job_type] = factory

    # -- creation ------------------------------------------------------------
    def create(self, job_type: str, payload: dict) -> int:
        if job_type not in self._resumers:
            raise JobsError(f"no resumer registered for {job_type!r}")

        def txn(t):
            # allocate the next id under the txn (scan the tail)
            jid = self._next_id_hint
            while t.get(_job_key(jid)) is not None:
                jid += 1
            rec = JobRecord(id=jid, type=job_type, payload=payload)
            t.put(_job_key(jid), rec.encode())
            return jid
        jid = self.db.txn(txn)
        self._next_id_hint = jid + 1
        return jid

    # -- observation ---------------------------------------------------------
    def job(self, job_id: int) -> JobRecord:
        raw = self.db.get(_job_key(job_id))
        if raw is None:
            raise JobsError(f"job {job_id} does not exist")
        return JobRecord.decode(raw)

    def jobs(self) -> list[JobRecord]:
        out = []
        for _k, v in self.db.scan(JOBS_PREFIX, JOBS_PREFIX + b"\xff"):
            out.append(JobRecord.decode(v))
        return out

    # -- lifecycle -----------------------------------------------------------
    def _update(self, job_id: int, expect_owner: Optional[str] = None,
                **changes) -> JobRecord:
        def txn(t):
            raw = t.get(_job_key(job_id))
            if raw is None:
                raise JobsError(f"job {job_id} vanished")
            rec = JobRecord.decode(raw)
            if expect_owner is not None and rec.lease_owner != expect_owner:
                raise LeaseLostError(
                    f"job {job_id} lease now held by "
                    f"{rec.lease_owner!r}, not {expect_owner!r}")
            if "progress" in changes:
                rec.progress = changes["progress"]
            if changes.get("fraction") is not None:
                rec.fraction_completed = float(changes["fraction"])
            for f in ("status", "lease_owner", "lease_expires", "error"):
                if f in changes:
                    setattr(rec, f, changes[f])
            t.put(_job_key(job_id), rec.encode())
            return rec
        return self.db.txn(txn)

    def _try_claim(self, job_id: int) -> Optional[JobRecord]:
        """Serializable claim: pending, or running with a lapsed lease
        (the dead-node adoption path, registry.go:1508)."""
        now = self.now()

        def txn(t):
            raw = t.get(_job_key(job_id))
            if raw is None:
                return None
            rec = JobRecord.decode(raw)
            adoptable = (
                rec.status == PENDING
                or (rec.status == RUNNING
                    and (rec.lease_owner == self.session_id
                         or rec.lease_expires <= now))
                or rec.status == CANCEL_REQUESTED)
            if not adoptable:
                return None
            if rec.status != CANCEL_REQUESTED:
                rec.status = RUNNING
            rec.lease_owner = self.session_id
            rec.lease_expires = now + self.lease_seconds
            t.put(_job_key(job_id), rec.encode())
            return rec
        return self.db.txn(txn)

    def run_job(self, job_id: int) -> JobRecord:
        """Claim and run one job to a terminal state (synchronously)."""
        rec = self._try_claim(job_id)
        if rec is None:
            return self.job(job_id)
        from ..utils import log
        log.structured(log.JOBS, "job_run", job_id=job_id,
                       job_type=rec.type, owner=self.session_id)
        factory = self._resumers.get(rec.type)
        if factory is None:
            return self._update(job_id, status=FAILED,
                                error=f"no resumer for {rec.type!r}")
        resumer = factory()
        ctx = JobContext(self, rec)
        if rec.status == CANCEL_REQUESTED:
            if hasattr(resumer, "on_fail_or_cancel"):
                resumer.on_fail_or_cancel(ctx)
            return self._update(job_id, status=CANCELED,
                                lease_owner="", lease_expires=0.0)
        try:
            resumer.resume(ctx)
        except LeaseLostError:
            # another session adopted the job out from under this one
            # (lease lapsed mid-chunk): abandon without touching the
            # record — the adopter owns it now
            return self.job(job_id)
        except JobCanceled:
            if hasattr(resumer, "on_fail_or_cancel"):
                resumer.on_fail_or_cancel(ctx)
            return self._update(job_id, status=CANCELED,
                                expect_owner=self.session_id,
                                lease_owner="", lease_expires=0.0)
        except _CrashForTesting:
            # simulated node death: leave RUNNING with the lease intact
            # — only lease expiry lets another registry adopt it
            raise
        except Exception as e:  # Resumer failure -> terminal FAILED
            from ..utils import log
            log.error(log.JOBS, "job %s (%s) failed: %s",
                      job_id, rec.type, e)
            if hasattr(resumer, "on_fail_or_cancel"):
                try:
                    resumer.on_fail_or_cancel(ctx)
                except Exception:
                    pass
            try:
                return self._update(job_id, status=FAILED, error=str(e),
                                    expect_owner=self.session_id,
                                    lease_owner="", lease_expires=0.0)
            except LeaseLostError:
                return self.job(job_id)
        try:
            return self._update(job_id, status=SUCCEEDED,
                                fraction=1.0,
                                expect_owner=self.session_id,
                                lease_owner="", lease_expires=0.0)
        except LeaseLostError:
            return self.job(job_id)

    def adopt_and_run_all(self) -> list[JobRecord]:
        """Run every adoptable job once (the adoption loop's body)."""
        out = []
        for rec in self.jobs():
            if rec.status in (PENDING, CANCEL_REQUESTED) or (
                    rec.status == RUNNING
                    and rec.lease_expires <= self.now()):
                out.append(self.run_job(rec.id))
        return out

    def cancel(self, job_id: int) -> JobRecord:
        rec = self.job(job_id)
        if rec.status in (SUCCEEDED, FAILED, CANCELED):
            return rec
        if rec.status == PENDING:
            return self._update(job_id, status=CANCELED)
        return self._update(job_id, status=CANCEL_REQUESTED)


class _CrashForTesting(BaseException):
    """TestingKnobs-style fault injection: simulates the process dying
    mid-job (lease stays, progress stays at the last checkpoint)."""
