"""Row-level TTL deletion job (the analogue of pkg/ttl).

A table opts in with a TTL column and duration; the TTL job scans for
expired rows (ttl_col + ttl_seconds <= now) and deletes them in
batches through ordinary DML — so deletions are transactional, visible
to changefeeds, and GC'd like any other tombstone. Progress
checkpoints the per-table deleted count; the job is idempotent (a
resumed pass re-selects only still-expired rows).

The reference drives this from a scheduled job per table reading
descriptor TTL config; here the config lives in the descriptor-adjacent
payload and the schedule is the caller's (Node loop / tests).
"""

from __future__ import annotations

from .registry import JobContext

TTL_JOB = "row-ttl"


class TTLResumer:
    """payload: {table, ttl_col, ttl_seconds, batch_rows}."""

    def __init__(self, engine):
        self.engine = engine

    def resume(self, ctx: JobContext) -> None:
        p = ctx.payload
        table = p["table"]
        col = p["ttl_col"]
        ttl_s = int(p["ttl_seconds"])
        batch = int(p.get("batch_rows", 1000))
        e = self.engine
        if table not in e.store.tables:
            return
        ty = e.store.table(table).schema.column(col).type
        now_us = e.clock.now().wall // 1000
        cutoff_us = now_us - ttl_s * 1_000_000
        if ty.family.value == "date":
            cutoff_lit = (f"date '1970-01-01' + interval "
                          f"'{cutoff_us // 86_400_000_000} day'")
        else:
            import datetime
            dt = (datetime.datetime(1970, 1, 1)
                  + datetime.timedelta(microseconds=cutoff_us))
            cutoff_lit = f"timestamp '{dt.isoformat(sep=' ')}'"
        deleted = int(ctx.progress().get("deleted", 0))
        while True:
            ctx.check_cancel()
            # batch-bounded delete: expired pks first, then targeted
            # deletes (the reference's SELECT..DELETE batching)
            n = e.execute(
                f"DELETE FROM {table} WHERE {col} <= {cutoff_lit}"
            ).row_count
            deleted += n
            ctx.checkpoint({"deleted": deleted})
            if n == 0 or n < batch:
                break

    def on_fail_or_cancel(self, ctx: JobContext) -> None:
        pass
