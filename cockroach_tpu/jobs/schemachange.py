"""Schema-change job: online ADD COLUMN backfill.

The analogue of the reference's schema changer running as a job
(pkg/sql/schemachanger executed through pkg/jobs; legacy backfill in
pkg/sql/backfill): the column is added to the descriptor in WRITE_ONLY
state and to the scan plane hidden, then this job backfills sealed
chunks one at a time (each chunk a checkpoint), and finally publishes
the descriptor version with the column PUBLIC and unhides it. Every
step is idempotent, so a crashed job resumes from its checkpoint and
a re-run of a finished step is a no-op.
"""

from __future__ import annotations

from .registry import JobContext

SCHEMA_CHANGE_JOB = "schema-change"


class SchemaChangeResumer:
    """payload: {table, column}; progress: {chunks_done}."""

    def __init__(self, engine, crash_after_chunk=None):
        self.engine = engine
        self.crash_after_chunk = crash_after_chunk

    def resume(self, ctx: JobContext) -> None:
        from ..catalog import CatalogError
        from ..catalog.descriptor import PUBLIC
        p = ctx.payload
        table, column = p["table"], p["column"]
        store = self.engine.store
        catalog = self.engine.catalog

        desc = catalog.get_by_name(table)
        if desc is None:
            raise CatalogError(f"table {table!r} vanished mid-change")
        col = desc.column(column)
        if col.state != PUBLIC:
            # backfill loop: chunks can grow while we run (concurrent
            # inserts), so iterate until none are missing the column
            done = int(ctx.progress().get("chunks_done", 0))
            while True:
                ctx.check_cancel()
                missing = store.unfilled_chunks(table, column)
                if not missing:
                    break
                for ci in missing:
                    ctx.check_cancel()
                    store.backfill_column_chunk(table, column, ci)
                    done += 1
                    if (self.crash_after_chunk is not None
                            and done >= self.crash_after_chunk):
                        from .registry import _CrashForTesting
                        raise _CrashForTesting()
                    ctx.checkpoint({"chunks_done": done})
            # publish: descriptor version+1 with the column PUBLIC,
            # wait for old leases (two-version invariant), then unhide
            # in the scan plane
            col.state = PUBLIC
            self.engine.leases.publish(desc)
        store.publish_column(table, column)
        ctx.checkpoint({"chunks_done": ctx.progress().get(
            "chunks_done", 0), "published": True}, fraction=1.0)

    def on_fail_or_cancel(self, ctx: JobContext) -> None:
        """Roll back: drop the half-added hidden column."""
        p = ctx.payload
        try:
            td = self.engine.store.table(p["table"])
            if any(c.name == p["column"] and c.hidden
                   for c in td.schema.columns):
                self.engine.store.drop_column(p["table"], p["column"])
            desc = self.engine.catalog.get_by_name(p["table"])
            if desc is not None and any(c.name == p["column"]
                                        for c in desc.columns):
                desc.columns = [c for c in desc.columns
                                if c.name != p["column"]]
                self.engine.catalog.write_new_version(desc)
        except KeyError:
            pass
