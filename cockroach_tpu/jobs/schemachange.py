"""Schema-change job: online ADD COLUMN backfill.

The analogue of the reference's schema changer running as a job
(pkg/sql/schemachanger executed through pkg/jobs; legacy backfill in
pkg/sql/backfill): the column is added to the descriptor in WRITE_ONLY
state and to the scan plane hidden, then this job backfills sealed
chunks one at a time (each chunk a checkpoint), and finally publishes
the descriptor version with the column PUBLIC and unhides it. Every
step is idempotent, so a crashed job resumes from its checkpoint and
a re-run of a finished step is a no-op.
"""

from __future__ import annotations

from .registry import JobContext

SCHEMA_CHANGE_JOB = "schema-change"


class SchemaChangeResumer:
    """payload: {table, column}; progress: {chunks_done}."""

    def __init__(self, engine, crash_after_chunk=None):
        self.engine = engine
        self.crash_after_chunk = crash_after_chunk

    def resume(self, ctx: JobContext) -> None:
        from ..catalog import CatalogError
        from ..catalog.descriptor import PUBLIC
        p = ctx.payload
        table, column = p["table"], p["column"]
        store = self.engine.store
        catalog = self.engine.catalog

        desc = catalog.get_by_name(table)
        if desc is None:
            raise CatalogError(f"table {table!r} vanished mid-change")
        col = desc.column(column)
        if col.state != PUBLIC:
            # backfill loop: chunks can grow while we run (concurrent
            # inserts), so iterate until none are missing the column
            done = int(ctx.progress().get("chunks_done", 0))
            while True:
                ctx.check_cancel()
                missing = store.unfilled_chunks(table, column)
                if not missing:
                    break
                for ci in missing:
                    ctx.check_cancel()
                    store.backfill_column_chunk(table, column, ci)
                    done += 1
                    if (self.crash_after_chunk is not None
                            and done >= self.crash_after_chunk):
                        from .registry import _CrashForTesting
                        raise _CrashForTesting()
                    ctx.checkpoint({"chunks_done": done})
            # publish: descriptor version+1 with the column PUBLIC,
            # wait for old leases (two-version invariant), then unhide
            # in the scan plane
            col.state = PUBLIC
            self.engine.leases.publish(desc)
        store.publish_column(table, column)
        ctx.checkpoint({"chunks_done": ctx.progress().get(
            "chunks_done", 0), "published": True}, fraction=1.0)

    def on_fail_or_cancel(self, ctx: JobContext) -> None:
        """Roll back: drop the half-added hidden column."""
        p = ctx.payload
        try:
            td = self.engine.store.table(p["table"])
            if any(c.name == p["column"] and c.hidden
                   for c in td.schema.columns):
                self.engine.store.drop_column(p["table"], p["column"])
            desc = self.engine.catalog.get_by_name(p["table"])
            if desc is not None and any(c.name == p["column"]
                                        for c in desc.columns):
                desc.columns = [c for c in desc.columns
                                if c.name != p["column"]]
                self.engine.catalog.write_new_version(desc)
        except KeyError:
            pass


INDEX_BACKFILL_JOB = "index-backfill"


class IndexBackfillResumer:
    """Online CREATE INDEX (pkg/sql/backfill's index backfiller as a
    job). The descriptor is already published in WRITE_ONLY — every
    writer maintains the index — so this job only has to cover the
    rows that existed before: for UNIQUE indexes it validates
    uniqueness over the live scan plane and materializes the KV
    entries chunk by chunk (each chunk a checkpoint); non-unique
    indexes are derived lazily from the scan plane and need no
    backfill beyond validation that the columns exist.

    payload: {table, index}; progress: {chunks_done}."""

    def __init__(self, engine, crash_after_chunk=None):
        self.engine = engine
        self.crash_after_chunk = crash_after_chunk

    def resume(self, ctx: JobContext) -> None:
        from ..catalog import CatalogError
        from ..catalog.descriptor import PUBLIC
        from ..storage import keys as K
        from ..storage.columnstore import MAX_TS_INT
        p = ctx.payload
        table, iname = p["table"], p["index"]
        engine = self.engine
        store = engine.store
        desc = engine.catalog.get_by_name(table)
        if desc is None:
            raise CatalogError(f"table {table!r} vanished mid-change")
        idx = next((i for i in desc.indexes if i.name == iname), None)
        if idx is None:
            raise CatalogError(f"index {iname!r} vanished mid-change")
        if idx.state != PUBLIC:
            td = store.table(table)
            cols = tuple(idx.columns)
            if idx.unique:
                # validate: no two live rows share a value (writers
                # racing the backfill already maintain KV entries, so
                # they are covered by the same check)
                sec = store.ensure_secondary_index(table, cols)
                for vals, positions in sec.items():
                    ctx.check_cancel()
                    live = [(ci, ri) for ci, ri in positions
                            if td.chunks[ci].mvcc_del[ri] == MAX_TS_INT]
                    if len(live) > 1:
                        raise ValueError(
                            f"duplicate key value {vals!r} violates "
                            f"unique index {iname!r} of {table!r}")
                # materialize KV entries chunk by chunk, checkpointed.
                # The cursor is positional, so it is only valid for
                # the chunk layout it was taken against: a GC pass
                # between crash and resume compacts td.chunks and
                # shifts indices — stamp the generation and restart
                # from 0 on mismatch (entry puts are idempotent).
                done = int(ctx.progress().get("chunks_done", 0))
                if int(ctx.progress().get("generation", -1)) != \
                        td.generation:
                    done = 0
                tid = desc.id
                while True:
                    ctx.check_cancel()
                    n_chunks = len(td.chunks)
                    if done >= n_chunks:
                        break
                    for ci in range(done, n_chunks):
                        ctx.check_cancel()
                        chunk = td.chunks[ci]

                        def fill(t, ci=ci, chunk=chunk):
                            for ri in range(chunk.n):
                                if chunk.mvcc_del[ri] != MAX_TS_INT:
                                    continue
                                row = store.extract_row(td, chunk, ri)
                                vals = tuple(row.get(cn) for cn in cols)
                                if any(v is None for v in vals):
                                    continue
                                t.put(K.table_key(tid, vals,
                                                  idx.index_id),
                                      store.row_key(td, chunk, ri))
                        engine.kv.txn(fill)
                        done = ci + 1
                        if (self.crash_after_chunk is not None
                                and done >= self.crash_after_chunk):
                            from .registry import _CrashForTesting
                            raise _CrashForTesting()
                        ctx.checkpoint({"chunks_done": done,
                                        "generation": td.generation})
            else:
                # warm the derived locator once (also validates the
                # column set against the live schema)
                store.ensure_secondary_index(table, cols)
            idx.state = PUBLIC
            engine.leases.publish(desc)
            engine._index_defs.pop(table, None)
        ctx.checkpoint({"chunks_done": ctx.progress().get(
            "chunks_done", 0), "published": True}, fraction=1.0)

    def on_fail_or_cancel(self, ctx: JobContext) -> None:
        """Roll back: remove the half-built index descriptor and any
        materialized KV entries."""
        from ..storage import keys as K
        p = ctx.payload
        engine = self.engine
        desc = engine.catalog.get_by_name(p["table"])
        if desc is None:
            return
        idx = next((i for i in desc.indexes
                    if i.name == p["index"]), None)
        if idx is None:
            return
        desc.indexes = [i for i in desc.indexes
                        if i.name != p["index"]]
        engine.catalog.write_new_version(desc)
        engine._index_defs.pop(p["table"], None)
        if idx.unique:
            pref = K.table_prefix(desc.id, idx.index_id)
            engine.kv.txn(
                lambda t: t.delete_range(pref, K.prefix_end(pref)))
