"""Table statistics + a simple cost model for the planner.

The analogue of pkg/sql/stats (table statistics + histograms feeding
the optimizer's costing, opt/memo/statistics_builder.go). ANALYZE
<table> computes exact per-column distinct counts and null fractions
over the live rows (our tables are host-resident columns, so "exact"
is one np.unique per column — the reference samples because its data
lives behind the KV API). Row counts are always exact and free.

The cost model is deliberately small: cardinality estimates drive two
real decisions — hash-join build-side selection and the EXPLAIN cost
column — matching the round-2 goal (VERDICT #10), not the reference's
full memo/xform search (opt/xform/optimizer.go:239, later rounds).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from . import plan as P

# default selectivities when no stats apply (the reference's
# unknownFilterSelectivity-style constants, statistics_builder.go)
SEL_EQ = 0.1
SEL_RANGE = 1.0 / 3.0
SEL_OTHER = 0.5


@dataclass
class TableStats:
    row_count: int = 0
    distinct: dict = field(default_factory=dict)   # col -> n distinct
    null_frac: dict = field(default_factory=dict)  # col -> fraction
    analyzed: bool = False


def analyze_columns(td) -> TableStats:
    """Exact stats over a table's live rows (ANALYZE)."""
    from ..storage.columnstore import MAX_TS_INT

    st = TableStats(analyzed=True)
    total = 0
    parts: dict[str, list] = {c.name: [] for c in td.schema.columns}
    nulls: dict[str, int] = {c.name: 0 for c in td.schema.columns}
    for chunk in td.chunks:
        live = chunk.mvcc_del == MAX_TS_INT
        total += int(live.sum())
        for col in td.schema.columns:
            cn = col.name
            v = chunk.valid[cn][live]
            d = chunk.data[cn][live]
            nulls[cn] += int((~v).sum())
            parts[cn].append(d[v])
    st.row_count = total
    for cn, ps in parts.items():
        arr = np.concatenate(ps) if ps else np.zeros(0)
        st.distinct[cn] = int(len(np.unique(arr))) if arr.size else 0
        st.null_frac[cn] = nulls[cn] / total if total else 0.0
    return st


def _underlying_col(e):
    """Peel wrappers (dict remaps, casts) down to a column reference."""
    from .bound import BCol
    seen = 0
    while e is not None and not isinstance(e, BCol) and seen < 8:
        e = getattr(e, "expr", None)
        seen += 1
    return e if isinstance(e, BCol) else None


def _col_distinct(name: str, stats: TableStats | None):
    if stats is None:
        return None
    # bound columns are alias-qualified ("lineitem.l_returnflag");
    # stats key on stored names
    return (stats.distinct.get(name)
            or stats.distinct.get(name.split(".")[-1]))


def _pred_selectivity(e, stats: TableStats | None) -> float:
    """Selectivity of one bound predicate expression."""
    from .bound import BBin

    if isinstance(e, BBin):
        if e.op == "and":
            return (_pred_selectivity(e.left, stats)
                    * _pred_selectivity(e.right, stats))
        if e.op == "or":
            a = _pred_selectivity(e.left, stats)
            b = _pred_selectivity(e.right, stats)
            return min(1.0, a + b)
        if e.op == "=":
            col = _underlying_col(e.left) or _underlying_col(e.right)
            nd = _col_distinct(col.name, stats) if col is not None else None
            if nd:
                return 1.0 / nd
            return SEL_EQ
        if e.op in ("<", "<=", ">", ">="):
            return SEL_RANGE
    return SEL_OTHER


def scan_rows(node: P.Scan, stats_map: dict) -> float:
    st = stats_map.get(node.table)
    rows = float(st.row_count) if st else 1000.0
    if node.filter is not None:
        rows *= _pred_selectivity(node.filter, st)
    return max(rows, 1.0)


def estimate(node: P.PlanNode, stats_map: dict) -> dict:
    """Bottom-up (est_rows, est_cost) per plan node, keyed by id().

    Costs are abstract row-touch units: scan = rows, filter = input
    rows, hash join = probe + build (build pays a table-build
    surcharge), aggregate = input + groups, sort = n log n.
    """
    out: dict[int, tuple[float, float]] = {}

    def walk(n) -> tuple[float, float]:
        if isinstance(n, P.Scan):
            st = stats_map.get(n.table)
            raw = float(st.row_count) if st else 1000.0
            rows = scan_rows(n, stats_map)
            r = (rows, raw)
        elif isinstance(n, P.Filter):
            crows, ccost = walk(n.child)
            st = None
            rows = crows * _pred_selectivity(n.pred, st)
            r = (max(rows, 1.0), ccost + crows)
        elif isinstance(n, P.HashJoin):
            prows, pcost = walk(n.left)
            brows, bcost = walk(n.right)
            # PK-FK: each probe row matches <= 1 build row
            rows = prows if n.join_type in ("inner", "left",
                                            "semi") else prows * 0.5
            r = (max(rows, 1.0), pcost + bcost + prows + 2.0 * brows)
        elif isinstance(n, P.Aggregate):
            crows, ccost = walk(n.child)
            groups = (min(float(n.max_groups), crows) if n.max_groups
                      else min(crows, 1 << 17) * 0.1)
            r = (max(groups if n.group_by else 1.0, 1.0),
                 ccost + crows + groups)
        elif isinstance(n, P.Project):
            crows, ccost = walk(n.child)
            r = (crows, ccost + crows)
        elif isinstance(n, P.Sort):
            crows, ccost = walk(n.child)
            r = (crows, ccost + crows * max(np.log2(max(crows, 2.0)), 1.0))
        elif isinstance(n, P.Limit):
            crows, ccost = walk(n.child)
            rows = crows
            if n.limit is not None:
                rows = min(crows, float(n.limit))
            r = (rows, ccost + crows)
        else:
            r = (1.0, 1.0)
        out[id(n)] = r
        return r

    walk(node)
    return out
