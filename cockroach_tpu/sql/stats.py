"""Table statistics + a simple cost model for the planner.

The analogue of pkg/sql/stats (table statistics + histograms feeding
the optimizer's costing, opt/memo/statistics_builder.go). ANALYZE
<table> computes exact per-column distinct counts and null fractions
over the live rows (our tables are host-resident columns, so "exact"
is one np.unique per column — the reference samples because its data
lives behind the KV API). Row counts are always exact and free.

The cost model is deliberately small: cardinality estimates drive two
real decisions — hash-join build-side selection and the EXPLAIN cost
column — matching the round-2 goal (VERDICT #10), not the reference's
full memo/xform search (opt/xform/optimizer.go:239, later rounds).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from . import plan as P

# default selectivities when no stats apply (the reference's
# unknownFilterSelectivity-style constants, statistics_builder.go)
SEL_EQ = 0.1
SEL_RANGE = 1.0 / 3.0
SEL_OTHER = 0.5


@dataclass
class TableStats:
    row_count: int = 0
    distinct: dict = field(default_factory=dict)   # col -> n distinct
    null_frac: dict = field(default_factory=dict)  # col -> fraction
    analyzed: bool = False
    # where the numbers came from: "analyze" (exact, explicit pass),
    # "sketch" (seal-time HLL/zone summaries), "default" (row count
    # only). EXPLAIN ANALYZE prints this per scan; the optimizer
    # metrics classify plans by it.
    source: str = "default"
    # live rows when an ANALYZE computed these stats (-1 = not an
    # ANALYZE). The staleness check compares against the current
    # row_count so exact-but-wrong numbers stop winning forever.
    analyzed_rows: int = -1
    # sketch-derived per-chunk summaries (stored-column name ->
    # [(lo, hi, nulls, nvalid) per chunk] / [BlockedBloom|None per
    # chunk]): predicate selectivity sums per-chunk overlap fractions
    # instead of applying SEL_EQ/SEL_RANGE constants. Empty for
    # analyze/default stats.
    zones: dict = field(default_factory=dict)
    blooms: dict = field(default_factory=dict)


def analyze_columns(td) -> TableStats:
    """Exact stats over a table's live rows (ANALYZE)."""
    from ..storage.columnstore import MAX_TS_INT

    st = TableStats(analyzed=True, source="analyze")
    total = 0
    parts: dict[str, list] = {c.name: [] for c in td.schema.columns}
    nulls: dict[str, int] = {c.name: 0 for c in td.schema.columns}
    for chunk in td.chunks:
        live = chunk.mvcc_del == MAX_TS_INT
        total += int(live.sum())
        for col in td.schema.columns:
            cn = col.name
            v = chunk.valid[cn][live]
            d = chunk.data[cn][live]
            nulls[cn] += int((~v).sum())
            parts[cn].append(d[v])
    st.row_count = total
    st.analyzed_rows = total
    for cn, ps in parts.items():
        arr = np.concatenate(ps) if ps else np.zeros(0)
        st.distinct[cn] = int(len(np.unique(arr))) if arr.size else 0
        st.null_frac[cn] = nulls[cn] / total if total else 0.0
    return st


def sketch_table_stats(td) -> TableStats:
    """Planner stats derived from seal-time chunk summaries — no
    ANALYZE pass, no row scan. HLL distinct sketches union mergeably
    across the table's chunks (register max), zones supply null
    fractions and per-chunk bounds, blooms allow equality containment
    zero-out. Open (unsealed) rows contribute to row_count but not to
    the summaries, so a table with no sealed chunks yields an empty
    `distinct` map and the memo gate falls back to greedy ordering.

    Dictionary-coded string columns keep their distinct estimate
    (distinct codes == distinct strings — exactly what join costing
    needs) but drop zones/blooms: their chunk arrays hold int32 codes
    whose order is dictionary-insertion order, meaningless against a
    SQL-level comparison constant."""
    from ..storage.chunkstats import DistinctSketch

    st = TableStats(source="sketch")
    st.row_count = td.row_count
    dict_cols = {c.name for c in td.schema.columns
                 if c.type.uses_dictionary}
    sketches: dict[str, DistinctSketch] = {}
    for chunk in td.chunks:
        if not chunk.stats_ready():
            chunk.finalize_stats()
        cs = chunk._stats
        for col, sk in cs.distinct.items():
            agg = sketches.get(col)
            if agg is None:
                sketches[col] = agg = DistinctSketch()
            agg.merge(sk)
        for col, z in cs.zones.items():
            if col in dict_cols:
                continue
            st.zones.setdefault(col, []).append(z)
            st.blooms.setdefault(col, []).append(cs.blooms.get(col))
    for col, sk in sketches.items():
        st.distinct[col] = max(1, sk.estimate())
    for col, zs in st.zones.items():
        nulls = sum(z[2] for z in zs)
        total = nulls + sum(z[3] for z in zs)
        st.null_frac[col] = nulls / total if total else 0.0
    return st


def _underlying_col(e):
    """Peel wrappers (dict remaps, casts) down to a column reference."""
    from .bound import BCol
    seen = 0
    while e is not None and not isinstance(e, BCol) and seen < 8:
        e = getattr(e, "expr", None)
        seen += 1
    return e if isinstance(e, BCol) else None


def _col_distinct(name: str, stats: TableStats | None):
    if stats is None:
        return None
    # bound columns are alias-qualified ("lineitem.l_returnflag");
    # stats key on stored names
    return (stats.distinct.get(name)
            or stats.distinct.get(name.split(".")[-1]))


def _zone_key(name: str, stats: TableStats):
    """Resolve an alias-qualified bound column name to the stored-name
    key the sketch zones use, or None when no zones exist for it."""
    if name in stats.zones:
        return name
    short = name.split(".")[-1]
    return short if short in stats.zones else None


_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}


def _col_const(e):
    """(BCol, python constant, normalized op) for a col-vs-const
    comparison in either operand order, else None."""
    from .bound import BConst
    cl = _underlying_col(e.left)
    cr = _underlying_col(e.right)
    if cl is not None and isinstance(e.right, BConst):
        return cl, e.right.value, e.op
    if cr is not None and isinstance(e.left, BConst):
        return cr, e.left.value, _FLIP.get(e.op)
    return None


def _is_num(v) -> bool:
    return isinstance(v, (int, float, np.integer, np.floating)) \
        and not isinstance(v, bool)


def _zone_eq_sel(stats: TableStats, key: str, v) -> float | None:
    """Equality selectivity from per-chunk containment: chunks whose
    [lo, hi] excludes v — or whose bloom proves absence — contribute
    zero candidate rows; surviving chunks contribute their valid rows
    scaled by the per-value density 1/distinct."""
    zs = stats.zones.get(key)
    if not zs or not _is_num(v):
        return None
    blooms = stats.blooms.get(key) or [None] * len(zs)
    total = cand = 0
    probe = None
    for z, bl in zip(zs, blooms):
        lo, hi, nulls, nvalid = z
        total += nulls + nvalid
        if nvalid == 0:
            continue
        if lo is None:
            cand += nvalid            # unordered chunk: can't exclude
            continue
        if not (lo <= v <= hi):
            continue
        if bl is not None:
            if probe is None:
                probe = np.asarray([v]).astype(np.int64, copy=False) \
                    if float(v).is_integer() else None
            if probe is not None and not bl.might_contain(probe)[0]:
                continue
        cand += nvalid
    if total == 0:
        return None
    if cand == 0:
        # no chunk can contain v: half a row's worth, never exactly 0
        return 0.5 / total
    nd = stats.distinct.get(key)
    per_value = 1.0 / nd if nd else SEL_EQ
    return min(1.0, per_value) * cand / total


def _overlap_frac(lo, hi, a, b) -> float:
    """Fraction of a chunk's [lo, hi] value span falling inside the
    query interval [a, b], assuming uniform spread. Integer zones use
    inclusive +1 widths so single-value chunks behave."""
    if isinstance(lo, int) and isinstance(hi, int):
        width = hi - lo + 1
        inter = min(hi, b) - max(lo, a) + 1
    else:
        width = hi - lo
        inter = min(hi, b) - max(lo, a)
        if width <= 0.0:
            return 1.0 if a <= lo <= b else 0.0
    if width <= 0:
        return 1.0 if a <= lo <= b else 0.0
    return min(1.0, max(0.0, inter / width))


def _zone_interval_sel(stats: TableStats, key: str, a, b) -> float | None:
    """Selectivity of `a <= col <= b` (half-open ranges pass +/-inf)
    as the valid-row-weighted sum of per-chunk overlap fractions.
    NULL rows count in the denominator — they fail every comparison."""
    zs = stats.zones.get(key)
    if not zs:
        return None
    total = 0
    cand = 0.0
    for lo, hi, nulls, nvalid in zs:
        total += nulls + nvalid
        if nvalid == 0:
            continue
        if lo is None:
            cand += nvalid * SEL_RANGE
            continue
        cand += nvalid * _overlap_frac(lo, hi, a, b)
    if total == 0:
        return None
    return max(cand / total, 0.5 / total)


def _range_bounds(op: str, v):
    """The (a, b) closed interval a comparison op selects. Strict
    bounds nudge integers by one; float strictness is noise at
    estimate precision."""
    if op == "<":
        return -np.inf, (v - 1 if isinstance(v, (int, np.integer)) else v)
    if op == "<=":
        return -np.inf, v
    if op == ">":
        return (v + 1 if isinstance(v, (int, np.integer)) else v), np.inf
    if op == ">=":
        return v, np.inf
    return None


def _pred_selectivity(e, stats: TableStats | None) -> float:
    """Selectivity of one bound predicate expression.

    With sketch-derived stats (per-chunk zones + blooms) equality and
    range comparisons against constants estimate real surviving
    fractions; otherwise the reference-style constants apply."""
    from .bound import (BBetween, BBin, BDictLookup, BInList, BIsNull,
                        BUnary)

    if isinstance(e, BBin):
        if e.op == "and":
            return (_pred_selectivity(e.left, stats)
                    * _pred_selectivity(e.right, stats))
        if e.op == "or":
            a = _pred_selectivity(e.left, stats)
            b = _pred_selectivity(e.right, stats)
            return min(1.0, a + b)
        if e.op == "=":
            cc = _col_const(e)
            if cc is not None and stats is not None:
                key = _zone_key(cc[0].name, stats)
                if key is not None:
                    s = _zone_eq_sel(stats, key, cc[1])
                    if s is not None:
                        return s
            col = _underlying_col(e.left) or _underlying_col(e.right)
            nd = _col_distinct(col.name, stats) if col is not None else None
            if nd:
                return 1.0 / nd
            return SEL_EQ
        if e.op in ("<", "<=", ">", ">="):
            cc = _col_const(e)
            if cc is not None and cc[2] is not None and stats is not None \
                    and _is_num(cc[1]):
                key = _zone_key(cc[0].name, stats)
                if key is not None:
                    bounds = _range_bounds(cc[2], cc[1])
                    if bounds is not None:
                        s = _zone_interval_sel(stats, key, *bounds)
                        if s is not None:
                            return s
            return SEL_RANGE
    if isinstance(e, BBetween):
        from .bound import BConst
        col = _underlying_col(e.expr)
        if (col is not None and stats is not None
                and isinstance(e.lo, BConst) and isinstance(e.hi, BConst)
                and _is_num(e.lo.value) and _is_num(e.hi.value)):
            key = _zone_key(col.name, stats)
            if key is not None:
                s = _zone_interval_sel(stats, key, e.lo.value, e.hi.value)
                if s is not None:
                    return min(1.0, 1.0 - s) if e.negated else s
        return SEL_RANGE
    if isinstance(e, BInList):
        col = _underlying_col(e.expr)
        if col is not None and stats is not None:
            key = _zone_key(col.name, stats)
            if key is not None:
                sels = [_zone_eq_sel(stats, key, v) for v in e.values]
                if all(s is not None for s in sels):
                    s = min(1.0, sum(sels))
                    return min(1.0, 1.0 - s) if e.negated else s
        return min(1.0, SEL_EQ * max(len(e.values), 1))
    if isinstance(e, BIsNull):
        col = _underlying_col(e.expr)
        if col is not None and stats is not None:
            nf = stats.null_frac.get(col.name)
            if nf is None:
                nf = stats.null_frac.get(col.name.split(".")[-1])
            if nf is not None:
                return max(min(1.0 - nf if e.negated else nf, 1.0),
                           0.5 / max(stats.row_count, 1))
        return SEL_OTHER
    if isinstance(e, BDictLookup):
        # fraction of dictionary codes passing the precomputed
        # membership table — exact over values, approximate over rows
        try:
            tb = np.asarray(e.table, dtype=bool)
            if tb.size:
                return float(min(1.0, max(tb.mean(), 1e-4)))
        except Exception:
            pass
        return SEL_OTHER
    if isinstance(e, BUnary) and e.op == "not":
        return min(1.0, max(0.0, 1.0 - _pred_selectivity(e.operand,
                                                         stats)))
    return SEL_OTHER


def scan_rows(node: P.Scan, stats_map: dict) -> float:
    st = stats_map.get(node.table)
    rows = float(st.row_count) if st else 1000.0
    if node.filter is not None:
        rows *= _pred_selectivity(node.filter, st)
    return max(rows, 1.0)


def estimate(node: P.PlanNode, stats_map: dict) -> dict:
    """Bottom-up (est_rows, est_cost) per plan node, keyed by id().

    Costs are abstract row-touch units: scan = rows, filter = input
    rows, hash join = probe + build (build pays a table-build
    surcharge), aggregate = input + groups, sort = n log n.
    """
    out: dict[int, tuple[float, float]] = {}

    def walk(n) -> tuple[float, float]:
        if isinstance(n, P.Scan):
            st = stats_map.get(n.table)
            raw = float(st.row_count) if st else 1000.0
            rows = scan_rows(n, stats_map)
            r = (rows, raw)
        elif isinstance(n, P.Filter):
            crows, ccost = walk(n.child)
            st = None
            rows = crows * _pred_selectivity(n.pred, st)
            r = (max(rows, 1.0), ccost + crows)
        elif isinstance(n, P.HashJoin):
            prows, pcost = walk(n.left)
            brows, bcost = walk(n.right)
            # PK-FK: each probe row matches <= 1 build row
            rows = prows if n.join_type in ("inner", "left",
                                            "semi") else prows * 0.5
            r = (max(rows, 1.0), pcost + bcost + prows + 2.0 * brows)
        elif isinstance(n, P.Aggregate):
            crows, ccost = walk(n.child)
            groups = (min(float(n.max_groups), crows) if n.max_groups
                      else min(crows, 1 << 17) * 0.1)
            r = (max(groups if n.group_by else 1.0, 1.0),
                 ccost + crows + groups)
        elif isinstance(n, P.Project):
            crows, ccost = walk(n.child)
            r = (crows, ccost + crows)
        elif isinstance(n, P.Sort):
            crows, ccost = walk(n.child)
            r = (crows, ccost + crows * max(np.log2(max(crows, 2.0)), 1.0))
        elif isinstance(n, P.Limit):
            crows, ccost = walk(n.child)
            rows = crows
            if n.limit is not None:
                rows = min(crows, float(n.limit))
            r = (rows, ccost + crows)
        else:
            r = (1.0, 1.0)
        out[id(n)] = r
        return r

    walk(node)
    return out
