"""Builtin scalar function library.

The analogue of pkg/sql/sem/builtins (~600 functions in the reference).
Functions split by execution strategy, each chosen for the TPU:

- **Elementwise numeric/date** (sin, pow, date_trunc, ...): bind to a
  BFunc/BUnary node whose kernel is a jnp elementwise op —- XLA fuses
  it into the surrounding scan, so a builtin costs nothing extra.
- **String functions over dictionary-encoded columns** (upper, length,
  substr, ...): evaluated ONCE against the column's dictionary on the
  host at bind time, producing a value table; on device the function is
  a single gather (BDictGather). upper() over 600M rows costs O(|dict|)
  host work + one gather — the dictionary-encoding dividend.
- **Constant folding**: any builtin over constants folds at bind time
  (the reference's normalization rules, opt/norm).

Registered entries are consulted by Binder.bind_func (binder.py).
"""

from __future__ import annotations

import datetime
import math
import re

import numpy as np

from .bound import BCase, BConst, BDictGather, BExpr, BFunc, BUnary
from .types import (BOOL, DATE, FLOAT8, INT8, STRING, TIMESTAMP, Family,
                    SQLType)


class BuiltinError(Exception):
    pass




# no-arg informational builtins: name -> (value, type). Session
# identity stays static (single-tenant engine); the point is driver/
# ORM compatibility (pg_catalog-adjacent probes).
_INFO_FNS = {
    "current_database": ("defaultdb", STRING),
    "current_schema": ("public", STRING),
    "current_user": ("root", STRING),
    "session_user": ("root", STRING),
    "pg_backend_pid": (0, INT8),
    "pg_is_in_recovery": (False, BOOL),
    "txid_current": (0, INT8),
    "inet_server_port": (26257, INT8),
}


# 1-arg float elementwise builtins: name -> python fn (for constant
# folding); the device kernel table lives in exec/expr.py:_FUNC_KERNELS
FLOAT_UNARY = {
    "sqrt": math.sqrt, "ln": math.log, "exp": math.exp,
    "log10": math.log10, "log2": math.log2,
    "cbrt": lambda x: math.copysign(abs(x) ** (1 / 3), x),
    "sin": math.sin, "cos": math.cos, "tan": math.tan,
    "cot": lambda x: 1.0 / math.tan(x),
    "asin": math.asin, "acos": math.acos, "atan": math.atan,
    "sinh": math.sinh, "cosh": math.cosh, "tanh": math.tanh,
    "asinh": math.asinh, "acosh": math.acosh, "atanh": math.atanh,
    "degrees": math.degrees, "radians": math.radians,
    "floor": math.floor, "ceil": math.ceil, "ceiling": math.ceil,
    "erf": math.erf, "erfc": math.erfc,
    # pg's degree-argument trigonometry family
    "sind": lambda x: math.sin(math.radians(x)),
    "cosd": lambda x: math.cos(math.radians(x)),
    "tand": lambda x: math.tan(math.radians(x)),
    "cotd": lambda x: 1.0 / math.tan(math.radians(x)),
    "asind": lambda x: math.degrees(math.asin(x)),
    "acosd": lambda x: math.degrees(math.acos(x)),
    "atand": lambda x: math.degrees(math.atan(x)),
}

# integer constant-fold-only builtins (no row-wise device kernel;
# these appear in expressions over literals, pg's immutable int fns):
# name -> (arity, fn)
INT_FOLD = {
    "factorial": (1, lambda n: math.factorial(int(n))),
    "gcd": (2, lambda a, b: math.gcd(int(a), int(b))),
    "lcm": (2, lambda a, b: math.lcm(int(a), int(b))),
}

# 2-arg float elementwise
FLOAT_BINARY = {
    "pow": math.pow, "power": math.pow, "atan2": math.atan2,
}


def _fold(name, args, pyfn, ty):
    """Constant-fold when every argument is a constant."""
    if all(isinstance(a, BConst) for a in args):
        vals = [a.value for a in args]
        if any(v is None for v in vals):
            return BConst(None, ty)
        try:
            return BConst(pyfn(*vals), ty)
        except (ValueError, OverflowError, ZeroDivisionError):
            return BConst(None, ty)
    return None


def bind_builtin(binder, name: str, args: list, e) -> BExpr | None:
    """Resolve a builtin call; returns None if unknown (caller errors).
    ``binder`` provides coerce() and dictionary resolution; ``e`` is the
    original ast.FuncCall (for string-literal args)."""
    if name in _DATUM_FNS and args \
            and args[0].type.family in (Family.ARRAY, Family.JSON):
        return _datum_builtin(binder, name, args)
    if name in FLOAT_UNARY:
        if len(args) != 1:
            raise BuiltinError(f"{name} takes one argument")
        x = binder.coerce(args[0], FLOAT8)
        return _fold(name, [x], FLOAT_UNARY[name], FLOAT8) \
            or BFunc(name, [x], FLOAT8)
    if name in FLOAT_BINARY:
        if len(args) != 2:
            raise BuiltinError(f"{name} takes two arguments")
        xs = [binder.coerce(a, FLOAT8) for a in args]
        return _fold(name, xs, FLOAT_BINARY[name], FLOAT8) \
            or BFunc(name, xs, FLOAT8)
    if name in INT_FOLD:
        arity, fn = INT_FOLD[name]
        if len(args) != arity:
            raise BuiltinError(
                f"{name} takes {arity} argument"
                + ("s" if arity != 1 else ""))
        out = _fold(name, args, fn, INT8)
        if out is None:
            raise BuiltinError(
                f"{name} over columns not supported (constants only)")
        return out
    if name in ("round", "trunc") and len(args) == 2:
        x = binder.coerce(args[0], FLOAT8)
        nd = args[1]
        if not isinstance(nd, BConst):
            raise BuiltinError(f"{name} digit count must be constant")
        return BFunc(name + "_n", [x, BConst(int(nd.value), INT8)], FLOAT8)
    if name == "trunc" and len(args) == 1:
        x = binder.coerce(args[0], FLOAT8)
        return _fold(name, [x], math.trunc, FLOAT8) \
            or BFunc("trunc", [x], FLOAT8)
    if name == "sign":
        x = binder.coerce(args[0], FLOAT8)
        return _fold(name, [x], lambda v: float(np.sign(v)), FLOAT8) \
            or BFunc("sign", [x], FLOAT8)
    if name == "mod":
        if len(args) != 2:
            raise BuiltinError("mod takes two arguments")
        from .binder import Binder  # for _align2 typing only
        l, r, ty = binder._align2(args[0], args[1])
        return BFunc("mod", [l, r], ty)
    if name == "div":
        xs = [binder.coerce(a, FLOAT8) for a in args]
        return BFunc("div", xs, FLOAT8)
    if name in ("greatest", "least"):
        if not args:
            raise BuiltinError(f"{name} needs arguments")
        ty = args[0].type
        for a in args[1:]:
            _, _, ty = binder._align2(BConst(None, ty), a)
        xs = [binder.coerce(a, ty) for a in args]
        return BFunc(name, xs, ty)
    if name == "nullif":
        if len(args) != 2:
            raise BuiltinError("nullif takes two arguments")
        l, r, _ = binder._align2(args[0], args[1])
        return BFunc("nullif", [l, r], l.type)
    if name == "pi":
        return BConst(math.pi, FLOAT8)
    if name == "log":
        # pg: log(x) = base-10; log(b, x) = arbitrary base
        xs = [binder.coerce(a, FLOAT8) for a in args]
        if len(xs) == 1:
            return _fold("log", xs, math.log10, FLOAT8) \
                or BFunc("log10", xs, FLOAT8)
        if len(xs) == 2:
            return _fold("log", xs,
                         lambda b, x: math.log(x) / math.log(b),
                         FLOAT8) or BFunc("logb", xs, FLOAT8)
        raise BuiltinError("log(x) or log(base, x)")
    if name == "random":
        # volatile; folded per bind like the sequence builtins (NB:
        # one value per statement, not per row — the device kernels
        # have no RNG key plumbing yet)
        import random as _random
        return BConst(_random.random(), FLOAT8)
    if name == "gen_random_uuid":
        import uuid as _uuid
        return BConst(str(_uuid.uuid4()), STRING)
    if name == "version":
        from .. import __version__
        return BConst(f"cockroach-tpu {__version__}", STRING)
    if name == "chr":
        x = binder.coerce(args[0], INT8)
        out = _fold("chr", [x], lambda v: chr(int(v)), STRING)
        if out is None:
            raise BuiltinError("chr over columns not supported "
                               "(constant only)")
        return out
    if name == "to_hex":
        x = binder.coerce(args[0], INT8)
        # negatives render as 64-bit two's complement, like pg
        out = _fold("to_hex", [x],
                    lambda v: format(int(v) & 0xFFFFFFFFFFFFFFFF, "x"),
                    STRING)
        if out is None:
            raise BuiltinError("to_hex over columns not supported "
                               "(constant only)")
        return out
    if name == "format":
        if not args or not isinstance(args[0], BConst):
            raise BuiltinError("format needs a constant template")
        if not all(isinstance(a, BConst) for a in args):
            raise BuiltinError("format over columns not supported "
                               "(constants only)")
        if args[0].value is None:
            return BConst(None, STRING)  # NULL template -> NULL (pg)
        tmpl = str(args[0].value)
        vals = []
        for a in args[1:]:
            v = a.value
            if v is not None and a.type.family == Family.DECIMAL:
                v = v / 10 ** a.type.scale
            vals.append(v)
        # pg format(): %s plain, %I quoted identifier, %L quoted
        # literal (NULL -> the keyword), %% literal percent
        out = []
        i = 0
        vi = 0
        n = len(tmpl)
        while i < n:
            ch = tmpl[i]
            if ch != "%":
                out.append(ch)
                i += 1
                continue
            spec = tmpl[i + 1:i + 2]
            i += 2
            if spec == "%":
                out.append("%")
                continue
            if spec not in ("s", "I", "L"):
                raise BuiltinError(
                    f"unrecognized format() type specifier "
                    f"%{spec or ''}")
            if vi >= len(vals):
                raise BuiltinError("too few arguments for format()")
            v = vals[vi]
            vi += 1
            if spec == "s":
                out.append("" if v is None else str(v))
            elif spec == "I":
                if v is None:
                    raise BuiltinError(
                        "format: NULL cannot be a %I identifier")
                out.append('"' + str(v).replace('"', '""') + '"')
            else:
                out.append("NULL" if v is None
                           else "'" + str(v).replace("'", "''")
                           + "'")
        return BConst("".join(out), STRING)
    if name == "isnan":
        x = binder.coerce(args[0], FLOAT8)
        return BFunc("isnan", [x], BOOL)
    if name == "width_bucket":
        if len(args) != 4:
            raise BuiltinError("width_bucket(x, lo, hi, n)")
        xs = [binder.coerce(a, FLOAT8) for a in args[:3]]
        n = args[3]
        if not isinstance(n, BConst):
            raise BuiltinError("width_bucket count must be constant")
        return BFunc("width_bucket", xs + [BConst(int(n.value), INT8)], INT8)

    # ---- date/time --------------------------------------------------------
    if name in ("now", "current_timestamp", "localtimestamp",
                "transaction_timestamp", "statement_timestamp",
                "clock_timestamp"):
        # every statement-timestamp variant folds to the statement's
        # HLC moment (timestamptz is future work, so local == utc)
        us = binder.now_micros
        if us is None:
            raise BuiltinError(f"{name}() needs a statement timestamp")
        return BConst(int(us), TIMESTAMP)
    if name == "current_date":
        us = binder.now_micros
        if us is None:
            raise BuiltinError("current_date needs a statement timestamp")
        return BConst(int(us // 86_400_000_000), DATE)
    if name == "to_timestamp":
        x = binder.coerce(args[0], FLOAT8)
        out = _fold(name, [x], lambda v: int(v * 1_000_000), TIMESTAMP)
        if out is None:
            raise BuiltinError(
                "to_timestamp over columns not supported "
                "(constants only)")
        return out
    if name == "make_timestamp":
        xs = [binder.coerce(a, FLOAT8) for a in args]
        if len(xs) != 6 or not all(isinstance(a, BConst) for a in xs):
            raise BuiltinError(
                "make_timestamp(y, mon, d, h, min, sec) constants")
        if any(a.value is None for a in xs):
            return BConst(None, TIMESTAMP)  # strict: NULL arg -> NULL
        y, mo, d, h, mi, s = (a.value for a in xs)
        try:
            dt = datetime.datetime(int(y), int(mo), int(d), int(h),
                                   int(mi)) \
                - datetime.datetime(1970, 1, 1)
        except (ValueError, OverflowError) as exc:
            raise BuiltinError(f"make_timestamp: {exc}") from None
        return BConst(int(dt.total_seconds() * 1_000_000
                          + s * 1_000_000), TIMESTAMP)
    if name == "isfinite":
        if not args:
            raise BuiltinError("isfinite takes one argument")
        x = args[0]
        if isinstance(x, BConst):
            # strict: NULL in -> NULL out (pg)
            return BConst(None if x.value is None else True, BOOL)
        # all STORED dates/timestamps are finite; NULL rows stay NULL
        from .bound import BIsNull
        return BCase(whens=[(BIsNull(x), BConst(None, BOOL))],
                     else_=BConst(True, BOOL), type=BOOL)
    if name == "date_trunc":
        if len(args) != 2 or not isinstance(args[0], BConst):
            raise BuiltinError("date_trunc('part', expr)")
        part = str(args[0].value).lower()
        x = args[1]
        if x.type.family not in (Family.DATE, Family.TIMESTAMP):
            raise BuiltinError("date_trunc needs date/timestamp")
        if part not in ("year", "quarter", "month", "week", "day",
                        "hour", "minute", "second"):
            raise BuiltinError(f"bad date_trunc field {part!r}")
        if x.type.family == Family.DATE and part in (
                "hour", "minute", "second", "day"):
            return x  # trunc below day granularity is identity on DATE
        kind = "ts" if x.type.family == Family.TIMESTAMP else "date"
        return BFunc(f"date_trunc_{kind}",
                     [BConst(part, STRING), x], x.type)
    if name in ("extract", "date_part"):
        # EXTRACT has dedicated syntax, but date_part('year', x) arrives
        # here as a plain call
        if len(args) != 2 or not isinstance(args[0], BConst):
            raise BuiltinError("date_part('part', expr)")
        from .bound import BExtract
        return BExtract(str(args[0].value).lower(), args[1], INT8)
    if name == "make_date":
        xs = [binder.coerce(a, INT8) for a in args]
        if all(isinstance(a, BConst) for a in xs):
            y, m, d = (int(a.value) for a in xs)
            return BConst(
                (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days,
                DATE)
        raise BuiltinError("make_date requires constants")
    if name == "age":
        if len(args) == 2:
            from .bound import BBin
            from .types import INTERVAL

            def _to_ts(a):
                if a.type.family == Family.TIMESTAMP:
                    return a
                if a.type.family == Family.DATE:
                    # days -> micros (both are epoch-relative ints)
                    return BBin("*", a,
                                BConst(86_400_000_000, INT8),
                                TIMESTAMP)
                if isinstance(a, BConst) and isinstance(a.value, str):
                    from .binder import parse_timestamp
                    return BConst(parse_timestamp(a.value), TIMESTAMP)
                return None
            l, r = _to_ts(args[0]), _to_ts(args[1])
            if l is not None and r is not None:
                return BBin("-", l, r, INTERVAL)
        raise BuiltinError("age(timestamp, timestamp)")
    if name == "to_char":
        # to_char(date|timestamp, 'pattern') over constants or a
        # dictionary-free context: pattern subset YYYY MM DD HH24 MI SS
        if len(args) != 2 or not isinstance(args[1], BConst):
            raise BuiltinError("to_char(expr, 'pattern')")
        x, pat = args[0], str(args[1].value)
        if not isinstance(x, BConst):
            raise BuiltinError("to_char over columns not supported "
                               "(constant only)")
        if x.value is None:
            return BConst(None, STRING)
        if x.type.family == Family.DATE:
            dt = datetime.date(1970, 1, 1) + \
                datetime.timedelta(days=int(x.value))
        elif x.type.family == Family.TIMESTAMP:
            dt = datetime.datetime(1970, 1, 1) + \
                datetime.timedelta(microseconds=int(x.value))
        else:
            raise BuiltinError("to_char needs a date/timestamp")
        fmt = (pat.replace("YYYY", "%Y").replace("MM", "%m")
               .replace("DD", "%d").replace("HH24", "%H")
               .replace("MI", "%M").replace("SS", "%S"))
        return BConst(dt.strftime(fmt), STRING)

    if name in _INFO_FNS:
        if args:
            raise BuiltinError(f"{name} takes no arguments")
        v, ty = _INFO_FNS[name]
        return BConst(v, ty)
    if name in ("justify_hours", "justify_days",
                "justify_interval"):
        # intervals are stored as total microseconds, so pg's
        # days/months re-bucketing is an output-formatting identity
        # here — the VALUE is unchanged by construction
        if len(args) != 1:
            raise BuiltinError(f"{name} takes one argument")
        return args[0]
    if name == "timeofday":
        us = binder.now_micros
        if us is None:
            raise BuiltinError("timeofday() needs a statement "
                               "timestamp")
        dt = datetime.datetime(1970, 1, 1) + \
            datetime.timedelta(microseconds=int(us))
        return BConst(dt.strftime("%a %b %d %H:%M:%S.%f")
                      + f" {dt.year} UTC", STRING)
    if name == "pg_typeof":
        if len(args) != 1:
            raise BuiltinError("pg_typeof takes one argument")
        return BConst(str(args[0].type).lower(), STRING)
    if name in ("obj_description", "col_description",
                "shobj_description"):
        return BConst(None, STRING)   # no comments stored
    if name == "pg_get_userbyid":
        return BConst("root", STRING)
    if name in ("has_table_privilege", "has_schema_privilege",
                "has_database_privilege", "pg_table_is_visible",
                "pg_function_is_visible"):
        return BConst(True, BOOL)     # single-role engine
    if name == "pg_encoding_to_char":
        return BConst("UTF8", STRING)
    if name == "uuid_generate_v4":
        return bind_builtin(binder, "gen_random_uuid", args, e)
    if name == "date_bin":
        # date_bin(stride, ts, origin): origin-aligned truncation —
        # pure int64 micros arithmetic, so it runs over COLUMNS and
        # fuses on device
        if len(args) != 3:
            raise BuiltinError("date_bin(stride, ts, origin)")
        from .bound import BBin
        stride, ts, origin = args
        if not isinstance(stride, BConst):
            raise BuiltinError("date_bin stride must be constant")
        sv = int(stride.value)
        if sv <= 0:
            raise BuiltinError("date_bin stride must be positive")
        if not isinstance(origin, BConst):
            raise BuiltinError("date_bin origin must be constant")
        ov = int(origin.value)
        # origin + ((ts - origin) / stride) * stride, integer division
        delta = BBin("-", ts, BConst(ov, TIMESTAMP), INT8)
        q = BFunc("div", [delta, BConst(sv, INT8)], INT8)
        return BBin("+", BConst(ov, TIMESTAMP),
                    BBin("*", q, BConst(sv, INT8), INT8), TIMESTAMP)

    # ---- strings over dictionaries ---------------------------------------
    out = _bind_string_builtin(binder, name, args)
    if out is not None:
        return out
    return None


# string -> string builtins: name -> fn(str, *const_args) -> str
_STR_TO_STR = {
    "upper": lambda s: s.upper(),
    "lower": lambda s: s.lower(),
    "initcap": lambda s: s.title(),
    "reverse": lambda s: s[::-1],
    "btrim": lambda s, chars=None: s.strip(chars),
    "trim": lambda s, chars=None: s.strip(chars),
    "ltrim": lambda s, chars=None: s.lstrip(chars),
    "rtrim": lambda s, chars=None: s.rstrip(chars),
    "replace": lambda s, a, b: s.replace(a, b),
    "translate": lambda s, frm, to: s.translate(
        str.maketrans(frm[:len(to)], to[:len(frm)], frm[len(to):])),
    "left": lambda s, n: s[:n] if n >= 0 else s[:len(s) + n],
    "right": lambda s, n: (s[-n:] if n > 0 else s[-n - len(s):]
                           if n < 0 else ""),
    "repeat": lambda s, n: s * max(n, 0),
    "lpad": lambda s, n, fill=" ": _pad(s, n, fill, left=True),
    "rpad": lambda s, n, fill=" ": _pad(s, n, fill, left=False),
    "substr": lambda s, start, length=None: _substr(s, start, length),
    "substring": lambda s, start, length=None: _substr(s, start, length),
    "split_part": lambda s, d, n: _split_part(s, d, n),
    "overlay": lambda s, repl, start, ln=None: (
        s[:start - 1] + repl
        + s[start - 1 + (len(repl) if ln is None else ln):]),
    "quote_ident": lambda s: '"' + s.replace('"', '""') + '"',
    "quote_literal": lambda s: "'" + s.replace("'", "''") + "'",
    "quote_nullable": lambda s: "'" + s.replace("'", "''") + "'",
    "encode": lambda s, fmt: _encode_blob(s, fmt),
    "decode": lambda s, fmt: _decode_blob(s, fmt),
    # pg regexp_replace: first match unless flags contain 'g'
    "regexp_replace": lambda s, pat, repl, flags="": re.sub(
        pat, repl, s,
        count=(0 if "g" in flags else 1),
        flags=(re.IGNORECASE if "i" in flags else 0)),
    "concat": None,     # variadic, handled specially
    "concat_ws": None,  # variadic, handled specially
    "md5": None,        # needs hashlib, handled specially
    "sha1": None,
    "sha256": None,
    "sha512": None,
}

# string -> scalar builtins: name -> (fn, SQLType)
_STR_TO_VAL = {
    "length": (len, INT8),
    "char_length": (len, INT8),
    "character_length": (len, INT8),
    "octet_length": (lambda s: len(s.encode()), INT8),
    "bit_length": (lambda s: len(s.encode()) * 8, INT8),
    "ascii": (lambda s: ord(s[0]) if s else 0, INT8),
    "strpos": (lambda s, sub: s.find(sub) + 1, INT8),
    "position": (lambda s, sub: s.find(sub) + 1, INT8),
    "starts_with": (lambda s, p: s.startswith(p), BOOL),
    "ends_with": (lambda s, p: s.endswith(p), BOOL),
    # CRDB string hash family (pkg/sql/sem/builtins: fnv/crc over the
    # value bytes) + fuzzystrmatch's levenshtein
    "fnv32": (lambda s: _fnv(s.encode(), 0x811c9dc5,
                             0x01000193, 1 << 32), INT8),
    "fnv32a": (lambda s: _fnva(s.encode(), 0x811c9dc5,
                               0x01000193, 1 << 32), INT8),
    "fnv64": (lambda s: _fnv(s.encode(), 0xcbf29ce484222325,
                             0x100000001b3, 1 << 64), INT8),
    "fnv64a": (lambda s: _fnva(s.encode(), 0xcbf29ce484222325,
                               0x100000001b3, 1 << 64), INT8),
    "crc32ieee": (lambda s: __import__("binascii").crc32(s.encode()),
                  INT8),
    "levenshtein": (lambda s, t: _levenshtein(s, t), INT8),
    "to_date": (lambda s, fmt: _to_date_days(s, fmt), DATE),
    # pg 15 regexp family (pattern/flags must be constants; the
    # predicate evaluates once per dictionary entry, sql/binder.py)
    "regexp_like": (lambda s, pat, flags="": bool(re.search(
        pat, s, re.IGNORECASE if "i" in flags else 0)), BOOL),
    "regexp_count": (lambda s, pat, flags="": len(re.findall(
        pat, s, re.IGNORECASE if "i" in flags else 0)), INT8),
    "regexp_instr": (lambda s, pat, flags="": (
        (lambda m: m.start() + 1 if m else 0)(re.search(
            pat, s, re.IGNORECASE if "i" in flags else 0))), INT8),
}


def _intersperse(args: list, sep) -> list:
    out = []
    for i, a in enumerate(args):
        if i:
            out.append(sep)
        out.append(a)
    return out


def _pad(s, n, fill, left):
    if n <= len(s):
        return s[:n]
    pad = (fill * n)[: n - len(s)]
    return pad + s if left else s + pad


def _split_part(s: str, delim, n):
    if delim is None or n is None:
        return None  # NULL in, NULL out (str.split(None) would
        # silently mean whitespace-split)
    n = int(n)
    if n < 1:
        raise BuiltinError("split_part field must be >= 1")
    parts = s.split(delim)
    return parts[n - 1] if n <= len(parts) else ""


def _substr(s, start, length=None):
    # SQL substring: 1-based; nonpositive start eats into length
    i = start - 1
    if length is None:
        return s[max(i, 0):]
    end = i + length
    return s[max(i, 0):max(end, 0)]


_HASH_FNS = ("md5", "sha1", "sha224", "sha256", "sha384", "sha512")


def _encode_blob(s: str, fmt: str) -> str:
    import base64 as _b64
    if fmt == "hex":
        return s.encode().hex()
    if fmt == "base64":
        return _b64.b64encode(s.encode()).decode()
    if fmt == "escape":
        return "".join(c if 32 <= ord(c) < 127 and c != "\\"
                       else f"\\{ord(c):03o}" for c in s)
    raise BuiltinError(f"unknown encode format {fmt!r}")


def _decode_blob(s: str, fmt: str) -> str:
    import base64 as _b64
    try:
        if fmt == "hex":
            return bytes.fromhex(s).decode()
        if fmt == "base64":
            return _b64.b64decode(s).decode()
    except (ValueError, UnicodeDecodeError) as exc:
        raise BuiltinError(f"decode: {exc}") from None
    raise BuiltinError(f"unknown decode format {fmt!r}")


def _fnv(data: bytes, basis: int, prime: int, mod: int) -> int:
    h = basis
    for b in data:
        h = (h * prime) % mod
        h ^= b
    return h if h < (1 << 63) else h - (1 << 64)


def _fnva(data: bytes, basis: int, prime: int, mod: int) -> int:
    h = basis
    for b in data:
        h ^= b
        h = (h * prime) % mod
    return h if h < (1 << 63) else h - (1 << 64)


def _levenshtein(s: str, t: str) -> int:
    if len(s) < len(t):
        s, t = t, s
    prev = list(range(len(t) + 1))
    for i, cs in enumerate(s, 1):
        cur = [i]
        for j, ct in enumerate(t, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                           prev[j - 1] + (cs != ct)))
        prev = cur
    return prev[-1]


def _to_date_days(s: str, fmt: str) -> int:
    pat = (fmt.replace("YYYY", "%Y").replace("MM", "%m")
           .replace("DD", "%d"))
    try:
        d = datetime.datetime.strptime(s.strip(), pat).date()
    except ValueError as exc:
        raise BuiltinError(f"to_date: {exc}") from None
    return (d - datetime.date(1970, 1, 1)).days


def _bind_string_builtin(binder, name: str, args: list) -> BExpr | None:
    import hashlib
    if name in _HASH_FNS:
        h = getattr(hashlib, name)
        fn = lambda s: h(s.encode()).hexdigest()  # noqa: E731
        return _dict_transform(binder, name, args[0], fn)
    if name == "concat_ws":
        if len(args) < 2 or not isinstance(args[0], BConst):
            raise BuiltinError(
                "concat_ws needs a constant separator first")
        sep = args[0].value
        if sep is None:
            return BConst(None, STRING)
        # pg: NULL arguments are skipped TOGETHER with their
        # separator (constant NULLs here; a NULL column VALUE still
        # nulls the row, a known narrowing of pg's per-row skip)
        live = [a for a in args[1:]
                if not (isinstance(a, BConst) and a.value is None)]
        if not live:
            return BConst("", STRING)
        return _bind_string_builtin(binder, "concat", _intersperse(
            live, BConst(str(sep), STRING)))
    if name == "concat":
        # variadic; exactly one dictionary column allowed, rest constants
        col_i = None
        parts = []
        for i, a in enumerate(args):
            if isinstance(a, BConst):
                parts.append("" if a.value is None else str(a.value))
            elif a.type.family == Family.STRING and col_i is None:
                col_i = i
                parts.append(None)
            else:
                raise BuiltinError(
                    "concat supports one string column + constants")
        if col_i is None:
            return BConst("".join(parts), STRING)
        pre = "".join(p for p in parts[:col_i] if p is not None)
        post = "".join(p for p in parts[col_i + 1:] if p is not None)
        return _dict_transform(binder, name, args[col_i],
                               lambda s: pre + s + post)
    if name in _STR_TO_STR:
        if not args:
            raise BuiltinError(f"{name} needs arguments")
        x, consts = args[0], args[1:]
        cvals = []
        for c in consts:
            if not isinstance(c, BConst):
                raise BuiltinError(
                    f"{name}: non-leading arguments must be constants")
            cvals.append(c.value)
        if any(v is None for v in cvals):
            return BConst(None, STRING)  # strict: NULL arg -> NULL
        fn = _STR_TO_STR[name]
        return _dict_transform(binder, name, x,
                               lambda s: fn(s, *cvals))
    if name in _STR_TO_VAL:
        fn, ty = _STR_TO_VAL[name]
        x, consts = args[0], args[1:]
        cvals = []
        for c in consts:
            if not isinstance(c, BConst):
                raise BuiltinError(
                    f"{name}: non-leading arguments must be constants")
            cvals.append(c.value)
        if any(v is None for v in cvals):
            return BConst(None, ty)  # strict: NULL arg -> NULL
        if isinstance(x, BConst):
            if x.value is None:
                return BConst(None, ty)
            return BConst(fn(str(x.value), *cvals), ty)
        d = binder._dict_of(x)
        if d is None:
            raise BuiltinError(f"{name} on non-dictionary column")
        vals = [fn(v, *cvals) for v in d.values]
        table = np.asarray(vals,
                           dtype=bool if ty is BOOL else np.int64)
        return BDictGather(x, table, ty)
    return None


def _dict_transform(binder, name, x, fn) -> BExpr:
    """string->string builtin: build an output dictionary by mapping the
    input dictionary through fn; the device op is a code remap gather."""
    from ..storage.columnstore import Dictionary
    if isinstance(x, BConst):
        if x.value is None:
            return BConst(None, STRING)
        try:
            return BConst(fn(str(x.value)), STRING)
        except re.error as exc:
            raise BuiltinError(f"{name}: invalid pattern: {exc}") \
                from None
    if x.type.family != Family.STRING:
        raise BuiltinError(f"{name} needs a string argument")
    d = binder._dict_of(x)
    if d is None:
        raise BuiltinError(f"{name} on non-dictionary column")
    out = Dictionary()
    try:
        codes = np.fromiter((out.encode(fn(v)) for v in d.values),
                            dtype=np.int64, count=len(d.values))
    except re.error as exc:
        # user-supplied malformed regexp (regexp_replace): a clean
        # bind error, not a traceback mid-dictionary-map
        raise BuiltinError(f"{name}: invalid pattern: {exc}") from None
    g = BDictGather(x, codes, STRING)
    g.dictionary = out
    return g


# -- datum builtins (ARRAY / JSONB) ---------------------------------------
# Same dictionary-LUT strategy as the string builtins above: the
# function runs once per DICTIONARY ENTRY on the host (values parsed
# from canonical text, sql/datum.py), and the device op is one typed
# gather. The reference evaluates these per row through tree.Datum
# (pkg/sql/sem/builtins/builtins.go json/array sections).

def _jsonb_typeof(v):
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, list):
        return "array"
    return "object"


def _array_position(v, needle):
    try:
        return v.index(needle) + 1
    except ValueError:
        return None


# name -> (fn(parsed, *const_args) -> value|None, result type, n_args,
#           required argument family) — array builtins bind ONLY on
# arrays and jsonb builtins only on jsonb, like pg's overload
# resolution; the wrong family is a bind error, not silent garbage
_DATUM_FNS = {
    "array_length": (lambda v, dim: len(v) if dim == 1 and v else None,
                     INT8, 2, Family.ARRAY),
    "cardinality": (lambda v: len(v), INT8, 1, Family.ARRAY),
    "array_position": (_array_position, INT8, 2, Family.ARRAY),
    "array_to_string": (
        lambda v, delim: delim.join(str(x) for x in v if x is not None),
        STRING, 2, Family.ARRAY),
    "jsonb_typeof": (_jsonb_typeof, STRING, 1, Family.JSON),
    "json_typeof": (_jsonb_typeof, STRING, 1, Family.JSON),
    "jsonb_array_length": (
        lambda v: len(v) if isinstance(v, list) else None, INT8, 1,
        Family.JSON),
    "jsonb_exists": (
        lambda v, key: (key in v if isinstance(v, dict)
                        else str(key) in [str(x) for x in v]
                        if isinstance(v, list) else False),
        BOOL, 2, Family.JSON),
}


def _datum_builtin(binder, name, args) -> BExpr:
    from . import datum as dtm
    from .bound import BDictRemap
    from ..storage.columnstore import Dictionary
    fn, ty, nargs, fam = _DATUM_FNS[name]
    if len(args) != nargs:
        raise BuiltinError(f"{name} takes {nargs} argument(s)")
    x, consts = args[0], args[1:]
    if x.type.family != fam:
        raise BuiltinError(
            f"{name} does not exist for argument type {x.type}")
    cvals = []
    for c in consts:
        if not isinstance(c, BConst):
            raise BuiltinError(
                f"{name}: non-leading arguments must be constants")
        if c.value is None:
            return BConst(None, ty)
        v = c.value
        if c.type.family in (Family.ARRAY, Family.JSON):
            v = dtm.decode_text(v, c.type)
        cvals.append(v)
    if name == "array_position" and x.type.family == Family.ARRAY \
            and x.type.elem.family == Family.DECIMAL:
        raise BuiltinError("array_position on decimal arrays unsupported")
    if isinstance(x, BConst):
        if x.value is None:
            return BConst(None, ty)
        return BConst(fn(dtm.decode_text(x.value, x.type), *cvals), ty)
    d = binder._dict_of(x)
    if d is None:
        raise BuiltinError(f"{name} on non-dictionary column")
    parsed = [dtm.decode_text(v, x.type) for v in d.values]
    results = [fn(pv, *cvals) for pv in parsed]
    nulls = np.fromiter((r is not None for r in results),
                        dtype=bool, count=len(results))
    if ty is STRING:
        out = Dictionary()
        table = np.fromiter(
            (out.encode(r) if r is not None else -1 for r in results),
            dtype=np.int32, count=len(results))
        g = BDictRemap(x, table, STRING, null_table=nulls)
        g.dictionary = out
        return g
    table = np.asarray([r if r is not None else 0 for r in results],
                       dtype=bool if ty is BOOL else np.int64)
    return BDictGather(x, table, ty, null_table=nulls)
