"""Semantic analysis: resolve names, assign types, lower to physical.

The binder (the analogue of optbuilder + sem/eval's type checking,
pkg/sql/opt/optbuilder/builder.go:184) turns parser AST into the bound
tree of bound.py. All host-only computation happens here so the
executor sees pure device-expressible operations:

- decimal literals/arithmetic are lowered to scaled-int64 ops with
  explicit rescales (scales tracked in SQLType);
- date/timestamp/interval literals are parsed and constant arithmetic
  on them is folded (calendar math never reaches the device);
- predicates over dictionary-encoded string columns become integer
  code comparisons, or code-set lookups for LIKE/ordered compares
  (BDictLookup: a precomputed bool table indexed by code — the binder
  evaluates the predicate against the dictionary once, so a LIKE over
  600M rows costs one gather on device).
"""

from __future__ import annotations

import datetime
import re
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from . import ast
from . import datum as dtm
from .bound import (BAggRef, BBetween, BBin, BCase, BCast, BCoalesce, BCol,
                    BConst, BDictGather, BDictLookup, BDictRemap, BExpr,
                    BExtract, BFunc, BInList, BIsNull, BoundAgg,
                    BoundWindow, BUnary, BWinRef)
from .types import (BOOL, DATE, FLOAT8, INT8, INTERVAL, STRING, TIMESTAMP,
                    Family, SQLType, common_numeric_type)

AGG_FUNCS = {"sum", "count", "min", "max", "avg"}

EPOCH = datetime.date(1970, 1, 1)


class BindError(Exception):
    pass


@dataclass
class ColumnBinding:
    batch_name: str
    type: SQLType
    dictionary: Optional[object] = None  # storage.columnstore.Dictionary


@dataclass
class Scope:
    """In-scope tables: alias -> {col -> ColumnBinding}."""
    tables: dict[str, dict[str, ColumnBinding]] = field(default_factory=dict)

    def add_table(self, alias: str, cols: dict[str, ColumnBinding]):
        if alias in self.tables:
            raise BindError(f"duplicate table alias {alias!r}")
        self.tables[alias] = cols

    def resolve(self, name: str, qualifier: Optional[str]) -> ColumnBinding:
        if qualifier is not None:
            t = self.tables.get(qualifier)
            if t is None:
                raise BindError(f"unknown table {qualifier!r}")
            b = t.get(name)
            if b is None:
                raise BindError(f"column {name!r} not in {qualifier!r}")
            return b
        hits = [t[name] for t in self.tables.values() if name in t]
        if not hits:
            raise BindError(f"unknown column {name!r}")
        if len(hits) > 1:
            raise BindError(f"ambiguous column {name!r}")
        return hits[0]

    def all_columns(self) -> list[ColumnBinding]:
        out = []
        for t in self.tables.values():
            out.extend(t.values())
        return out


# ---------------------------------------------------------------------------
# literal parsing
# ---------------------------------------------------------------------------

def parse_date(s: str) -> int:
    d = datetime.date.fromisoformat(s.strip())
    return (d - EPOCH).days


def parse_timestamp(s: str) -> int:
    s = s.strip()
    try:
        dt = datetime.datetime.fromisoformat(s)
    except ValueError as e:
        raise BindError(f"bad timestamp {s!r}") from e
    if dt.tzinfo is not None:
        dt = dt.astimezone(datetime.timezone.utc).replace(tzinfo=None)
    return int((dt - datetime.datetime(1970, 1, 1)).total_seconds() * 1e6)


# longer unit spellings must precede their prefixes in the alternation
# (regex | is first-match: "minute" before "minutes" would strand the s)
_INTERVAL_RE = re.compile(
    r"\s*(-?\d+)\s*(years|year|months|mons|month|mon|days|day|"
    r"hours|hour|minutes|mins|minute|min|seconds|secs|second|sec)\s*",
    re.I)


@dataclass
class Interval:
    months: int = 0
    days: int = 0
    micros: int = 0


def parse_interval(s: str) -> Interval:
    iv = Interval()
    pos = 0
    matched = False
    for m in _INTERVAL_RE.finditer(s):
        if m.start() != pos:
            break
        pos = m.end()
        matched = True
        qty = int(m.group(1))
        unit = m.group(2).lower()
        if unit.startswith("year"):
            iv.months += 12 * qty
        elif unit.startswith("mon"):
            iv.months += qty
        elif unit.startswith("day"):
            iv.days += qty
        elif unit.startswith("hour"):
            iv.micros += qty * 3_600_000_000
        elif unit.startswith("min"):
            iv.micros += qty * 60_000_000
        else:
            iv.micros += qty * 1_000_000
    if not matched or pos != len(s.rstrip()):
        raise BindError(f"bad interval {s!r}")
    return iv


def add_interval_to_date(days: int, iv: Interval, sign: int = 1) -> int:
    d = EPOCH + datetime.timedelta(days=days)
    if iv.months:
        total = d.year * 12 + (d.month - 1) + sign * iv.months
        y, m = divmod(total, 12)
        last = [31, 29 if _leap(y) else 28, 31, 30, 31, 30,
                31, 31, 30, 31, 30, 31][m]
        d = d.replace(year=y, month=m + 1, day=min(d.day, last))
    d += datetime.timedelta(days=sign * iv.days)
    return (d - EPOCH).days


def _leap(y: int) -> bool:
    return y % 4 == 0 and (y % 100 != 0 or y % 400 == 0)


# ---------------------------------------------------------------------------
# binder
# ---------------------------------------------------------------------------

class Binder:
    def __init__(self, scope: Scope, subquery_eval=None,
                 now_micros: Optional[int] = None,
                 sequence_ops=None, volatile_fold_ok: bool = True,
                 dict_folds: bool = True):
        self.scope = scope
        # dict_folds=False: a string literal absent from the column's
        # dictionary binds to an impossible code (-1) compare instead
        # of folding to a constant. Folding is dictionary-CONTENT
        # dependent, so plans bound on different shards diverge
        # structurally — the host-level shuffle (distsql/shuffle.py)
        # needs every node to derive an identical stage graph.
        self.dict_folds = dict_folds
        # populated by bind_with_aggs
        self.aggs: list[BoundAgg] = []
        self._collect_aggs = False
        # subquery_eval(ast.Select) -> (rows, types): executes a
        # subquery before the main statement (the reference plans and
        # runs planTop.subqueryPlans first, sql/subquery.go); None when
        # the caller cannot execute (pure-binder contexts)
        self.subquery_eval = subquery_eval
        # statement timestamp in unix micros for now()/current_date
        self.now_micros = now_micros
        # sequence_ops(fn, seq_name, arg) -> int: volatile sequence
        # builtins (nextval/currval/setval), folded to constants at
        # bind time; None when no engine is attached
        self.sequence_ops = sequence_ops
        # window function instances (bind_with_windows)
        self.windows: list[BoundWindow] = []
        self._collect_windows = False
        # volatile builtins (nextval/random/gen_random_uuid) fold to
        # ONE constant per bind; in a SELECT with a FROM clause pg
        # evaluates them per ROW, so folding silently corrupts results.
        # plan_select sets this False for executed SELECTs; DML WHERE /
        # EXPLAIN contexts keep the (documented) per-statement fold
        self.volatile_fold_ok = volatile_fold_ok

    # -- main dispatch -------------------------------------------------------
    def bind(self, e: ast.Expr) -> BExpr:
        if isinstance(e, ast.Literal):
            return self.bind_literal(e)
        if isinstance(e, ast.ColumnRef):
            b = self.scope.resolve(e.name, e.table)
            return BCol(b.batch_name, b.type)
        if isinstance(e, ast.BinOp):
            return self.bind_binop(e)
        if isinstance(e, ast.UnaryOp):
            o = self.bind(e.operand)
            if e.op == "not":
                if o.type.family == Family.UNKNOWN:
                    return BConst(None, BOOL)  # NOT NULL is NULL
                if o.type.family != Family.BOOL:
                    raise BindError("NOT requires boolean")
                return BUnary("not", o, BOOL)
            if isinstance(o, BConst) and o.value is not None:
                return BConst(-o.value, o.type)
            return BUnary("-", o, o.type)
        if isinstance(e, ast.Between):
            x = self.bind(e.expr)
            lo = self.coerce(self.bind(e.lo), x.type)
            hi = self.coerce(self.bind(e.hi), x.type)
            x, lo, hi = self._align3(x, lo, hi)
            return BBetween(x, lo, hi, e.negated, BOOL)
        if isinstance(e, ast.InList):
            return self.bind_in(e)
        if isinstance(e, ast.IsNull):
            return BIsNull(self.bind(e.expr), e.negated, BOOL)
        if isinstance(e, ast.Case):
            return self.bind_case(e)
        if isinstance(e, ast.Subscript):
            return self.bind_subscript(e)
        if isinstance(e, ast.ArrayLit):
            return self.bind_array_lit(e)
        if isinstance(e, ast.Cast):
            return self.bind_cast(self.bind(e.expr), e.to)
        if isinstance(e, ast.FuncCall):
            return self.bind_func(e)
        if isinstance(e, ast.WindowCall):
            return self.bind_window(e)
        if isinstance(e, ast.Extract):
            x = self.bind(e.expr)
            if x.type.family not in (Family.DATE, Family.TIMESTAMP):
                raise BindError("EXTRACT needs date/timestamp")
            return BExtract(e.part.lower(), x, INT8)
        if isinstance(e, ast.Substring):
            from . import builtins as bi
            args = [self.bind(e.expr), self.bind(e.start)]
            if e.length is not None:
                args.append(self.bind(e.length))
            for a in args[1:]:
                if not isinstance(a, BConst):
                    raise BindError("SUBSTRING bounds must be constants")
            try:
                out = bi.bind_builtin(self, "substr", args, None)
            except bi.BuiltinError as err:
                raise BindError(str(err)) from err
            if out is None:
                raise BindError("SUBSTRING binding failed")
            return out
        if isinstance(e, ast.Subquery):
            rows, types = self._run_subquery(e.select)
            if len(types) != 1:
                raise BindError("scalar subquery must return one column")
            if len(rows) > 1:
                raise BindError(
                    "more than one row returned by a subquery used as "
                    "an expression")
            val = rows[0][0] if rows else None
            return self._subquery_const(val, types[0])
        if isinstance(e, ast.Exists):
            rows, _ = self._run_subquery(e.select, limit_one=True)
            return BConst(bool(rows), BOOL)
        if isinstance(e, ast.InSubquery):
            rows, types = self._run_subquery(e.select)
            if len(types) != 1:
                raise BindError("IN subquery must return one column")
            items = [self._subquery_const(r[0], types[0]) for r in rows
                     if r[0] is not None]
            had_null = any(r[0] is None for r in rows)
            out = self._bind_in_consts(self.bind(e.expr), items,
                                       e.negated)
            if had_null:
                # three-valued IN: a NULL in the list means "maybe" —
                # x NOT IN (..., NULL) is never TRUE (false on match,
                # else NULL); x IN (..., NULL) is never FALSE. AND/OR
                # with NULL realizes exactly that truth table.
                out = BBin("and" if e.negated else "or",
                           out, BConst(None, BOOL), BOOL)
            return out
        raise BindError(f"cannot bind {e!r}")

    # -- subqueries ---------------------------------------------------------
    def _run_subquery(self, sel: ast.Select, limit_one: bool = False):
        if self.subquery_eval is None:
            raise BindError("subqueries not supported in this context")
        try:
            return self.subquery_eval(sel, limit_one)
        except BindError as e:
            # outer-column references fail name resolution in the
            # subquery's own scope: report it as what it is
            raise BindError(
                f"correlated subqueries not supported ({e})") from e

    def _subquery_const(self, val, ty: SQLType) -> BConst:
        """Re-encode a decoded subquery result value to physical form."""
        if val is None:
            return BConst(None, SQLType.unknown())
        f = ty.family
        if f == Family.DECIMAL:
            return BConst(int(round(float(val) * 10 ** ty.scale)), ty)
        if f == Family.DATE:
            return BConst((val - EPOCH).days
                          if isinstance(val, datetime.date) else int(val), ty)
        if f == Family.TIMESTAMP:
            if isinstance(val, datetime.datetime):
                us = int((val - datetime.datetime(1970, 1, 1))
                         .total_seconds() * 1e6)
                return BConst(us, ty)
            return BConst(int(val), ty)
        return BConst(val, ty)

    def _bind_in_consts(self, x: BExpr, items: list[BConst],
                        negated: bool) -> BExpr:
        """IN over pre-bound constant items (subquery results)."""
        if x.type.family == Family.STRING:
            d = self._dict_of(x)
            if d is None:
                raise BindError("IN on non-dictionary string column")
            vals = [d.codes[c.value] for c in items
                    if c.value in d.codes]
            if not vals:
                return BConst(negated, BOOL)
            return BInList(x, vals, negated, BOOL)
        vals = []
        target = x.type
        for c in items:
            if x.type.is_numeric:
                target = common_numeric_type(target, c.type)
        x2 = self.coerce(x, target) if x.type != target else x
        for c in items:
            vals.append(self.coerce(c, target).value)
        if not vals:
            return BConst(negated, BOOL)
        return BInList(x2, vals, negated, BOOL)

    def bind_literal(self, e: ast.Literal) -> BExpr:
        v, th = e.value, e.type_hint
        if v is None:
            return BConst(None, SQLType.unknown())
        if th is not None and th.family == Family.DATE:
            return BConst(parse_date(v), DATE)
        if th is not None and th.family == Family.TIMESTAMP:
            return BConst(parse_timestamp(v), TIMESTAMP)
        if th is not None and th.family == Family.INTERVAL:
            iv = parse_interval(v)
            c = BConst(iv, INTERVAL)
            return c
        if isinstance(v, bool):
            return BConst(v, BOOL)
        if isinstance(v, int):
            return BConst(v, INT8)
        if isinstance(v, str) and th is None:
            # number-looking strings come from decimal literals
            if re.fullmatch(r"-?\d*\.\d+([eE][-+]?\d+)?|-?\d+[eE][-+]?\d+", v):
                scale = len(v.split(".")[1].split("e")[0].split("E")[0]) \
                    if "." in v else 0
                if "e" in v.lower():
                    return BConst(float(v), FLOAT8)
                return BConst(int(round(float(v) * 10 ** scale)),
                              SQLType.decimal(scale=scale))
            return BConst(v, STRING)
        if isinstance(v, float):
            return BConst(v, FLOAT8)
        raise BindError(f"cannot type literal {v!r}")

    # -- coercion ------------------------------------------------------------
    def coerce(self, e: BExpr, target: SQLType) -> BExpr:
        """Coerce e toward target's family (constants fold)."""
        t = e.type
        if t.family == target.family:
            if t.family == Family.DECIMAL and t.scale != target.scale:
                return self._rescale_decimal(e, target.scale)
            return e
        if t.family == Family.UNKNOWN:
            e.type = target
            return e
        if isinstance(e, BConst):
            return self._const_to(e, target)
        if t.family == Family.INT and target.family == Family.DECIMAL:
            return BBin("*", e, BConst(10 ** target.scale, INT8), target)
        if t.family == Family.INT and target.family == Family.FLOAT:
            return BCast(e, FLOAT8)
        if t.family == Family.DECIMAL and target.family == Family.FLOAT:
            return BCast(e, FLOAT8)
        if t.family == Family.STRING and target.family == Family.DATE \
                and isinstance(e, BConst):
            return BConst(parse_date(e.value), DATE)
        if t.family == Family.DATE and target.family == Family.TIMESTAMP:
            # days -> micros: a date is midnight of that day
            return BBin("*", e, BConst(86_400_000_000, INT8), TIMESTAMP)
        raise BindError(f"cannot coerce {t} to {target}")

    def _const_to(self, e: BConst, target: SQLType) -> BConst:
        v = e.value
        f = target.family
        if v is None:
            return BConst(None, target)
        if f in (Family.JSON, Family.ARRAY):
            if e.type.family == f:
                # re-canonicalize (e.g. INT[] -> FLOAT[] not supported;
                # same family means text is already canonical)
                return BConst(v, target) if e.type == target else \
                    BConst(dtm.canon_text(str(v), target), target)
            if isinstance(v, str):
                try:
                    return BConst(dtm.canon_text(v, target), target)
                except dtm.DatumError as err:
                    raise BindError(str(err)) from None
            raise BindError(f"cannot convert constant {v!r} to {target}")
        if e.type.family in (Family.JSON, Family.ARRAY) \
                and f == Family.STRING:
            return BConst(str(v), STRING)
        if f == Family.DECIMAL:
            if e.type.family == Family.DECIMAL:
                return self._rescale_decimal(e, target.scale)
            return BConst(int(round(float(v) * 10 ** target.scale)), target)
        if f == Family.FLOAT:
            if e.type.family == Family.DECIMAL:
                return BConst(float(v) / 10 ** e.type.scale, FLOAT8)
            return BConst(float(v), FLOAT8)
        if f == Family.INT:
            if e.type.family == Family.DECIMAL:
                # v is the scaled physical value; cast rounds the logical
                # value half-away-from-zero (SQL semantics)
                logical = v / 10 ** e.type.scale
                return BConst(int(logical + (0.5 if logical >= 0 else -0.5)),
                              target)
            if isinstance(v, float):
                return BConst(round(v), target)  # half-even (pg float8)
            if isinstance(v, str):
                try:
                    return BConst(int(v.strip()), target)
                except ValueError:
                    raise BindError(
                        f"cannot convert constant {v!r} to {target}") \
                        from None
            return BConst(int(v), target)
        if f == Family.DATE and isinstance(v, str):
            return BConst(parse_date(v), DATE)
        if f == Family.TIMESTAMP and isinstance(v, str):
            return BConst(parse_timestamp(v), TIMESTAMP)
        if f in (Family.DATE, Family.TIMESTAMP) \
                and e.type.family == f and isinstance(v, int):
            return BConst(v, target)  # already physical (days / micros)
        if f == Family.TIMESTAMP and e.type.family == Family.DATE \
                and isinstance(v, int):
            return BConst(v * 86_400_000_000, TIMESTAMP)  # days -> us
        if f == Family.DATE and e.type.family == Family.TIMESTAMP \
                and isinstance(v, int):
            return BConst(v // 86_400_000_000, DATE)
        if f == Family.STRING:
            if isinstance(v, str):
                return BConst(v, STRING)
            if isinstance(v, bool):
                return BConst("true" if v else "false", STRING)
            if e.type.family == Family.DECIMAL:
                return BConst(f"{v / 10 ** e.type.scale:.{e.type.scale}f}",
                              STRING)
            if isinstance(v, (int, float)):
                return BConst(str(v), STRING)
        if f == Family.BOOL:
            if isinstance(v, str):
                s = v.strip().lower()
                if s in ("t", "true", "yes", "on", "1"):
                    return BConst(True, target)
                if s in ("f", "false", "no", "off", "0"):
                    return BConst(False, target)
                raise BindError(f"invalid bool value {v!r}")
            if isinstance(v, (bool, int)):
                return BConst(bool(v), target)
        raise BindError(f"cannot convert constant {v!r} to {target}")

    def _rescale_decimal(self, e: BExpr, scale: int) -> BExpr:
        cur = e.type.scale
        if cur == scale:
            return e
        ty = SQLType.decimal(scale=scale)
        if isinstance(e, BConst):
            if e.value is None:
                return BConst(None, ty)
            if scale > cur:
                return BConst(e.value * 10 ** (scale - cur), ty)
            # numeric rounds half away from zero on scale reduction
            div = 10 ** (cur - scale)
            q, r = divmod(abs(e.value), div)
            mag = q + (1 if 2 * r >= div else 0)
            return BConst(-mag if e.value < 0 else mag, ty)
        if scale > cur:
            return BBin("*", e, BConst(10 ** (scale - cur), INT8), ty)
        return BBin("//", e, BConst(10 ** (cur - scale), INT8), ty)

    def _align2(self, a: BExpr, b: BExpr) -> tuple[BExpr, BExpr, SQLType]:
        """Align two operands to a common physical type for +,-,cmp."""
        ta, tb = a.type, b.type
        if ta.family == Family.STRING or tb.family == Family.STRING:
            return a, b, STRING
        if {ta.family, tb.family} <= {Family.DATE, Family.INT}:
            return a, b, DATE if Family.DATE in (ta.family, tb.family) else ta
        target = common_numeric_type(ta, tb)
        return self.coerce(a, target), self.coerce(b, target), target

    def _align3(self, x, lo, hi):
        x2, lo2, _ = self._align2(x, lo)
        x3, hi2, _ = self._align2(x2, hi)
        # re-align lo in case x changed scale
        x4, lo3, _ = self._align2(x3, lo2)
        return x4, lo3, hi2

    # -- operators -----------------------------------------------------------
    def bind_binop(self, e: ast.BinOp) -> BExpr:
        op = e.op
        if op in ("and", "or"):
            l, r = self.bind(e.left), self.bind(e.right)
            for s in (l, r):
                if s.type.family not in (Family.BOOL, Family.UNKNOWN):
                    raise BindError(f"{op.upper()} requires booleans")
            return BBin(op, l, r, BOOL)
        if op == "like":
            return self.bind_like(e)
        l, r = self.bind(e.left), self.bind(e.right)

        # interval constant folding: date +/- interval, timestamp +/- interval
        for a, b, sign_sw in ((l, r, False), (r, l, True)):
            if b.type.family == Family.INTERVAL:
                if not isinstance(b, BConst):
                    raise BindError("non-constant intervals unsupported")
                if op not in ("+", "-"):
                    raise BindError(f"bad interval op {op}")
                sign = -1 if (op == "-" and not sign_sw) else 1
                if sign_sw and op == "-":
                    raise BindError("interval - date is invalid")
                return self._fold_interval(a, b.value, sign)

        # json/array operators and datum-typed operands take the
        # dictionary-LUT path (host-precomputed per-entry tables)
        datum_fams = (Family.JSON, Family.ARRAY)
        if op in ("->", "->>", "@>", "<@", "?") or (
                op in ("=", "!=", "<>", "||")
                and (l.type.family in datum_fams
                     or r.type.family in datum_fams)):
            return self._bind_datum_op("!=" if op == "<>" else op, l, r)
        if op in ("<", "<=", ">", ">=") and (
                l.type.family in datum_fams
                or r.type.family in datum_fams):
            raise BindError(
                "array/jsonb values are not orderable here (codes "
                "order by insertion, not value; only =/!= supported)")

        if op in ("=", "!=", "<>", "<", "<=", ">", ">="):
            if op == "<>":
                op = "!="
            # string comparisons against dict-encoded columns
            s = self._bind_string_compare(op, l, r)
            if s is not None:
                return s
            l2, r2, _ = self._align2(l, r)
            return BBin(op, l2, r2, BOOL)
        if op in ("+", "-"):
            if op == "-" and l.type.family == Family.DATE \
                    and r.type.family == Family.DATE:
                return BBin("-", l, r, INT8)  # day-count difference
            if op == "-" and l.type.family == Family.TIMESTAMP \
                    and r.type.family == Family.TIMESTAMP:
                return BBin("-", l, r, INTERVAL)  # microseconds
            l2, r2, t = self._align2(l, r)
            return BBin(op, l2, r2, t)
        if op == "*":
            return self.bind_mul(l, r)
        if op == "/":
            l2 = self.coerce(l, FLOAT8) if l.type.family != Family.FLOAT else l
            r2 = self.coerce(r, FLOAT8) if r.type.family != Family.FLOAT else r
            return BBin("/", l2, r2, FLOAT8)
        if op == "%":
            l2, r2, t = self._align2(l, r)
            return BBin("%", l2, r2, t)
        if op == "^":
            from . import builtins as bi
            try:
                return bi.bind_builtin(self, "pow", [l, r], e)
            except bi.BuiltinError as err:
                raise BindError(str(err)) from err
        if op == "||":
            # unlike concat() (which skips NULL args, pg-style), the
            # || operator is strict: NULL || x IS NULL
            if (isinstance(l, BConst) and l.value is None) or \
                    (isinstance(r, BConst) and r.value is None):
                return BConst(None, STRING)
            from . import builtins as bi
            try:
                out = bi.bind_builtin(self, "concat", [l, r], e)
            except bi.BuiltinError as err:
                raise BindError(str(err)) from err
            return out
        raise BindError(f"unknown operator {op}")

    def bind_mul(self, l: BExpr, r: BExpr) -> BExpr:
        tl, tr = l.type, r.type
        if Family.FLOAT in (tl.family, tr.family):
            return BBin("*", self.coerce(l, FLOAT8), self.coerce(r, FLOAT8),
                        FLOAT8)
        if tl.family == Family.DECIMAL and tr.family == Family.DECIMAL:
            # scaled-int multiply: scales add (rescale happens only on
            # explicit cast or output)
            ty = SQLType.decimal(scale=tl.scale + tr.scale)
            return BBin("*", l, r, ty)
        if tl.family == Family.DECIMAL or tr.family == Family.DECIMAL:
            dec, other = (l, r) if tl.family == Family.DECIMAL else (r, l)
            if other.type.family != Family.INT:
                raise BindError(f"cannot multiply {tl} by {tr}")
            return BBin("*", dec, other, dec.type)
        l2, r2, t = self._align2(l, r)
        return BBin("*", l2, r2, t)

    def _fold_interval(self, d: BExpr, iv: Interval, sign: int) -> BExpr:
        if d.type.family == Family.DATE:
            if isinstance(d, BConst):
                return BConst(add_interval_to_date(d.value, iv, sign), DATE)
            if iv.months == 0 and iv.micros == 0:
                return BBin("+", d, BConst(sign * iv.days, INT8), DATE)
            raise BindError("month intervals on non-constant dates")
        if d.type.family == Family.TIMESTAMP:
            if iv.months == 0:
                delta = sign * (iv.days * 86_400_000_000 + iv.micros)
                if isinstance(d, BConst):
                    return BConst(d.value + delta, TIMESTAMP)
                return BBin("+", d, BConst(delta, INT8), TIMESTAMP)
            raise BindError("month intervals on timestamps")
        raise BindError(f"interval arithmetic on {d.type}")

    # -- strings over dictionaries --------------------------------------------
    def _dict_of(self, e: BExpr):
        # nodes that carry their own output dictionary (string builtins,
        # CASE over constants) chain transforms: upper(trim(col)) works
        d = getattr(e, "dictionary", None)
        if d is not None:
            return d
        if isinstance(e, BCol) and e.type.uses_dictionary:
            for t in self.scope.tables.values():
                for b in t.values():
                    if b.batch_name == e.name:
                        return b.dictionary
        return None

    def _bind_string_compare(self, op, l, r):
        if l.type.family != Family.STRING and r.type.family != Family.STRING:
            return None
        if isinstance(l, BConst) and isinstance(r, BConst):
            if l.value is None or r.value is None:
                return BConst(None, BOOL)
            lv, rv = str(l.value), str(r.value)
            res = {"=": lv == rv, "!=": lv != rv, "<": lv < rv,
                   "<=": lv <= rv, ">": lv > rv, ">=": lv >= rv}[op]
            return BConst(res, BOOL)
        col, lit, flip = None, None, False
        if isinstance(r, BConst) and isinstance(r.value, str):
            col, lit = l, r.value
        elif isinstance(l, BConst) and isinstance(l.value, str):
            col, lit, flip = r, l.value, True
        if col is None:
            # col-col string compare
            if isinstance(l, BCol) and isinstance(r, BCol) and op in ("=", "!="):
                dl, dr = self._dict_of(l), self._dict_of(r)
                if dl is dr:
                    return BBin(op, l, r, BOOL)
                if dl is not None and dr is not None:
                    # translate r's codes into l's code space (host-side
                    # table; on device it's one gather — join keys ride this)
                    table = np.fromiter(
                        (dl.codes.get(v, -1) for v in dr.values),
                        dtype=np.int32, count=len(dr.values))
                    return BBin(op, l, BDictRemap(r, table, l.type), BOOL)
            raise BindError("unsupported string comparison")
        d = self._dict_of(col)
        if d is None:
            raise BindError("string compare on non-dictionary column")
        if flip:
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
        if op == "=":
            code = d.codes.get(lit)
            if code is None:
                if not self.dict_folds:
                    return BBin("=", col, BConst(-1, col.type), BOOL)
                return BConst(False, BOOL)  # value absent from data
            return BBin("=", col, BConst(code, col.type), BOOL)
        if op == "!=":
            code = d.codes.get(lit)
            if code is None:
                if not self.dict_folds:
                    return BBin("!=", col, BConst(-1, col.type), BOOL)
                return BConst(True, BOOL)
            return BBin("!=", col, BConst(code, col.type), BOOL)
        # ordered compare: evaluate against dictionary -> lookup table
        vals = np.asarray(d.values, dtype=object)
        pyop = {"<": np.less, "<=": np.less_equal,
                ">": np.greater, ">=": np.greater_equal}[op]
        table = pyop(vals.astype(str), lit)
        return BDictLookup(col, np.asarray(table, dtype=bool), BOOL)

    def bind_like(self, e: ast.BinOp) -> BExpr:
        col = self.bind(e.left)
        pat = self.bind(e.right)
        if isinstance(pat, BConst) and pat.value is None:
            return BConst(None, BOOL)  # x LIKE NULL is NULL
        if not isinstance(pat, BConst) or not isinstance(pat.value, str):
            raise BindError("LIKE pattern must be a constant")
        rx = re.compile(
            "^" + re.escape(pat.value).replace("%", ".*").replace("_", ".")
            + "$", re.S)
        if isinstance(col, BConst):
            if col.value is None:
                return BConst(None, BOOL)  # NULL LIKE p is NULL
            return BConst(rx.match(str(col.value)) is not None, BOOL)
        d = self._dict_of(col)
        if d is None:
            raise BindError("LIKE on non-dictionary column")
        table = np.fromiter((rx.match(v) is not None for v in d.values),
                            dtype=bool, count=len(d.values))
        return BDictLookup(col, table, BOOL)

    # -- datum types (ARRAY / JSONB) over dictionaries ------------------------
    #
    # Same playbook as strings: each distinct value is interned under
    # its canonical text (sql/datum.py), so per-row operators become
    # host-precomputed tables over the dictionary — one
    # BDictLookup/BDictRemap/BDictGather on device. The reference
    # instead walks per-element host objects through tree.Datum
    # (coldata/datum_vec.go, util/json) — per-row host work we never do.

    _MISSING = object()

    def _datum_dict(self, col: BExpr):
        d = self._dict_of(col)
        if d is None:
            raise BindError(
                f"{col.type} operator on a column with no dictionary")
        parsed = [dtm.decode_text(v, col.type) for v in d.values]
        return d, parsed

    @staticmethod
    def _json_get(pv, key):
        """jsonb -> field/element access; _MISSING when absent."""
        if isinstance(pv, dict) and isinstance(key, str):
            return pv.get(key, Binder._MISSING)
        if isinstance(pv, list) and isinstance(key, int) \
                and not isinstance(key, bool):
            i = key if key >= 0 else len(pv) + key
            return pv[i] if 0 <= i < len(pv) else Binder._MISSING
        return Binder._MISSING

    @staticmethod
    def _json_contains(a, b) -> bool:
        """jsonb @> containment (pg semantics, recursive)."""
        if isinstance(a, dict) and isinstance(b, dict):
            return all(k in a and Binder._json_contains(a[k], v)
                       for k, v in b.items())
        if isinstance(a, list):
            if isinstance(b, list):
                return all(any(Binder._json_contains(x, y) for x in a)
                           for y in b)
            # a scalar is contained in a top-level array (pg quirk)
            return any(Binder._json_contains(x, b) for x in a)
        return a == b

    def _datum_rhs_value(self, r: BConst, ty):
        """Parse the constant right operand of a datum operator."""
        if r.value is None:
            return None
        if r.type.family in (Family.JSON, Family.ARRAY):
            return dtm.decode_text(r.value, r.type)
        if ty.family == Family.JSON and isinstance(r.value, str) \
                and r.type.family == Family.STRING:
            # bare string literal on @>/? : treat as jsonb when it
            # parses ('{"a":1}'), else as a key string
            return r.value
        return r.value

    def _bind_datum_op(self, op: str, l: BExpr, r: BExpr) -> BExpr:
        from ..storage.columnstore import Dictionary
        if op == "<@":
            return self._bind_datum_op("@>", r, l)
        if op in ("=", "!="):
            return self._datum_eq(op, l, r)
        if op == "||":
            return self._datum_concat(l, r)
        # -> / ->> / @> / ? : constant right operand required (the LUT
        # is precomputed per dictionary entry)
        if isinstance(l, BConst) and isinstance(r, BConst):
            return self._fold_datum_op(op, l, r)
        if not isinstance(r, BConst):
            raise BindError(f"{op} requires a constant right operand")
        if l.type.family not in (Family.JSON, Family.ARRAY):
            raise BindError(f"{op} on {l.type}")
        if r.value is None:
            # NULL result types: predicates are BOOL, ->> is text,
            # -> keeps the datum type (matches the fold path)
            return BConst(None, BOOL if op in ("@>", "?")
                          else STRING if op == "->>" else l.type)
        d, parsed = self._datum_dict(l)
        rv = self._datum_rhs_value(r, l.type)

        if op in ("->", "->>"):
            if l.type.family != Family.JSON:
                raise BindError(f"{op} on {l.type}")
            if isinstance(r.value, int) and r.type.family == Family.INT:
                key = int(r.value)
            elif isinstance(rv, str):
                key = rv
            else:
                raise BindError(f"{op} key must be a string or integer")
            results = [self._json_get(pv, key) for pv in parsed]
            if op == "->":
                d2 = Dictionary()
                table = np.fromiter(
                    (d2.encode(dtm.canon_json(res))
                     if res is not Binder._MISSING else -1
                     for res in results),
                    dtype=np.int32, count=len(results))
                nulls = np.fromiter(
                    (res is not Binder._MISSING for res in results),
                    dtype=bool, count=len(results))
                out = BDictRemap(l, table, SQLType.json(),
                                 null_table=nulls)
                out.dictionary = d2
                return out
            # ->> : text, with JSON null and missing both SQL NULL
            d2 = Dictionary()
            texts = [None if res is Binder._MISSING or res is None
                     else (res if isinstance(res, str)
                           else dtm.canon_json(res))
                     for res in results]
            table = np.fromiter(
                (d2.encode(t) if t is not None else -1 for t in texts),
                dtype=np.int32, count=len(texts))
            nulls = np.fromiter((t is not None for t in texts),
                                dtype=bool, count=len(texts))
            out = BDictRemap(l, table, STRING, null_table=nulls)
            out.dictionary = d2
            return out

        if op == "@>":
            if l.type.family == Family.JSON:
                if isinstance(rv, str) and r.type.family == Family.STRING:
                    rv = dtm.parse_json(rv)
                table = np.fromiter(
                    (self._json_contains(pv, rv) for pv in parsed),
                    dtype=bool, count=len(parsed))
            else:
                if not isinstance(rv, list):
                    raise BindError("array @> needs an array operand")
                table = np.fromiter(
                    (all(y in pv for y in rv) for pv in parsed),
                    dtype=bool, count=len(parsed))
            return BDictLookup(l, table, BOOL)

        if op == "?":
            if not isinstance(rv, str):
                raise BindError("? needs a string key")

            def has_key(pv):
                if isinstance(pv, dict):
                    return rv in pv
                if isinstance(pv, list):
                    return rv in pv
                return pv == rv
            table = np.fromiter((has_key(pv) for pv in parsed),
                                dtype=bool, count=len(parsed))
            return BDictLookup(l, table, BOOL)

        raise BindError(f"unsupported datum operator {op}")

    def _datum_eq(self, op: str, l: BExpr, r: BExpr) -> BExpr:
        if isinstance(l, BConst) and not isinstance(r, BConst):
            l, r = r, l
        if isinstance(l, BConst) and isinstance(r, BConst):
            if l.value is None or r.value is None:
                return BConst(None, BOOL)
            eq = str(l.value) == str(r.value)  # canonical text
            return BConst(eq if op == "=" else not eq, BOOL)
        if isinstance(r, BConst):
            if r.value is None:
                return BConst(None, BOOL)
            d = self._dict_of(l)
            if d is None:
                raise BindError("datum compare on non-dictionary column")
            if r.type.family in (Family.JSON, Family.ARRAY):
                text = r.value
            else:
                try:
                    text = dtm.canon_text(str(r.value), l.type)
                except dtm.DatumError as err:
                    raise BindError(str(err)) from None
            code = d.codes.get(text)
            if code is None:
                if not self.dict_folds:
                    return BBin(op, l, BConst(-1, l.type), BOOL)
                return BConst(op == "!=", BOOL)
            return BBin(op, l, BConst(code, l.type), BOOL)
        # col-col: same dictionary -> direct code compare; else remap
        dl, dr = self._dict_of(l), self._dict_of(r)
        if dl is None or dr is None:
            raise BindError("datum compare on non-dictionary column")
        if dl is dr:
            return BBin(op, l, r, BOOL)
        table = np.fromiter((dl.codes.get(v, -1) for v in dr.values),
                            dtype=np.int32, count=len(dr.values))
        return BBin(op, l, BDictRemap(r, table, l.type), BOOL)

    def _datum_concat(self, l: BExpr, r: BExpr) -> BExpr:
        from ..storage.columnstore import Dictionary
        if isinstance(l, BConst) and not isinstance(r, BConst):
            raise BindError("const || column arrays not supported")
        if (isinstance(l, BConst) and l.value is None) or \
                (isinstance(r, BConst) and r.value is None):
            return BConst(None, l.type if not isinstance(l, BConst)
                          or l.value is not None else r.type)
        # jsonb || jsonb: a bare string literal operand must BE jsonb
        # (pg rejects jsonb || text); parse it so '{"z":true}' merges
        # as an object instead of appending as a scalar string
        if l.type.family == Family.JSON and isinstance(r, BConst) \
                and r.type.family == Family.STRING:
            r = self._const_to(r, SQLType.json())
        if isinstance(l, BConst) and isinstance(r, BConst):
            if l.type.family == Family.ARRAY:
                elem = l.type.elem
                vals = dtm.parse_array(l.value, elem) + \
                    dtm.parse_array(r.value, r.type.elem)
                return BConst(dtm.canon_array(vals, elem), l.type)
            a, b = dtm.parse_json(l.value), dtm.parse_json(r.value)
            if isinstance(a, dict) and isinstance(b, dict):
                return BConst(dtm.canon_json({**a, **b}), l.type)
            la = a if isinstance(a, list) else [a]
            lb = b if isinstance(b, list) else [b]
            return BConst(dtm.canon_json(la + lb), l.type)
        if not isinstance(r, BConst):
            raise BindError("array || array needs a constant operand")
        d, parsed = self._datum_dict(l)
        rv = self._datum_rhs_value(r, l.type)
        d2 = Dictionary()
        if l.type.family == Family.ARRAY:
            if not isinstance(rv, list):
                rv = [rv]
            texts = [dtm.canon_array(pv + rv, l.type.elem)
                     for pv in parsed]
        else:
            def joinj(pv):
                if isinstance(pv, dict) and isinstance(rv, dict):
                    return dtm.canon_json({**pv, **rv})
                la = pv if isinstance(pv, list) else [pv]
                lb = rv if isinstance(rv, list) else [rv]
                return dtm.canon_json(la + lb)
            texts = [joinj(pv) for pv in parsed]
        table = np.fromiter((d2.encode(t) for t in texts),
                            dtype=np.int32, count=len(texts))
        out = BDictRemap(l, table, l.type)
        out.dictionary = d2
        return out

    def bind_subscript(self, e: ast.Subscript) -> BExpr:
        x = self.bind(e.expr)
        if x.type.family == Family.JSON:
            return self._bind_datum_op("->", x, self.bind(e.index))
        if x.type.family != Family.ARRAY:
            raise BindError(f"cannot subscript {x.type}")
        idx = self.bind(e.index)
        if not isinstance(idx, BConst) or \
                idx.type.family != Family.INT:
            raise BindError("array index must be a constant integer")
        i = int(idx.value)
        elem = x.type.elem
        if isinstance(x, BConst):
            if x.value is None:
                return BConst(None, elem)
            vals = dtm.parse_array(x.value, elem)
            v = vals[i - 1] if 1 <= i <= len(vals) else None
            return self._elem_const(v, elem)
        d, parsed = self._datum_dict(x)
        picks = [pv[i - 1] if 1 <= i <= len(pv) else None
                 for pv in parsed]
        return self._elem_lut(x, picks, elem)

    def _elem_const(self, v, elem: SQLType) -> BConst:
        if v is None:
            return BConst(None, elem)
        if elem.family == Family.DECIMAL:
            return BConst(int(round(float(v) * 10 ** elem.scale)), elem)
        return BConst(v, elem)

    def _elem_lut(self, col: BExpr, picks: list, elem: SQLType) -> BExpr:
        """Per-dictionary-entry element values -> one typed LUT node."""
        from ..storage.columnstore import Dictionary
        nulls = np.fromiter((p is not None for p in picks),
                            dtype=bool, count=len(picks))
        if elem.family == Family.STRING:
            d2 = Dictionary()
            table = np.fromiter(
                (d2.encode(p) if p is not None else -1 for p in picks),
                dtype=np.int32, count=len(picks))
            out = BDictRemap(col, table, STRING, null_table=nulls)
            out.dictionary = d2
            return out
        if elem.family == Family.DECIMAL:
            vals = [int(round(float(p) * 10 ** elem.scale))
                    if p is not None else 0 for p in picks]
        elif elem.family == Family.FLOAT:
            vals = [float(p) if p is not None else 0.0 for p in picks]
        elif elem.family == Family.BOOL:
            vals = [bool(p) if p is not None else False for p in picks]
        else:
            vals = [int(p) if p is not None else 0 for p in picks]
        table = np.asarray(vals, dtype=elem.np_dtype)
        return BDictGather(col, table, elem, null_table=nulls)

    def bind_array_lit(self, e: ast.ArrayLit) -> BExpr:
        items = [self.bind(i) for i in e.items]
        if not all(isinstance(b, BConst) for b in items):
            raise BindError(
                "ARRAY[...] elements must be constants (arrays built "
                "from row values are not supported)")
        fams = {b.type.family for b in items
                if b.type.family != Family.UNKNOWN}
        if not fams:
            elem = INT8
        elif fams <= {Family.INT}:
            elem = INT8
        elif fams <= {Family.INT, Family.FLOAT, Family.DECIMAL}:
            elem = FLOAT8
        elif fams == {Family.STRING}:
            elem = STRING
        elif fams == {Family.BOOL}:
            elem = BOOL
        else:
            raise BindError(f"mixed array element types {fams}")
        vals = []
        for b in items:
            if b.value is None:
                vals.append(None)
            elif b.type.family == Family.DECIMAL:
                vals.append(b.value / 10 ** b.type.scale)
            else:
                vals.append(b.value)
        return BConst(dtm.canon_array(vals, elem), SQLType.array(elem))

    def _fold_datum_op(self, op: str, l: BConst, r: BConst) -> BConst:
        if l.value is None or r.value is None:
            return BConst(None, BOOL if op in ("@>", "?")
                          else STRING if op == "->>" else l.type)
        lv = dtm.decode_text(l.value, l.type)
        rv = self._datum_rhs_value(r, l.type)
        if op in ("->", "->>"):
            key = int(r.value) if (isinstance(r.value, int)
                                   and r.type.family == Family.INT) else rv
            res = self._json_get(lv, key)
            if res is Binder._MISSING:
                return BConst(None, SQLType.json() if op == "->"
                              else STRING)
            if op == "->":
                return BConst(dtm.canon_json(res), SQLType.json())
            if res is None:
                return BConst(None, STRING)
            return BConst(res if isinstance(res, str)
                          else dtm.canon_json(res), STRING)
        if op == "@>":
            if l.type.family == Family.JSON:
                if isinstance(rv, str):
                    rv = dtm.parse_json(rv)
                return BConst(self._json_contains(lv, rv), BOOL)
            if not isinstance(rv, list):
                raise BindError("array @> needs an array operand")
            return BConst(all(y in lv for y in rv), BOOL)
        if op == "?":
            if not isinstance(rv, str):
                raise BindError("? needs a string key")
            if isinstance(lv, (dict, list)):
                return BConst(rv in lv, BOOL)
            return BConst(lv == rv, BOOL)
        raise BindError(f"unsupported datum operator {op}")

    # -- IN / CASE / CAST ------------------------------------------------------
    def bind_in(self, e: ast.InList) -> BExpr:
        x = self.bind(e.expr)
        vals = []
        if x.type.family == Family.STRING:
            d = self._dict_of(x)
            if d is None:
                raise BindError("IN on non-dictionary string column")
            for item in e.items:
                b = self.bind(item)
                if not isinstance(b, BConst):
                    raise BindError("IN list must be constants")
                code = d.codes.get(b.value)
                if code is not None:
                    vals.append(code)
                elif not self.dict_folds:
                    vals.append(-1)   # impossible code: never matches
            if not vals:
                return BConst(e.negated, BOOL)
            return BInList(x, vals, e.negated, BOOL)
        # common numeric type across x and all items (so `int_col IN
        # (1.5)` compares at decimal precision instead of rounding 1.5)
        bound_items = [self.bind(i) for i in e.items]
        target = x.type
        for b in bound_items:
            target = common_numeric_type(target, b.type) \
                if x.type.is_numeric else target
        x2 = self.coerce(x, target) if x.type != target else x
        for b in bound_items:
            b2 = self.coerce(b, target)
            if not isinstance(b2, BConst):
                raise BindError("IN list must be constants")
            vals.append(b2.value)
        return BInList(x2, vals, e.negated, BOOL)

    def bind_case(self, e: ast.Case) -> BExpr:
        whens = [(self.bind(c), self.bind(v)) for c, v in e.whens]
        else_ = self.bind(e.else_) if e.else_ is not None else BConst(
            None, SQLType.unknown())
        # result type: first non-unknown branch type, all coerced to it
        rty = None
        for _, v in whens:
            if v.type.family != Family.UNKNOWN:
                rty = v.type
                break
        if rty is None:
            rty = else_.type
        if rty.family == Family.UNKNOWN:
            raise BindError("untyped CASE")
        if rty.family == Family.STRING:
            # constant string branches get an ad-hoc output dictionary
            from ..storage.columnstore import Dictionary
            d = Dictionary()

            def enc(v):
                if isinstance(v, BConst):
                    if v.value is None:
                        return BConst(None, STRING)
                    if not isinstance(v.value, str):
                        raise BindError("mixed CASE branch types")
                    return BConst(d.encode(v.value), STRING)
                raise BindError(
                    "CASE over string columns not supported (constants only)")
            whens = [(c, enc(v)) for c, v in whens]
            else_ = enc(else_) if not (isinstance(else_, BConst)
                                       and else_.value is None) else BConst(None, STRING)
            out = BCase(whens, else_, STRING)
            out.dictionary = d
            return out
        # widen decimals to max scale among branches
        if rty.family == Family.DECIMAL:
            smax = max([v.type.scale for _, v in whens
                        if v.type.family == Family.DECIMAL] +
                       ([else_.type.scale]
                        if else_.type.family == Family.DECIMAL else [0]))
            rty = SQLType.decimal(scale=smax)
        whens = [(c, self.coerce(v, rty)) for c, v in whens]
        else_ = self.coerce(else_, rty)
        return BCase(whens, else_, rty)

    def bind_cast(self, x: BExpr, to: SQLType) -> BExpr:
        if x.type.family == to.family and x.type == to:
            return x
        if x.type.family in (Family.JSON, Family.ARRAY) \
                and to.family == Family.STRING and not isinstance(x, BConst):
            # datum::TEXT — the stored canonical text IS the result;
            # identity remap re-types the codes under a string dict
            from ..storage.columnstore import Dictionary
            d = self._dict_of(x)
            if d is None:
                raise BindError("cast on non-dictionary datum column")
            d2 = Dictionary()
            table = np.fromiter((d2.encode(v) for v in d.values),
                                dtype=np.int32,
                                count=len(d.values))
            out = BDictRemap(x, table, STRING)
            out.dictionary = d2
            return out
        if isinstance(x, BConst):
            return self._const_to(x, to)
        if to.family == Family.FLOAT:
            return BCast(x, FLOAT8)
        if to.family == Family.DECIMAL:
            if x.type.family == Family.DECIMAL:
                return self._rescale_decimal(x, to.scale)
            if x.type.family == Family.INT:
                return BBin("*", x, BConst(10 ** to.scale, INT8), to)
            if x.type.family == Family.FLOAT:
                return BCast(x, to)  # executor rounds
        if to.family == Family.INT:
            return BCast(x, to)
        raise BindError(f"unsupported cast {x.type} -> {to}")

    # -- functions & aggregates --------------------------------------------
    def bind_func(self, e: ast.FuncCall) -> BExpr:
        name = e.name
        if name in ("nextval", "random", "gen_random_uuid") \
                and self.scope.tables and not self.volatile_fold_ok:
            raise BindError(
                f"{name}() in a statement with a FROM clause is not "
                "supported: it would fold to one value per statement "
                "instead of one per row")
        if name in AGG_FUNCS or name in self.STATS_AGGS \
                or name in self.BOOL_AGGS:
            if not self._collect_aggs:
                raise BindError(f"aggregate {name} not allowed here")
            return self._bind_agg(e)
        if name in ("nextval", "currval", "setval"):
            if self.sequence_ops is None:
                raise BindError(
                    f"{name} is not available in this context")
            if not e.args or not isinstance(e.args[0], ast.Literal) \
                    or not isinstance(e.args[0].value, str):
                raise BindError(
                    f"{name} takes a sequence name string literal")
            seq = e.args[0].value
            arg = None
            if name == "setval":
                if len(e.args) != 2:
                    raise BindError("setval(seq, value)")
                v = self.bind(e.args[1])
                if not isinstance(v, BConst) or v.value is None:
                    raise BindError("setval(seq, value) takes a "
                                    "constant value")
                try:
                    arg = int(v.value)
                except (TypeError, ValueError):
                    raise BindError(
                        f"setval value must be an integer, got "
                        f"{v.value!r}")
            return BConst(self.sequence_ops(name, seq, arg), INT8)
        if name == "coalesce":
            args = [self.bind(a) for a in e.args]
            rty = next((a.type for a in args
                        if a.type.family != Family.UNKNOWN), None)
            if rty is None:
                raise BindError("untyped COALESCE")
            args = [self.coerce(a, rty) for a in args]
            return BCoalesce(args, rty)
        if name == "abs":
            x = self.bind(e.args[0])
            return BUnary("abs", x, x.type)
        if name == "round" and len(e.args) == 1:
            x = self.coerce(self.bind(e.args[0]), FLOAT8)
            return BUnary(name, x, FLOAT8)
        from . import builtins as bi
        args = [self.bind(a) for a in e.args]
        try:
            out = bi.bind_builtin(self, name, args, e)
        except bi.BuiltinError as err:
            raise BindError(str(err)) from err
        if out is not None:
            return out
        raise BindError(f"unknown function {name}")

    # statistical aggregates rewritten at bind time into compositions
    # of sum/count partials (the reference computes them the same way
    # from local sums, builtins/aggregate_builtins.go): no new device
    # kernels, and distributed/streaming merges come for free
    STATS_AGGS = {"stddev", "stddev_samp", "stddev_pop",
                  "variance", "var_samp", "var_pop"}
    BOOL_AGGS = {"bool_and": "min", "bool_or": "max", "every": "min"}

    def _reg_agg(self, spec: BoundAgg) -> BExpr:
        for i, existing in enumerate(self.aggs):
            if _agg_key(existing) == _agg_key(spec):
                return BAggRef(i, existing.type)
        self.aggs.append(spec)
        return BAggRef(len(self.aggs) - 1, spec.type)

    def _check_no_nested_agg(self, arg: BExpr) -> None:
        from .bound import walk as _walk
        for nd in _walk(arg):
            if isinstance(nd, BAggRef):
                raise BindError("nested aggregates")

    def _bind_stats_agg(self, name: str, e: ast.FuncCall) -> BExpr:
        """stddev/variance via single-pass sum-of-squares partials in
        float64. PRECISION CAVEAT (round-4 advisor): for large-mean,
        low-variance data (mean ~1e8, var ~1) the ``sum(x²)-sum(x)²/n``
        form cancels catastrophically where Postgres' Youngs-Cramer
        recurrence stays accurate; the clamp-to-0 CASE below bounds the
        failure at 0, not at a wrong positive value. The single-pass
        form is what splits across DistSQL partials (SUM/SUM/COUNT
        merge; a per-group mean-centering pre-pass would need a second
        scan). Tests pin the well-conditioned cases; document, don't
        hide, the ill-conditioned one."""
        if e.distinct:
            raise BindError(f"{name}(DISTINCT) not supported")
        if len(e.args) != 1:
            raise BindError(f"{name} takes one argument")
        x = self.coerce(self.bind(e.args[0]), FLOAT8)
        self._check_no_nested_agg(x)
        s = self._reg_agg(BoundAgg("sum", x, FLOAT8))
        ss = self._reg_agg(BoundAgg("sum", BBin("*", x, x, FLOAT8),
                                    FLOAT8))
        n = self.coerce(self._reg_agg(BoundAgg("count", x, INT8)),
                        FLOAT8)
        # var_pop = (sum(x^2) - sum(x)^2/n) / n; _samp divides by n-1
        # (NULL when the divisor is zero, pg semantics, via nullif)
        num = BBin("-", ss, BBin("/", BBin("*", s, s, FLOAT8), n,
                                 FLOAT8), FLOAT8)
        pop = name.endswith("_pop")
        div = n if pop else BBin("-", n, BConst(1.0, FLOAT8), FLOAT8)
        var = BBin("/", num, BFunc("nullif", [div,
                                              BConst(0.0, FLOAT8)],
                                   FLOAT8), FLOAT8)
        # float error can drive the numerator epsilon-negative; CASE
        # (not greatest: pg's greatest IGNORES NULLs, which would turn
        # the empty-set NULL into 0)
        var = BCase(whens=[(BBin("<", var, BConst(0.0, FLOAT8), BOOL),
                            BConst(0.0, FLOAT8))],
                    else_=var, type=FLOAT8)
        if name.startswith("stddev"):
            return BFunc("sqrt", [var], FLOAT8)
        return var

    def _bind_agg(self, e: ast.FuncCall) -> BExpr:
        name = e.name
        if name in self.STATS_AGGS:
            return self._bind_stats_agg(name, e)
        if name in self.BOOL_AGGS:
            if len(e.args) != 1:
                raise BindError(f"{name} takes one argument")
            # min/max over the 0/1 encoding (the scatter identities
            # have no bool lane); the ref casts back to BOOL
            arg = BCast(self.coerce(self.bind(e.args[0]), BOOL), INT8)
            self._check_no_nested_agg(arg)
            ref = self._reg_agg(BoundAgg(self.BOOL_AGGS[name], arg,
                                         INT8))
            return BCast(ref, BOOL)
        if name == "count" and e.star:
            spec = BoundAgg("count_rows", None, INT8)
        else:
            if len(e.args) != 1:
                raise BindError(f"{name} takes one argument")
            arg = self.bind(e.args[0])
            for a in (arg,):
                from .bound import walk
                for nd in walk(a):
                    if isinstance(nd, BAggRef):
                        raise BindError("nested aggregates")
            if name == "count":
                spec = BoundAgg("count", arg, INT8, e.distinct)
            elif name == "avg":
                spec = BoundAgg("avg", arg, FLOAT8, e.distinct)
            elif name == "sum":
                if arg.type.family == Family.INT:
                    spec = BoundAgg("sum_int", arg, INT8, e.distinct)
                elif arg.type.family == Family.DECIMAL:
                    spec = BoundAgg("sum", arg, arg.type, e.distinct)
                else:
                    spec = BoundAgg("sum", self.coerce(arg, FLOAT8), FLOAT8,
                                    e.distinct)
            elif name in ("min", "max"):
                spec = BoundAgg(name, arg, arg.type, e.distinct)
            else:
                raise BindError(name)
        if spec.distinct and spec.func in ("min", "max"):
            spec.distinct = False  # DISTINCT is a no-op for min/max
        # dedup identical aggregates
        for i, existing in enumerate(self.aggs):
            if _agg_key(existing) == _agg_key(spec):
                return BAggRef(i, existing.type)
        self.aggs.append(spec)
        return BAggRef(len(self.aggs) - 1, spec.type)

    def bind_with_aggs(self, e: ast.Expr) -> BExpr:
        self._collect_aggs = True
        try:
            return self.bind(e)
        finally:
            self._collect_aggs = False

    # -- window functions ---------------------------------------------------
    WINDOW_FUNCS = {"row_number", "rank", "dense_rank", "lag", "lead",
                    "first_value", "last_value", "ntile"}

    def bind_window(self, e: ast.WindowCall) -> BExpr:
        if not self._collect_windows:
            raise BindError("window functions not allowed here")
        name = e.func
        parts = [self.bind(p) for p in e.partition_by]
        orders = [(self.bind(o.expr), o.desc) for o in e.order_by]
        offset = 1
        arg = None
        if name in ("row_number", "rank", "dense_rank"):
            if e.args:
                raise BindError(f"{name}() takes no arguments")
            if not orders:
                raise BindError(f"{name}() requires ORDER BY")
            ty = INT8
        elif name in ("lag", "lead"):
            if not 1 <= len(e.args) <= 2:
                raise BindError(f"{name}(expr[, offset])")
            if not orders:
                raise BindError(f"{name}() requires ORDER BY")
            arg = self.bind(e.args[0])
            if len(e.args) == 2:
                off = self.bind(e.args[1])
                if not isinstance(off, BConst):
                    raise BindError(f"{name} offset must be constant")
                offset = int(off.value)
            ty = arg.type
        elif name in ("first_value", "last_value"):
            if len(e.args) != 1:
                raise BindError(f"{name}(expr)")
            arg = self.bind(e.args[0])
            ty = arg.type
        elif name == "ntile":
            if len(e.args) != 1:
                raise BindError("ntile(buckets)")
            if not orders:
                raise BindError("ntile() requires ORDER BY")
            nb = self.bind(e.args[0])
            if not isinstance(nb, BConst) \
                    or nb.type.family != Family.INT \
                    or nb.value is None or int(nb.value) < 1:
                raise BindError("ntile bucket count must be a "
                                "positive integer constant")
            offset = int(nb.value)  # bucket count rides the offset slot
            ty = INT8
        elif name == "count" and e.star:
            ty = INT8
            name = "count_rows"
        elif name in AGG_FUNCS:
            if len(e.args) != 1:
                raise BindError(f"{name} takes one argument")
            arg = self.bind(e.args[0])
            if name == "count":
                ty = INT8
            elif name == "avg":
                ty = FLOAT8
            elif name == "sum":
                if arg.type.family == Family.INT:
                    name, ty = "sum_int", INT8
                elif arg.type.family == Family.DECIMAL:
                    ty = arg.type
                else:
                    arg = self.coerce(arg, FLOAT8)
                    ty = FLOAT8
            else:  # min/max
                ty = arg.type
        else:
            raise BindError(f"unknown window function {name}")
        spec = BoundWindow(name, arg, parts, orders, offset, ty)
        self.windows.append(spec)
        return BWinRef(len(self.windows) - 1, ty)

    def bind_with_windows(self, e: ast.Expr) -> BExpr:
        self._collect_windows = True
        try:
            return self.bind(e)
        finally:
            self._collect_windows = False


def _agg_key(a: BoundAgg):
    return (a.func, repr(a.arg), a.distinct)
