"""Pratt-style recursive-descent SQL parser.

Grammar coverage tracks what the execution engine supports (the TPC-H /
SSB / YCSB benchmark surface plus DDL/DML): SELECT with joins, GROUP
BY/HAVING, ORDER BY/LIMIT, CASE, CAST, BETWEEN, IN, LIKE, EXTRACT,
SUBSTRING, date/interval literals; CREATE/DROP TABLE; INSERT/UPDATE/
DELETE; SET/SHOW; EXPLAIN [ANALYZE]; BEGIN/COMMIT/ROLLBACK.

The reference's grammar is goyacc-generated from a 5MB sql.y
(pkg/sql/parser/BUILD.bazel:86-99); precedence below mirrors standard
PostgreSQL precedence.
"""

from __future__ import annotations

from . import ast
from .lexer import Tok, Token, lex
from .types import (BOOL, DATE, FLOAT4, FLOAT8, INT2, INT4, INT8, INTERVAL,
                    STRING, TIMESTAMP, SQLType)


class ParseError(Exception):
    pass


# binding powers for binary operators
PRECEDENCE = {
    "or": 10,
    "and": 20,
    # NOT handled as prefix with bp 25
    "=": 40, "!=": 40, "<>": 40, "<": 40, "<=": 40, ">": 40, ">=": 40,
    "like": 40, "ilike": 40,
    "@>": 42, "<@": 42, "?": 42,   # json/array containment + key-exists
    "||": 45,
    "->": 65, "->>": 65,           # json access binds tighter than math
    "+": 50, "-": 50,
    "*": 60, "/": 60, "%": 60,
    "^": 70,  # below unary +/- (pg: -2 ^ 2 = (-2)^2 = 4)
    "::": 80,
}

TYPE_NAMES = {
    "int": INT8, "int2": INT2, "int4": INT4, "int8": INT8, "bigint": INT8,
    "smallint": INT2, "integer": INT4, "bool": BOOL, "boolean": BOOL,
    "float": FLOAT8, "float4": FLOAT4, "float8": FLOAT8, "real": FLOAT4,
    "double": FLOAT8, "date": DATE, "timestamp": TIMESTAMP,
    "timestamptz": TIMESTAMP, "interval": INTERVAL, "string": STRING,
    "text": STRING, "varchar": STRING, "char": STRING,
    "jsonb": SQLType.json(), "json": SQLType.json(),
}


class Parser:
    def __init__(self, sql: str):
        self.sql = sql  # kept for view-body text capture
        self.toks = lex(sql)
        self.i = 0

    # -- token helpers -----------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != Tok.EOF:
            self.i += 1
        return t

    def accept_kw(self, *kws: str) -> bool:
        if self.peek().is_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise ParseError(f"expected {kw.upper()}, got {self.peek()}")

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == Tok.OP and t.text == op:
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise ParseError(f"expected {op!r}, got {self.peek()}")

    def expect_ident(self) -> str:
        t = self.next()
        if t.kind not in (Tok.IDENT, Tok.KEYWORD):
            raise ParseError(f"expected identifier, got {t}")
        return t.text

    def dotted_name(self) -> str:
        """a.b.c — setting/variable names."""
        parts = [self.expect_ident()]
        while self.accept_op("."):
            parts.append(self.expect_ident())
        return ".".join(parts)

    # -- entry -------------------------------------------------------------
    def parse_statement(self) -> ast.Statement:
        t = self.peek()
        if t.is_kw("select"):
            return self.parse_select_stmt()
        if t.is_kw("with"):
            return self.parse_with()
        if t.is_kw("create"):
            return self.parse_create()
        if t.is_kw("drop"):
            return self.parse_drop()
        if t.is_kw("alter"):
            return self.parse_alter()
        if t.is_kw("insert"):
            return self.parse_insert()
        if t.is_kw("upsert"):
            return self.parse_insert(upsert=True)
        if t.is_kw("update"):
            return self.parse_update()
        if t.is_kw("delete"):
            return self.parse_delete()
        if t.is_kw("set"):
            return self.parse_set()
        if t.is_kw("show"):
            self.next()
            if self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                    and self.peek().text == "tables":
                self.next()
                return ast.ShowTables()
            if self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                    and self.peek().text == "jobs":
                self.next()
                return ast.ShowJobs()
            if self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                    and self.peek().text == "statements":
                self.next()
                return ast.ShowStatements()
            if self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                    and self.peek().text == "indexes":
                self.next()
                self.expect_kw("from")
                return ast.ShowIndexes(self.expect_ident())
            if self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                    and self.peek().text == "columns":
                self.next()
                self.expect_kw("from")
                return ast.ShowColumns(self.expect_ident())
            if self.peek().kind == Tok.IDENT \
                    and self.peek().text == "sequences":
                self.next()
                return ast.ShowSequences()
            if self.peek().is_kw("create"):
                self.next()
                self.expect_kw("table")
                return ast.ShowCreateTable(self.expect_ident())
            if self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                    and self.peek().text == "zone":
                self.next()
                for word in ("configuration", "for"):
                    if not (self.peek().kind in (Tok.IDENT, Tok.KEYWORD)
                            and self.peek().text == word):
                        raise ParseError(
                            "expected ZONE CONFIGURATION FOR TABLE")
                    self.next()
                self.expect_kw("table")
                return ast.ShowZone(self.expect_ident())
            if self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                    and self.peek().text == "trace":
                self.next()
                self.expect_kw("for")
                if not (self.peek().kind in (Tok.IDENT, Tok.KEYWORD)
                        and self.peek().text == "session"):
                    raise ParseError("expected SESSION after TRACE FOR")
                self.next()
                return ast.ShowTrace()
            if self.accept_kw("all"):
                return ast.ShowAll()
            self.accept_kw("cluster")
            self.accept_kw("setting")
            return ast.ShowVar(self.dotted_name())
        if t.is_kw("explain"):
            self.next()
            analyze = self.accept_kw("analyze")
            debug = False
            if analyze and self.accept_op("("):
                # EXPLAIN ANALYZE (DEBUG): the reference's option list
                # (sql.y explain_option_list); DEBUG — produce a
                # statement diagnostics bundle — is the only option
                # understood here
                while True:
                    o = self.next()
                    if o.kind not in (Tok.IDENT, Tok.KEYWORD) \
                            or o.text.lower() != "debug":
                        raise ParseError(
                            f"unsupported EXPLAIN ANALYZE option "
                            f"{o.text!r} (only DEBUG)")
                    debug = True
                    if not self.accept_op(","):
                        break
                if not self.accept_op(")"):
                    raise ParseError(
                        "expected ) closing EXPLAIN ANALYZE options")
            return ast.Explain(self.parse_statement(), analyze=analyze,
                               debug=debug)
        if t.is_kw("analyze"):
            self.next()
            return ast.Analyze(self.expect_ident())
        if t.kind == Tok.IDENT and t.text == "truncate":
            self.next()
            self.accept_kw("table")
            return ast.Truncate(self.expect_ident())
        if t.kind in (Tok.IDENT, Tok.KEYWORD) and t.text == "cancel":
            self.next()
            if not (self.peek().kind in (Tok.IDENT, Tok.KEYWORD)
                    and self.peek().text == "job"):
                raise ParseError("expected JOB after CANCEL")
            self.next()
            n = self.next()
            if n.kind != Tok.NUMBER:
                raise ParseError("expected job id")
            return ast.CancelJob(int(n.text))
        if t.is_kw("backup"):
            self.next()
            self.expect_kw("table")
            tables = [self.expect_ident()]
            while self.accept_op(","):
                tables.append(self.expect_ident())
            self.expect_kw("into")
            s = self.next()
            if s.kind != Tok.STRING:
                raise ParseError("expected destination string")
            return ast.Backup(tables, s.text)
        if t.is_kw("restore"):
            self.next()
            tables = []
            if self.accept_kw("table"):
                tables.append(self.expect_ident())
                while self.accept_op(","):
                    tables.append(self.expect_ident())
            self.expect_kw("from")
            s = self.next()
            if s.kind != Tok.STRING:
                raise ParseError("expected source string")
            return ast.Restore(tables, s.text)
        if t.is_kw("begin"):
            self.next()
            self.accept_kw("transaction")
            return ast.BeginTxn()
        if t.is_kw("commit"):
            self.next()
            return ast.CommitTxn()
        if t.is_kw("rollback"):
            self.next()
            return ast.RollbackTxn()
        raise ParseError(f"unexpected {t}")

    def finish(self) -> None:
        self.accept_op(";")
        if self.peek().kind != Tok.EOF:
            raise ParseError(f"trailing tokens at {self.peek()}")

    # -- SELECT ------------------------------------------------------------
    def parse_select_stmt(self) -> ast.Statement:
        """A select possibly chained with UNION/INTERSECT/EXCEPT
        (left-associative); ORDER BY/LIMIT parsed into the last branch
        hoist to the set op, matching pg's grammar."""
        node: ast.Statement = self.parse_select()
        while self.peek().is_kw("union", "intersect", "except"):
            op = self.next().text
            all_ = self.accept_kw("all")
            if self.accept_kw("distinct"):
                all_ = False
            right = self.parse_select()
            node = ast.SetOp(op, all_, node, right)
        if isinstance(node, ast.SetOp):
            last = node.right
            if isinstance(last, ast.Select) and (
                    last.order_by or last.limit is not None
                    or last.offset is not None):
                node.order_by = last.order_by
                node.limit, node.offset = last.limit, last.offset
                last.order_by = []
                last.limit = last.offset = None
        return node

    def parse_with(self) -> ast.Select:
        """WITH name [(cols)] AS (select) [, ...] SELECT ... — the CTEs
        attach to the main Select (non-recursive; RECURSIVE rejected)."""
        self.expect_kw("with")
        if self.accept_kw("recursive"):
            raise ParseError("WITH RECURSIVE not supported")
        ctes = []
        while True:
            name = self.expect_ident()
            cols = None
            if self.accept_op("("):
                cols = [self.expect_ident()]
                while self.accept_op(","):
                    cols.append(self.expect_ident())
                self.expect_op(")")
            self.expect_kw("as")
            self.expect_op("(")
            sub = self.parse_with() if self.peek().is_kw("with") \
                else self.parse_select_stmt()
            self.expect_op(")")
            ctes.append((name, cols, sub))
            if not self.accept_op(","):
                break
        sel = self.parse_select_stmt()
        sel.ctes = ctes + sel.ctes
        return sel

    def parse_select(self) -> ast.Select:
        self.expect_kw("select")
        sel = ast.Select()
        sel.distinct = self.accept_kw("distinct")
        while True:
            if self.accept_op("*"):
                sel.items.append(ast.SelectItem(expr=None, star=True))
            else:
                e = self.parse_expr()
                alias = None
                if self.accept_kw("as"):
                    alias = self.expect_ident()
                elif self.peek().kind == Tok.IDENT:
                    alias = self.next().text
                sel.items.append(ast.SelectItem(expr=e, alias=alias))
            if not self.accept_op(","):
                break
        if self.accept_kw("from"):
            sel.table = self.parse_table_ref()
            while True:
                jt = self.parse_join_type()
                if jt is None:
                    break
                tbl = self.parse_table_ref()
                on = None
                if jt != "cross":
                    self.expect_kw("on")
                    on = self.parse_expr()
                sel.joins.append(ast.JoinClause(tbl, jt, on))
            if self.peek().is_kw("as") and \
                    self.peek(1).kind == Tok.IDENT \
                    and self.peek(1).text == "of":
                # AS OF SYSTEM TIME <expr> (historical read)
                self.next()
                self.next()
                for word in ("system", "time"):
                    t = self.next()
                    if not (t.kind == Tok.IDENT and t.text == word):
                        raise ParseError("expected SYSTEM TIME after "
                                         "AS OF")
                sel.as_of = self.parse_expr()
        if self.accept_kw("where"):
            sel.where = self.parse_expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            sel.group_by.append(self.parse_expr())
            while self.accept_op(","):
                sel.group_by.append(self.parse_expr())
        if self.accept_kw("having"):
            sel.having = self.parse_expr()
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.parse_expr()
                desc = False
                if self.accept_kw("desc"):
                    desc = True
                else:
                    self.accept_kw("asc")
                nulls_first = None
                if self.accept_kw("nulls"):
                    if self.accept_kw("first"):
                        nulls_first = True
                    elif self.accept_kw("last"):
                        nulls_first = False
                    else:
                        raise ParseError("expected FIRST or LAST")
                sel.order_by.append(ast.OrderItem(e, desc, nulls_first))
                if not self.accept_op(","):
                    break
        if self.accept_kw("limit"):
            sel.limit = int(self.next().text)
        if self.accept_kw("offset"):
            sel.offset = int(self.next().text)
        return sel

    def parse_table_ref(self) -> ast.TableRef:
        if self.peek().kind == Tok.OP and self.peek().text == "(":
            # derived table: FROM (SELECT ...) [AS] alias
            self.next()
            sub = self.parse_with() if self.peek().is_kw("with") \
                else self.parse_select_stmt()
            self.expect_op(")")
            self.accept_kw("as")
            alias = self.expect_ident()
            return ast.TableRef(alias, alias, subquery=sub)
        name = self.expect_ident()
        if self.peek().kind == Tok.OP and self.peek().text == "(":
            # set-returning function in FROM position:
            #   FROM generate_series(a, b) [AS] g[(col)]
            # desugars to the supported derived-table shape
            #   (SELECT fn(...) AS col) AS g
            self.next()
            args = []
            if not (self.peek().kind == Tok.OP
                    and self.peek().text == ")"):
                args.append(self.parse_expr(0))
                while self.accept_op(","):
                    args.append(self.parse_expr(0))
            self.expect_op(")")
            self.accept_kw("as")
            alias = name
            if self.peek().kind == Tok.IDENT:
                alias = self.next().text
            col = alias
            if self.peek().kind == Tok.OP and self.peek().text == "(":
                self.next()
                col = self.expect_ident()
                self.expect_op(")")
            sub = ast.Select(
                items=[ast.SelectItem(
                    ast.FuncCall(name, args), alias=col)],
                table=None)
            return ast.TableRef(alias, alias, subquery=sub)
        alias = None
        if self.peek().is_kw("as") and not (
                self.peek(1).kind == Tok.IDENT
                and self.peek(1).text == "of"):
            self.next()
            alias = self.expect_ident()
        elif self.peek().kind == Tok.IDENT \
                and self.peek().text != "of":
            alias = self.next().text
        return ast.TableRef(name, alias)

    def parse_join_type(self):
        t = self.peek()
        if t.is_kw("join"):
            self.next()
            return "inner"
        if t.is_kw("inner"):
            self.next()
            self.expect_kw("join")
            return "inner"
        if t.is_kw("left"):
            self.next()
            self.accept_kw("outer")
            self.expect_kw("join")
            return "left"
        if t.is_kw("cross"):
            self.next()
            self.expect_kw("join")
            return "cross"
        if t.is_kw("right"):
            self.next()
            self.accept_kw("outer")
            self.expect_kw("join")
            return "right"
        if t.is_kw("full"):
            raise ParseError("FULL JOIN not supported yet")
        if t.kind == Tok.OP and t.text == ",":
            nxt = self.peek(1)
            # comma-join only when followed by a table name (not a
            # subquery); keyword-named tables ("date" in SSB) allowed
            if nxt.kind in (Tok.IDENT, Tok.KEYWORD):
                self.next()
                return "cross"
        return None

    # -- expressions -------------------------------------------------------
    def parse_expr(self, min_bp: int = 0) -> ast.Expr:
        left = self.parse_prefix()
        while True:
            t = self.peek()
            # postfix-ish constructs
            if t.is_kw("not") and self.peek(1).is_kw("between", "in", "like", "ilike"):
                if 35 < min_bp:
                    break
                self.next()
                left = self.parse_not_suffix(left, negated=True)
                continue
            if t.is_kw("between", "in"):
                if 35 < min_bp:
                    break
                left = self.parse_not_suffix(left, negated=False)
                continue
            if t.is_kw("is"):
                if 35 < min_bp:
                    break
                self.next()
                neg = self.accept_kw("not")
                if self.accept_kw("null"):
                    left = ast.IsNull(left, negated=neg)
                elif self.accept_kw("true"):
                    # IS TRUE never returns NULL: (x IS NOT NULL) AND x
                    cmp = ast.BinOp("and", ast.IsNull(left, negated=True),
                                    left)
                    left = ast.UnaryOp("not", cmp) if neg else cmp
                elif self.accept_kw("false"):
                    cmp = ast.BinOp("and", ast.IsNull(left, negated=True),
                                    ast.UnaryOp("not", left))
                    left = ast.UnaryOp("not", cmp) if neg else cmp
                elif self.accept_kw("distinct"):
                    # IS [NOT] DISTINCT FROM: null-safe comparison,
                    # desugared to a three-valued-logic-exact form that
                    # never yields NULL:
                    #   NOT DISTINCT = (a NULL AND b NULL)
                    #               OR (a NOT NULL AND b NOT NULL
                    #                   AND a = b)
                    if not self.accept_kw("from"):
                        raise ParseError(
                            f"expected FROM after IS DISTINCT at "
                            f"{self.peek()}")
                    rhs = self.parse_expr(36)
                    both_null = ast.BinOp(
                        "and", ast.IsNull(left),
                        ast.IsNull(rhs))
                    both_set_eq = ast.BinOp(
                        "and",
                        ast.BinOp("and",
                                  ast.IsNull(left, negated=True),
                                  ast.IsNull(rhs, negated=True)),
                        ast.BinOp("=", left, rhs))
                    not_distinct = ast.BinOp("or", both_null,
                                             both_set_eq)
                    # note the polarity: IS DISTINCT (neg=False)
                    # negates NOT-DISTINCT
                    left = not_distinct if neg \
                        else ast.UnaryOp("not", not_distinct)
                else:
                    raise ParseError(f"expected NULL/TRUE/FALSE/"
                                     f"DISTINCT FROM after IS at "
                                     f"{self.peek()}")
                continue
            if t.kind == Tok.OP and t.text == "[":
                # subscript binds tightest of the postfix operators
                if 85 < min_bp:
                    break
                self.next()
                idx = self.parse_expr()
                self.expect_op("]")
                left = ast.Subscript(left, idx)
                continue
            op = None
            if t.kind == Tok.OP and t.text in PRECEDENCE:
                op = t.text
            elif t.is_kw("and", "or", "like", "ilike"):
                op = t.text
            if op is None:
                break
            bp = PRECEDENCE[op]
            if bp < min_bp:
                break
            self.next()
            if op == "::":
                left = ast.Cast(left, self.parse_type())
                continue
            right = self.parse_expr(bp + 1)
            left = ast.BinOp(op, left, right)
        return left

    def parse_not_suffix(self, left: ast.Expr, negated: bool) -> ast.Expr:
        if self.accept_kw("between"):
            lo = self.parse_expr(41)
            self.expect_kw("and")
            hi = self.parse_expr(41)
            return ast.Between(left, lo, hi, negated=negated)
        if self.accept_kw("in"):
            self.expect_op("(")
            if self.peek().is_kw("select", "with"):
                sub = self.parse_with() if self.peek().is_kw("with") \
                    else self.parse_select_stmt()
                self.expect_op(")")
                return ast.InSubquery(left, sub, negated=negated)
            items = [self.parse_expr()]
            while self.accept_op(","):
                items.append(self.parse_expr())
            self.expect_op(")")
            return ast.InList(left, items, negated=negated)
        if self.accept_kw("like") or self.accept_kw("ilike"):
            right = self.parse_expr(41)
            e = ast.BinOp("like", left, right)
            return ast.UnaryOp("not", e) if negated else e
        raise ParseError(f"unexpected {self.peek()}")

    def parse_prefix(self) -> ast.Expr:
        t = self.next()
        if t.kind == Tok.NUMBER:
            txt = t.text
            if "." in txt or "e" in txt or "E" in txt:
                # decimal literal: keep string for scale-aware binding
                return ast.Literal(txt, None)
            return ast.Literal(int(txt), None)
        if t.kind == Tok.STRING:
            return ast.Literal(t.text, None)
        if t.is_kw("true"):
            return ast.Literal(True, BOOL)
        if t.is_kw("false"):
            return ast.Literal(False, BOOL)
        if t.is_kw("null"):
            return ast.Literal(None, None)
        if t.is_kw("date"):
            if self.peek().kind == Tok.STRING:
                return ast.Literal(self.next().text, DATE)
            return ast.ColumnRef("date")
        if t.is_kw("timestamp"):
            if self.peek().kind == Tok.STRING:
                return ast.Literal(self.next().text, TIMESTAMP)
            return ast.ColumnRef("timestamp")
        if t.is_kw("interval"):
            if self.peek().kind == Tok.STRING:
                return ast.Literal(self.next().text, INTERVAL)
            return ast.ColumnRef("interval")
        if t.is_kw("not"):
            return ast.UnaryOp("not", self.parse_expr(25))
        if t.kind == Tok.OP and t.text == "-":
            # pg precedence: unary minus binds TIGHTER than ^
            # (-2 ^ 2 is (-2)^2 = 4), so the operand stops before ^
            return ast.UnaryOp("-", self.parse_expr(75))
        if t.kind == Tok.OP and t.text == "+":
            return self.parse_expr(75)
        if t.kind == Tok.OP and t.text == "(":
            if self.peek().is_kw("select", "with"):
                sub = self.parse_with() if self.peek().is_kw("with") \
                    else self.parse_select_stmt()
                self.expect_op(")")
                return ast.Subquery(sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.is_kw("exists"):
            self.expect_op("(")
            sub = self.parse_with() if self.peek().is_kw("with") \
                else self.parse_select_stmt()
            self.expect_op(")")
            return ast.Exists(sub)
        if t.is_kw("case"):
            whens = []
            operand = None
            if not self.peek().is_kw("when"):
                operand = self.parse_expr()
            while self.accept_kw("when"):
                cond = self.parse_expr()
                if operand is not None:
                    cond = ast.BinOp("=", operand, cond)
                self.expect_kw("then")
                val = self.parse_expr()
                whens.append((cond, val))
            else_ = None
            if self.accept_kw("else"):
                else_ = self.parse_expr()
            self.expect_kw("end")
            return ast.Case(whens, else_)
        if t.is_kw("cast"):
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("as")
            ty = self.parse_type()
            self.expect_op(")")
            return ast.Cast(e, ty)
        if t.is_kw("coalesce"):
            self.expect_op("(")
            args = [self.parse_expr()]
            while self.accept_op(","):
                args.append(self.parse_expr())
            self.expect_op(")")
            return ast.FuncCall("coalesce", args)
        if t.is_kw("extract"):
            self.expect_op("(")
            if self.peek().kind == Tok.STRING:
                part = self.next().text  # extract('year' from x)
            else:
                part = self.expect_ident()
            self.expect_kw("from")
            e = self.parse_expr()
            self.expect_op(")")
            return ast.Extract(part, e)
        if t.kind in (Tok.IDENT, Tok.KEYWORD) and t.text == "position" \
                and self.peek().kind == Tok.OP \
                and self.peek().text == "(":
            # position(needle IN haystack) -> strpos(haystack, needle);
            # the comma form position(haystack, needle) stays a plain call
            self.expect_op("(")
            first = self.parse_expr(min_bp=36)  # stop before IN (bp 35)
            if self.accept_kw("in"):
                hay = self.parse_expr()
                self.expect_op(")")
                return ast.FuncCall("strpos", [hay, first])
            args = [first]
            while self.accept_op(","):
                args.append(self.parse_expr())
            self.expect_op(")")
            return ast.FuncCall("position", args)
        if t.is_kw("substring"):
            self.expect_op("(")
            e = self.parse_expr()
            if self.accept_op(","):
                # pg's comma form: substring(s, start [, length])
                start = self.parse_expr()
                length = None
                if self.accept_op(","):
                    length = self.parse_expr()
                self.expect_op(")")
                return ast.Substring(e, start, length)
            self.expect_kw("from")
            start = self.parse_expr()
            length = None
            if self.accept_kw("for"):
                length = self.parse_expr()
            elif self.accept_op(","):
                start2 = start
                length = self.parse_expr()
                start = start2
            self.expect_op(")")
            return ast.Substring(e, start, length)
        if t.kind in (Tok.IDENT, Tok.KEYWORD):
            name = t.text
            if name.lower() == "array" and self.peek().kind == Tok.OP \
                    and self.peek().text == "[":
                self.next()
                items = []
                if not (self.peek().kind == Tok.OP
                        and self.peek().text == "]"):
                    items.append(self.parse_expr())
                    while self.accept_op(","):
                        items.append(self.parse_expr())
                self.expect_op("]")
                return ast.ArrayLit(items)
            # parenless special-syntax functions (SQL standard)
            if name in ("current_date", "current_timestamp") and not (
                    self.peek().kind == Tok.OP and self.peek().text == "("):
                return ast.FuncCall(name, [])
            # function call?
            if self.peek().kind == Tok.OP and self.peek().text == "(":
                self.next()
                if self.accept_op("*"):
                    self.expect_op(")")
                    fc = ast.FuncCall(name, [], star=True)
                    if self.peek().is_kw("over"):
                        return self.parse_over(fc)
                    return fc
                distinct = self.accept_kw("distinct")
                args = []
                if not self.accept_op(")"):
                    args.append(self.parse_expr())
                    while self.accept_op(","):
                        args.append(self.parse_expr())
                    self.expect_op(")")
                fc = ast.FuncCall(name, args, distinct=distinct)
                if self.peek().is_kw("over"):
                    return self.parse_over(fc)
                return fc
            # qualified column a.b
            if self.peek().kind == Tok.OP and self.peek().text == ".":
                self.next()
                col = self.expect_ident()
                return ast.ColumnRef(col, table=name)
            return ast.ColumnRef(name)
        raise ParseError(f"unexpected token {t}")

    def parse_over(self, fc: ast.FuncCall) -> ast.WindowCall:
        """OVER ( [PARTITION BY e,...] [ORDER BY e [ASC|DESC],...] )."""
        self.expect_kw("over")
        self.expect_op("(")
        parts: list[ast.Expr] = []
        orders: list[ast.OrderItem] = []
        if self.accept_kw("partition"):
            self.expect_kw("by")
            parts.append(self.parse_expr())
            while self.accept_op(","):
                parts.append(self.parse_expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.parse_expr()
                desc = False
                if self.accept_kw("desc"):
                    desc = True
                else:
                    self.accept_kw("asc")
                orders.append(ast.OrderItem(e, desc))
                if not self.accept_op(","):
                    break
        if self.peek().is_kw("rows", "range", "groups"):
            raise ParseError("explicit window frames not supported")
        self.expect_op(")")
        if fc.distinct:
            raise ParseError("DISTINCT in window functions not supported")
        return ast.WindowCall(fc.name, fc.args, fc.star, parts, orders)

    def parse_type(self) -> SQLType:
        t = self.next()
        name = t.text.lower()
        if name == "double" and self.peek().kind == Tok.IDENT \
                and self.peek().text == "precision":
            self.next()
            return FLOAT8
        if name in ("decimal", "numeric"):
            prec, scale = 19, 2
            if self.accept_op("("):
                prec = int(self.next().text)
                if self.accept_op(","):
                    scale = int(self.next().text)
                self.expect_op(")")
            return SQLType.decimal(prec, scale)
        if name in TYPE_NAMES:
            ty = TYPE_NAMES[name]
            if self.accept_op("("):  # varchar(n) etc. — length ignored
                self.next()
                self.expect_op(")")
            if self.accept_op("["):  # INT[] / TEXT[] array types
                self.expect_op("]")
                ty = SQLType.array(ty)
            return ty
        raise ParseError(f"unknown type {name!r}")

    # -- DDL/DML -----------------------------------------------------------
    def parse_create(self) -> ast.Statement:
        self.expect_kw("create")
        if self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                and self.peek().text == "changefeed":
            self.next()
            self.expect_kw("for")
            table = self.expect_ident()
            if not (self.peek().kind in (Tok.IDENT, Tok.KEYWORD)
                    and self.peek().text == "into"):
                raise ParseError("expected INTO '<sink>'")
            self.next()
            t = self.next()
            if t.kind != Tok.STRING:
                raise ParseError("sink must be a string literal")
            return ast.CreateChangefeed(table, t.text)
        unique = False
        if self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                and self.peek().text == "unique":
            self.next()
            unique = True
        if self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                and self.peek().text == "index":
            self.next()
            if_not_exists = False
            if self.accept_kw("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                if_not_exists = True
            iname = self.expect_ident()
            self.expect_kw("on")
            table = self.expect_ident()
            self.expect_op("(")
            cols = [self.expect_ident()]
            while self.accept_op(","):
                cols.append(self.expect_ident())
            self.expect_op(")")
            return ast.CreateIndex(iname, table, cols, unique,
                                   if_not_exists)
        if unique:
            raise ParseError("expected INDEX after CREATE UNIQUE")
        if self.peek().kind == Tok.IDENT and self.peek().text == "view":
            self.next()
            if_not_exists = False
            if self.accept_kw("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                if_not_exists = True
            vname = self.expect_ident()
            cols = None
            if self.accept_op("("):
                cols = [self.expect_ident()]
                while self.accept_op(","):
                    cols.append(self.expect_ident())
                self.expect_op(")")
            self.expect_kw("as")
            body_start = self.peek().pos
            sel = self.parse_select_stmt()
            body = self.sql[body_start:].strip().rstrip(";").strip()
            return ast.CreateView(vname, cols, sel, body,
                                  if_not_exists)
        if self.peek().kind == Tok.IDENT \
                and self.peek().text == "sequence":
            self.next()
            if_not_exists = False
            if self.accept_kw("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                if_not_exists = True
            sname = self.expect_ident()
            start, increment = 1, 1
            while self.peek().kind == Tok.IDENT and \
                    self.peek().text in ("start", "increment"):
                which = self.next().text
                self.accept_kw("with")
                if self.peek().kind == Tok.IDENT \
                        and self.peek().text == "by":
                    self.next()
                t = self.next()
                if t.kind != Tok.NUMBER:
                    raise ParseError(f"expected number after {which}")
                if which == "start":
                    start = int(t.text)
                else:
                    increment = int(t.text)
            return ast.CreateSequence(sname, start, increment,
                                      if_not_exists)
        self.expect_kw("table")
        if_not_exists = False
        if self.accept_kw("if"):
            self.expect_kw("not")
            self.expect_kw("exists")
            if_not_exists = True
        name = self.expect_ident()
        self.expect_op("(")
        cols: list[ast.ColumnDef] = []
        pk: list[str] = []
        checks: list = []
        fks: list = []
        uniques: list = []  # table-level UNIQUE (cols)

        def _is_word(w: str) -> bool:
            return self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                and self.peek().text == w

        def parse_check():
            self.expect_op("(")
            start = self.peek().pos
            e = self.parse_expr()
            end = self.peek().pos
            self.expect_op(")")
            text = self.sql[start:end].strip()
            checks.append((f"check_{name}_{len(checks) + 1}", e, text))

        def parse_references(local_cols: list[str]):
            rt = self.expect_ident()
            rcols = []
            if self.accept_op("("):
                rcols.append(self.expect_ident())
                while self.accept_op(","):
                    rcols.append(self.expect_ident())
                self.expect_op(")")
            fks.append((f"fk_{name}_{len(fks) + 1}", local_cols, rt,
                        rcols))

        while True:
            if self.accept_kw("primary"):
                self.expect_kw("key")
                self.expect_op("(")
                pk.append(self.expect_ident())
                while self.accept_op(","):
                    pk.append(self.expect_ident())
                self.expect_op(")")
            elif _is_word("check"):
                self.next()
                parse_check()
            elif _is_word("foreign"):
                self.next()
                self.expect_kw("key")
                self.expect_op("(")
                lcols = [self.expect_ident()]
                while self.accept_op(","):
                    lcols.append(self.expect_ident())
                self.expect_op(")")
                if not _is_word("references"):
                    raise ParseError("expected REFERENCES")
                self.next()
                parse_references(lcols)
            elif _is_word("unique") and self.peek(1).kind == Tok.OP \
                    and self.peek(1).text == "(":
                self.next()
                self.expect_op("(")
                ucols = [self.expect_ident()]
                while self.accept_op(","):
                    ucols.append(self.expect_ident())
                self.expect_op(")")
                uniques.append(ucols)
            else:
                cname = self.expect_ident()
                ctype = self.parse_type()
                nullable = True
                primary = False
                unique = False
                default = None
                while True:
                    if self.accept_kw("not"):
                        self.expect_kw("null")
                        nullable = False
                    elif self.accept_kw("null"):
                        pass
                    elif self.accept_kw("primary"):
                        self.expect_kw("key")
                        primary = True
                        nullable = False
                    elif self.accept_kw("default"):
                        default = self.parse_expr()
                    elif _is_word("check"):
                        self.next()
                        parse_check()
                    elif _is_word("references"):
                        self.next()
                        parse_references([cname])
                    elif _is_word("unique"):
                        self.next()
                        unique = True
                    else:
                        break
                cols.append(ast.ColumnDef(cname, ctype, nullable,
                                          primary, unique,
                                          default=default))
                if primary:
                    pk.append(cname)
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return ast.CreateTable(name, cols, pk, if_not_exists,
                               checks=checks, foreign_keys=fks,
                               uniques=uniques)

    def parse_alter(self) -> ast.Statement:
        self.expect_kw("alter")
        self.expect_kw("table")
        table = self.expect_ident()
        if self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                and self.peek().text == "configure":
            self.next()
            if not (self.peek().kind in (Tok.IDENT, Tok.KEYWORD)
                    and self.peek().text == "zone"):
                raise ParseError("expected ZONE after CONFIGURE")
            self.next()
            if not (self.peek().kind in (Tok.IDENT, Tok.KEYWORD)
                    and self.peek().text == "using"):
                raise ParseError("expected USING")
            self.next()
            opts = {}
            while True:
                name = self.dotted_name()
                self.expect_op("=")
                t = self.next()
                if t.kind == Tok.NUMBER:
                    opts[name] = (float(t.text) if "." in t.text
                                  else int(t.text))
                else:
                    opts[name] = t.text
                if not self.accept_op(","):
                    break
            return ast.ConfigureZone(table, opts)
        if self.accept_kw("add"):
            self.accept_kw("column")
            cname = self.expect_ident()
            ctype = self.parse_type()
            default = None
            nullable = True
            while True:
                if self.accept_kw("default"):
                    default = self.parse_expr()
                elif self.accept_kw("not"):
                    self.expect_kw("null")
                    nullable = False
                elif self.accept_kw("null"):
                    pass
                else:
                    break
            return ast.AlterTable(
                table, add=ast.ColumnDef(cname, ctype, nullable),
                default=default)
        if self.accept_kw("drop"):
            self.accept_kw("column")
            return ast.AlterTable(table, drop=self.expect_ident())
        raise ParseError("expected ADD or DROP after ALTER TABLE")

    def parse_drop(self) -> ast.Statement:
        self.expect_kw("drop")
        if self.peek().kind in (Tok.IDENT, Tok.KEYWORD) \
                and self.peek().text == "index":
            self.next()
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            return ast.DropIndex(self.expect_ident(), if_exists)
        if self.peek().kind == Tok.IDENT and self.peek().text in (
                "view", "sequence"):
            kind = self.next().text
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            name = self.expect_ident()
            return (ast.DropView(name, if_exists) if kind == "view"
                    else ast.DropSequence(name, if_exists))
        self.expect_kw("table")
        if_exists = False
        if self.accept_kw("if"):
            self.expect_kw("exists")
            if_exists = True
        return ast.DropTable(self.expect_ident(), if_exists)

    def parse_insert(self, upsert: bool = False) -> ast.Statement:
        if upsert:
            self.expect_kw("upsert")
        else:
            self.expect_kw("insert")
        self.expect_kw("into")
        table = self.expect_ident()
        columns: list[str] = []
        if self.accept_op("("):
            columns.append(self.expect_ident())
            while self.accept_op(","):
                columns.append(self.expect_ident())
            self.expect_op(")")
        if self.peek().is_kw("select"):
            return ast.Insert(table, columns,
                              select=self.parse_select_stmt(),
                              upsert=upsert)
        self.expect_kw("values")
        rows: list[list[ast.Expr]] = []
        while True:
            self.expect_op("(")
            row = [self.parse_expr()]
            while self.accept_op(","):
                row.append(self.parse_expr())
            self.expect_op(")")
            rows.append(row)
            if not self.accept_op(","):
                break
        return ast.Insert(table, columns, rows=rows,
                          upsert=upsert)

    def parse_update(self) -> ast.Statement:
        self.expect_kw("update")
        table = self.expect_ident()
        self.expect_kw("set")
        assigns: list[tuple[str, ast.Expr]] = []
        while True:
            col = self.expect_ident()
            self.expect_op("=")
            assigns.append((col, self.parse_expr()))
            if not self.accept_op(","):
                break
        where = self.parse_expr() if self.accept_kw("where") else None
        return ast.Update(table, assigns, where)

    def parse_delete(self) -> ast.Statement:
        self.expect_kw("delete")
        self.expect_kw("from")
        table = self.expect_ident()
        where = self.parse_expr() if self.accept_kw("where") else None
        return ast.Delete(table, where)

    def parse_set(self) -> ast.Statement:
        self.expect_kw("set")
        cluster = False
        if self.accept_kw("cluster"):
            self.expect_kw("setting")
            cluster = True
        name = self.dotted_name()
        if not self.accept_op("="):
            self.expect_kw("to")
        t = self.next()
        if t.kind == Tok.NUMBER:
            val: object = float(t.text) if "." in t.text else int(t.text)
        elif t.is_kw("true"):
            val = True
        elif t.is_kw("false"):
            val = False
        else:
            val = t.text
        return ast.SetVar(name, val, cluster)


def parse(sql: str) -> ast.Statement:
    p = Parser(sql)
    stmt = p.parse_statement()
    p.finish()
    return stmt


def parse_many(sql: str) -> list[ast.Statement]:
    p = Parser(sql)
    out = []
    while p.peek().kind != Tok.EOF:
        out.append(p.parse_statement())
        if not p.accept_op(";"):
            break
    if p.peek().kind != Tok.EOF:
        raise ParseError(f"trailing tokens at {p.peek()}")
    return out
