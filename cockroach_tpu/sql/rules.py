"""Normalization rule plane: match/apply rewrites with a trace.

The analogue of the reference's optgen-generated normalization rules
(pkg/sql/opt/norm/rules/*.opt, applied by the norm factory during
memo construction) — asked for in rounds 3 AND 4. The frame:

- a ``Rule`` matches one plan-node shape and returns a replacement
  (or None); the engine runs all rules bottom-up to a fixpoint;
- ``GlobalRule`` hosts the whole-tree passes that already earned
  their keep (build-side expression pushdown, scan column pruning)
  so every rewrite — local or global — lands in ONE trace;
- every firing is recorded as (rule, detail) and surfaced by
  EXPLAIN (``rules: ...`` lines), the way the reference's
  opttester shows norm rule applications.

Constant folding happens at BIND time (builtins._fold and the
binder's arithmetic folds — the reference folds in norm the same
way); the binder counts its folds and the planner reports them into
this trace so the whole normalization story reads in one place.
Decorrelation likewise runs at the AST layer (sql/decorrelate.py)
and reports its firings here via the engine.

Exploration (join orders, index-aware scan costs) stays in
sql/memo.py — the reference splits norm/xform the same way.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import plan as P
from .bound import BBin, BConst
from .types import BOOL


@dataclass
class Firing:
    rule: str
    detail: str


@dataclass
class RuleTrace:
    firings: list = field(default_factory=list)

    def fire(self, rule: str, detail: str = "") -> None:
        self.firings.append(Firing(rule, detail))

    def summary(self) -> list[str]:
        """One line per rule: 'rule ×N (first detail)'."""
        by: dict[str, list] = {}
        for f in self.firings:
            by.setdefault(f.rule, []).append(f.detail)
        out = []
        for rule, details in by.items():
            d = next((x for x in details if x), "")
            n = f" ×{len(details)}" if len(details) > 1 else ""
            out.append(f"{rule}{n}" + (f" ({d})" if d else ""))
        return out


class Rule:
    """One local rewrite: apply(node) -> replacement | None."""

    name = "?"

    def apply(self, node: P.PlanNode, trace: RuleTrace):
        raise NotImplementedError


class MergeFilters(Rule):
    """Filter(Filter(x, p1), p2) => Filter(x, p1 AND p2) — one
    selection-mask pass instead of two (the reference's
    MergeSelects)."""

    name = "merge_filters"

    def apply(self, node, trace):
        if isinstance(node, P.Filter) and \
                isinstance(node.child, P.Filter):
            inner = node.child
            trace.fire(self.name)
            return P.Filter(inner.child,
                            BBin("and", inner.pred, node.pred, BOOL))
        return None


class DropTrueFilter(Rule):
    """Filter(x, TRUE) => x (EliminateSelect)."""

    name = "drop_true_filter"

    def apply(self, node, trace):
        if isinstance(node, P.Filter) and \
                isinstance(node.pred, BConst) and \
                node.pred.value is True:
            trace.fire(self.name)
            return node.child
        return None


class PushFilterIntoScan(Rule):
    """Filter(Scan) => Scan[filter AND pred] — the selection fuses
    into the MVCC visibility mask instead of running as a separate
    batch pass (PushSelectIntoScan; on TPU this keeps the whole
    predicate inside the one fused scan kernel)."""

    name = "push_filter_into_scan"

    def apply(self, node, trace):
        if isinstance(node, P.Filter) and \
                isinstance(node.child, P.Scan):
            sc = node.child
            trace.fire(self.name, sc.alias)
            merged = node.pred if sc.filter is None else \
                BBin("and", sc.filter, node.pred, BOOL)
            return P.Scan(sc.table, sc.alias, dict(sc.columns),
                          merged, list(sc.computed), sc.narrowed)
        return None


class CollapseProjects(Rule):
    """Project(Project(x)) => Project(x) with inner expressions
    substituted into the outer items (MergeProjects). Outer items
    that are plain column refs of inner items inline fully; anything
    else substitutes per-reference."""

    name = "collapse_projects"

    def apply(self, node, trace):
        if not (isinstance(node, P.Project)
                and isinstance(node.child, P.Project)):
            return None
        from .bound import BCol
        inner = {n: e for n, e in node.child.items}

        def subst(e):
            import copy

            from .bound import (BBetween, BCase, BCast, BCoalesce,
                                BDictGather, BDictLookup, BDictRemap,
                                BExtract, BFunc, BInList, BIsNull,
                                BUnary)
            if e is None:
                return None
            if isinstance(e, BCol):
                return inner.get(e.name, e)
            e2 = copy.copy(e)
            if isinstance(e2, BBin):
                e2.left = subst(e2.left)
                e2.right = subst(e2.right)
            elif isinstance(e2, BUnary):
                e2.operand = subst(e2.operand)
            elif isinstance(e2, BBetween):
                e2.expr = subst(e2.expr)
                e2.lo = subst(e2.lo)
                e2.hi = subst(e2.hi)
            elif isinstance(e2, (BInList, BIsNull, BDictLookup,
                                 BDictRemap, BDictGather, BCast,
                                 BExtract)):
                e2.expr = subst(e2.expr)
            elif isinstance(e2, (BFunc, BCoalesce)):
                e2.args = [subst(a) for a in e2.args]
            elif isinstance(e2, BCase):
                e2.whens = [(subst(c), subst(v)) for c, v in e2.whens]
                if e2.else_ is not None:
                    e2.else_ = subst(e2.else_)
            return e2

        # aggregate/window refs cannot cross a project boundary here
        from .bound import BAggRef, BWinRef, walk
        for _, e in node.items:
            for x in walk(e):
                if isinstance(x, (BAggRef, BWinRef)):
                    return None
        trace.fire(self.name)
        return P.Project(node.child.child,
                         [(n, subst(e)) for n, e in node.items])


def _split_disjuncts(e):
    if isinstance(e, BBin) and e.op == "or":
        return _split_disjuncts(e.left) + _split_disjuncts(e.right)
    return [e]


def _split_conjuncts(e):
    if isinstance(e, BBin) and e.op == "and":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _or_all(parts):
    out = parts[0]
    for p in parts[1:]:
        out = BBin("or", out, p, BOOL)
    return out


def _and_all(parts):
    out = parts[0]
    for p in parts[1:]:
        out = BBin("and", out, p, BOOL)
    return out


class DeriveOrSideFilters(Rule):
    """A disjunction of conjunctions above a join implies a per-table
    filter: ``(S1∧R1) ∨ (S2∧R2) ⇒ (S1∨S2)`` on the table S's
    conjuncts reference — sound whenever every branch contributes a
    conjunct for that table. TPC-H q19's three-way OR of
    brand/container/quantity groups is the canonical case: the
    derived part-side OR prunes the build before the join and the
    derived lineitem-side quantity OR shrinks the probe, instead of
    evaluating the whole disjunction at post-join width (the
    reference derives the same constraints in
    opt/idxconstraint + norm's SimplifySelectFilters).

    Inner joins only: under an outer join a pushed build filter
    null-extends rows whose actual values an IS NULL branch would
    then misjudge."""

    name = "derive_or_side_filters"

    def apply(self, node, trace):
        if not isinstance(node, P.Filter) or \
                getattr(node, "_or_derived", False):
            return None
        if not isinstance(node.child, P.HashJoin):
            return None
        # all joins in the subtree must be inner, and scans are
        # collected by alias
        scans: dict[str, P.Scan] = {}
        ok = [True]

        def rec(n):
            if isinstance(n, P.Scan):
                scans[n.alias] = n
            elif isinstance(n, P.HashJoin):
                if n.join_type != "inner":
                    ok[0] = False
                rec(n.left)
                rec(n.right)
            elif getattr(n, "child", None) is not None:
                rec(n.child)
        rec(node.child)
        if not ok[0] or not scans:
            return None
        branches = _split_disjuncts(node.pred)
        if len(branches) < 2:
            return None
        from .bound import referenced_columns

        def alias_of(name):
            return name.split(".", 1)[0] if "." in name else None

        fired = False
        for alias, sc in scans.items():
            per_branch = []
            for b in branches:
                mine = [c for c in _split_conjuncts(b)
                        if referenced_columns(c)
                        and {alias_of(r)
                             for r in referenced_columns(c)}
                        == {alias}]
                if not mine:
                    per_branch = None
                    break
                per_branch.append(_and_all(mine))
            if not per_branch:
                continue
            derived = _or_all(per_branch)
            sc.filter = derived if sc.filter is None else \
                BBin("and", sc.filter, derived, BOOL)
            trace.fire(self.name, alias)
            fired = True
        if not fired:
            return None
        node._or_derived = True
        return node


LOCAL_RULES = [MergeFilters(), DropTrueFilter(), PushFilterIntoScan(),
               CollapseProjects(), DeriveOrSideFilters()]


def _children(n):
    if isinstance(n, P.HashJoin):
        return [("left", n.left), ("right", n.right)]
    c = getattr(n, "child", None)
    return [("child", c)] if c is not None else []


def normalize(root: P.PlanNode, trace: RuleTrace,
              max_passes: int = 8) -> P.PlanNode:
    """Bottom-up fixpoint over LOCAL_RULES, then the global passes
    (build-expression pushdown, column pruning) with their rewrites
    recorded in the same trace."""

    def rec(n):
        for attr, c in _children(n):
            setattr(n, attr, rec(c))
        for rule in LOCAL_RULES:
            r = rule.apply(n, trace)
            if r is not None:
                return rec(r)
        return n

    for _ in range(max_passes):
        before = len(trace.firings)
        root = rec(root)
        if len(trace.firings) == before:
            break

    from .pushdown import push_build_exprs
    pushed = push_build_exprs(root)
    for name in pushed or []:
        trace.fire("push_build_expr", name)
    dropped = P.prune_scan_columns_traced(root)
    for alias, ncols in dropped:
        trace.fire("prune_columns", f"{alias}: -{ncols}")
    return root
