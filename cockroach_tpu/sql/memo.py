"""Memoized cost-based join-order search.

The compact tier of the reference's optimizer (pkg/sql/opt:
optbuilder -> memo -> xform exploration -> costing,
opt/xform/optimizer.go:239). The full optgen rule engine is not
rebuilt; what IS rebuilt is the part that changes plans on this
engine: exploration of join orders with memoized per-group best
plans and a stats-driven cost model.

The physical join here is a broadcast-build device hash join over a
left-deep chain (ops/join.py; the build side is always a base-table
scan), so the search space is: choice of probe root x order of
builds, constrained to equi-connected prefixes. That is exactly the
classic System-R dynamic program — ``best[subset]`` memoizes the
cheapest plan producing each connected subset of tables (the memo
group), and larger groups are explored by extending smaller ones
(the xform step).

Cost model (relative weights tuned to the device execution profile):
  scan:   est_rows (post-filter, from stats selectivities)
  join:   BUILD_W * build_rows   (hash-table build / direct scatter)
        + PROBE_W * probe_rows   (gather per probe row)
        + OUT_W   * out_rows     (materialized join output)
  out_rows = probe_rows * build_rows * sel,
  sel      = product over key pairs of 1 / max(distinct_l, distinct_r)
(the standard independence estimate; distinct counts from ANALYZE).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

BUILD_W = 2.0
PROBE_W = 1.0
OUT_W = 0.5
# a duplicate-keyed build cannot take the one-scatter direct path:
# it falls to the while-loop hash build + K-slot probe gathers,
# measured ~100x the per-row cost of a unique direct build on the
# TPU (and minutes of XLA compile at 10^6 rows). Charging hash
# builds near their real weight steers the DP toward fact-table
# probe spines with unique dimension builds (q3: customer,orders,
# lineitem spec order would otherwise build on 540K dup-keyed
# lineitem rows instead of probing lineitem through unique orders)
HASH_BUILD_W = 100.0
# the device join expands duplicate-keyed builds by gathering K slots
# per probe, capped at MAX per-key duplicates = 32 (engine
# MAX_JOIN_EXPANSION). Stats give the AVERAGE multiplicity
# (rows/distinct); real key distributions are skewed, so builds whose
# average exceeds 32/SKEW_MARGIN are penalized — conservative: a
# falsely-penalized order merely yields a safer plan, while a
# falsely-allowed one fails at execution
SKEW_MARGIN = 4.0
MAX_BUILD_MULT = 32.0 / SKEW_MARGIN
MULT_PENALTY = 1e9


@dataclass
class GroupPlan:
    cost: float
    rows: float
    root: str
    order: list = field(default_factory=list)  # build aliases in order


@dataclass
class MemoResult:
    root: str
    order: list           # [alias, ...] build order
    cost: float
    rows: float
    groups: int           # memo groups materialized
    considered: int       # candidate plans costed


def search(aliases: list[str], scan_rows, join_info,
           scan_cost=None) -> MemoResult | None:
    """Find the cheapest connected left-deep join order.

    scan_rows(alias) -> estimated post-filter scan rows.
    scan_cost(alias) -> access-path-aware cost of producing those rows
    (planner._choose_access_paths: an index point/prefix lookup costs
    its matched rows, a full scan its post-filter rows) — this is
    where index selection is costed INSIDE the memo instead of beside
    it. Defaults to scan_rows.
    join_info(left_set, alias) -> (selectivity, build_multiplicity
    [, direct_eligible]) — build_multiplicity is the estimated
    duplicate rows per join key on the build side `alias` — or None
    when no equality condition connects `alias` to `left_set`
    (disconnected extensions are not explored — cartesian products
    are rejected by the planner anyway). direct_eligible (default
    True) reports whether the build's key columns admit the
    direct-address table (dense int span within the engine's slot
    caps); a unique build that CANNOT direct-address still pays the
    while-loop hash build, so it is charged HASH_BUILD_W (q9's memo
    otherwise picks a partsupp spine with a 1M-row hash build of
    lineitem — measured ~1s/exec in the while loop — over the
    lineitem spine with packed-direct dimension builds).

    Returns None when no fully connected order exists.
    """
    n = len(aliases)
    best: dict[frozenset, GroupPlan] = {}
    considered = 0
    for a in aliases:
        r = max(scan_rows(a), 1.0)
        c = max(scan_cost(a), 1.0) if scan_cost is not None else r
        best[frozenset([a])] = GroupPlan(cost=c, rows=r, root=a)
    for size in range(2, n + 1):
        for combo in itertools.combinations(aliases, size):
            s = frozenset(combo)
            champion = None
            for last in combo:
                rest = s - {last}
                b = best.get(rest)
                if b is None:
                    continue
                info = join_info(rest, last)
                if info is None:
                    continue
                sel, build_mult = info[0], info[1]
                direct_ok = info[2] if len(info) > 2 else True
                build = max(scan_rows(last), 1.0)
                out = max(b.rows * build * sel, 1.0)
                bw = (BUILD_W if build_mult <= 1.05 and direct_ok
                      else HASH_BUILD_W)
                cost = (b.cost + bw * build
                        + PROBE_W * b.rows + OUT_W * out)
                if build_mult > MAX_BUILD_MULT:
                    cost += MULT_PENALTY * build_mult
                considered += 1
                # the 2-table case is an exact tie under this model
                # (cost is symmetric in probe/build), so break ties
                # toward the smaller build: it caps hash-table HBM
                # and keeps the bigger side streamable as the probe
                key = (cost, build)
                if champion is None or key < champ_key:
                    champ_key = key
                    champion = GroupPlan(cost=cost, rows=out,
                                         root=b.root,
                                         order=b.order + [last])
            if champion is not None:
                best[s] = champion
    full = best.get(frozenset(aliases))
    if full is None:
        return None
    return MemoResult(root=full.root, order=full.order,
                      cost=full.cost, rows=full.rows,
                      groups=len(best), considered=considered)
