"""Build-side expression pushdown: evaluate join-build-only
subexpressions BEFORE the join, on the (small) build domain.

The reference's normalization rules push filters and projections
through joins (pkg/sql/opt/norm/rules/select.opt, prune_cols.opt).
On TPU the stakes are higher than CPU cycle counts: every payload
column an expression touches after the join is one probe-length
random GATHER (~44 ms per 8M rows measured on v5e), while the same
expression computed on the build side costs a build-length
elementwise pass — and a BOOL result packs into the direct join's
three-state table (ops/join.py), so the whole dimension predicate
rides the join's ONE gather.

TPC-H Q14's `p_type LIKE 'PROMO%'`, Q19's brand/container tests and
every SSB dimension filter are exactly this shape.

The pass runs after planning, before column pruning: BOOL-typed
maximal subtrees whose column refs all come from one hash-join build
scan are replaced by a reference to a computed build column, then
payload columns nothing references anymore are dropped (often the
original dictionary column itself — its probe gather disappears)."""

from __future__ import annotations

from . import plan
from .bound import (BAggRef, BCol, BConst, BExpr, BWinRef,
                    referenced_columns, walk)
from .types import Family


def _expr_key(e: BExpr) -> str:
    """Structural dedup key. repr() alone is unsafe: numpy summarizes
    arrays >1000 elements ('[False False ... False]'), so two distinct
    dictionary LUTs could collide — include a digest of every table's
    full contents."""
    import hashlib
    h = hashlib.sha256(repr(e).encode())
    for x in walk(e):
        t = getattr(x, "table", None)
        if t is not None and hasattr(t, "tobytes"):
            h.update(t.tobytes())
        elif isinstance(t, (list, tuple)):
            h.update(repr(t).encode())
    return h.hexdigest()


def _rebuild(e, f):
    """Rebuild a bound expr with f applied to child expressions."""
    import dataclasses
    if not dataclasses.is_dataclass(e):
        return e
    changes = {}
    for fld in dataclasses.fields(e):
        v = getattr(e, fld.name)
        if isinstance(v, BExpr):
            nv = f(v)
            if nv is not v:
                changes[fld.name] = nv
        elif isinstance(v, list) and v and \
                isinstance(v[0], tuple) and len(v[0]) == 2 and \
                isinstance(v[0][0], BExpr):
            nv = [(f(a), f(b)) for a, b in v]
            changes[fld.name] = nv
        elif isinstance(v, list) and v and isinstance(v[0], BExpr):
            changes[fld.name] = [f(x) for x in v]
    return dataclasses.replace(e, **changes) if changes else e


def push_build_exprs(root: plan.PlanNode) -> list:
    """In-place pass over a plan spine (see module doc). Returns the
    names of the pushed computed columns (rule-trace fodder,
    sql/rules.py)."""
    joins: list = []

    def collect(n):
        if n is None or isinstance(n, plan.Scan):
            return
        if isinstance(n, plan.HashJoin):
            # inner joins only: a LEFT join NULL-extends build columns
            # for unmatched probe rows, and a pushed expression (e.g.
            # coalesce) would wrongly see build-side values instead of
            # those NULLs
            if isinstance(n.right, plan.Scan) and \
                    n.join_type == "inner":
                joins.append(n)
            collect(n.left)
            collect(n.right)
            return
        collect(getattr(n, "child", None))

    collect(root)
    if not joins:
        return []
    by_alias = {}
    for j in joins:
        cols = set(j.payload) | set(j.right.columns) | \
            {n for n, _ in j.right.computed}
        by_alias[j.right.alias] = (j, cols)
    counter = [0]
    created: dict = {}

    def try_push(e):
        if isinstance(e, (BCol, BConst)) or \
                getattr(e, "type", None) is None or \
                e.type.family != Family.BOOL:
            return None
        refs = referenced_columns(e)
        if not refs:
            return None
        if any(isinstance(x, (BAggRef, BWinRef)) for x in walk(e)):
            return None
        for alias, (j, cols) in by_alias.items():
            if refs <= cols:
                key = (alias, _expr_key(e))
                name = created.get(key)
                if name is None:
                    name = f"{alias}.__push{counter[0]}"
                    counter[0] += 1
                    created[key] = name
                    j.right.computed.append((name, e))
                    j.payload.append(name)
                    j.pack_payload.append(name)
                return BCol(name, e.type)
        return None

    def rewrite(e):
        if e is None or not isinstance(e, BExpr):
            return e
        r = try_push(e)
        if r is not None:
            return r
        return _rebuild(e, rewrite)

    has_window = False

    def apply(n):
        nonlocal has_window
        if n is None:
            return
        if isinstance(n, plan.Scan):
            return
        if isinstance(n, plan.HashJoin):
            apply(n.left)
            apply(n.right)
            return
        if isinstance(n, plan.Filter):
            n.pred = rewrite(n.pred)
        elif isinstance(n, plan.Project):
            n.items = [(nm, rewrite(e)) for nm, e in n.items]
        elif isinstance(n, plan.Aggregate):
            n.group_by = [(nm, rewrite(e)) for nm, e in n.group_by]
            for a in n.aggs:
                if a.arg is not None:
                    a.arg = rewrite(a.arg)
            if n.having is not None:
                n.having = rewrite(n.having)
            n.items = [(nm, rewrite(e)) for nm, e in n.items]
        elif isinstance(n, plan.Window):
            has_window = True
        apply(getattr(n, "child", None))

    apply(root)
    if not created:
        return []
    if has_window:
        return []  # window specs not rewritten: keep payloads untouched

    # drop payload columns no STRICT ancestor references anymore
    # (their probe gathers disappear with them). A join's own keys
    # read the build batch directly, and the build scan's computed
    # exprs resolve below the join — neither is a payload use; only
    # nodes ABOVE the join on the probe spine are.
    def node_refs(n) -> set:
        out: set = set()
        if isinstance(n, plan.Filter):
            out |= referenced_columns(n.pred)
        elif isinstance(n, plan.Project):
            for _, e in n.items:
                out |= referenced_columns(e)
        elif isinstance(n, plan.Aggregate):
            for _, e in n.group_by:
                out |= referenced_columns(e)
            for a in n.aggs:
                if a.arg is not None:
                    out |= referenced_columns(a.arg)
            if n.having is not None:
                out |= referenced_columns(n.having)
            for _, e in n.items:
                out |= referenced_columns(e)
        elif isinstance(n, plan.HashJoin):
            out |= set(n.left_keys)   # probe keys may come from a
            # lower join's payload; right keys read its own build
        return out

    spine = []
    n = root
    while n is not None and not isinstance(n, plan.Scan):
        spine.append(n)
        n = n.left if isinstance(n, plan.HashJoin) \
            else getattr(n, "child", None)
    above: set = set()
    for n in spine:
        if isinstance(n, plan.HashJoin) and n in joins:
            n.payload = [p for p in n.payload if p in above]
            n.pack_payload = [p for p in n.pack_payload
                              if p in n.payload]
        above |= node_refs(n)
    return sorted(created.values())
