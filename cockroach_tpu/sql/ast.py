"""AST nodes (the analogue of pkg/sql/sem/tree)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .types import SQLType


class Expr:
    pass


@dataclass
class Literal(Expr):
    value: object  # python int/float/str/bool/None
    type_hint: Optional[SQLType] = None

    def __repr__(self):
        return f"Lit({self.value!r})"


@dataclass
class ColumnRef(Expr):
    name: str
    table: Optional[str] = None  # qualifier

    def __repr__(self):
        return f"Col({self.table + '.' if self.table else ''}{self.name})"


@dataclass
class BinOp(Expr):
    op: str  # + - * / % = != < <= > >= and or || like
    left: Expr
    right: Expr


@dataclass
class UnaryOp(Expr):
    op: str  # - not
    operand: Expr


@dataclass
class Between(Expr):
    expr: Expr
    lo: Expr
    hi: Expr
    negated: bool = False


@dataclass
class InList(Expr):
    expr: Expr
    items: list[Expr]
    negated: bool = False


@dataclass
class IsNull(Expr):
    expr: Expr
    negated: bool = False


@dataclass
class Case(Expr):
    whens: list[tuple[Expr, Expr]]
    else_: Optional[Expr] = None


@dataclass
class Cast(Expr):
    expr: Expr
    to: SQLType


@dataclass
class Subscript(Expr):
    """``arr[i]`` — 1-based array element access (pg semantics)."""
    expr: Expr
    index: Expr


@dataclass
class ArrayLit(Expr):
    """``ARRAY[e1, e2, ...]`` constructor."""
    items: list[Expr]


@dataclass
class FuncCall(Expr):
    name: str  # lowercased
    args: list[Expr]
    star: bool = False  # count(*)
    distinct: bool = False


@dataclass
class Extract(Expr):
    part: str  # year/month/day...
    expr: Expr


@dataclass
class WindowCall(Expr):
    """f(args) OVER (PARTITION BY ... ORDER BY ...)."""
    func: str
    args: list[Expr] = field(default_factory=list)
    star: bool = False
    partition_by: list[Expr] = field(default_factory=list)
    order_by: list["OrderItem"] = field(default_factory=list)


@dataclass
class Subquery(Expr):
    """Scalar subquery: (SELECT one column, at most one row). Executed
    before the main statement and inlined as a constant (the
    reference's planTop subquery execution, sql/subquery.go)."""
    select: "Select" = None


@dataclass
class Exists(Expr):
    """EXISTS (SELECT ...) — true iff the subquery returns any row."""
    select: "Select" = None


@dataclass
class InSubquery(Expr):
    """x IN (SELECT ...) — membership against a one-column subquery."""
    expr: Expr = None
    select: "Select" = None
    negated: bool = False


@dataclass
class Substring(Expr):
    expr: Expr
    start: Expr
    length: Optional[Expr] = None


# ---------------------------------------------------------------------------
# statements
# ---------------------------------------------------------------------------

class Statement:
    pass


@dataclass
class TableRef:
    name: str
    alias: Optional[str] = None
    # derived table: FROM (SELECT ...) alias — materialized before
    # planning like a single-use CTE; name is synthesized
    subquery: Optional["Select"] = None


@dataclass
class JoinClause:
    table: TableRef
    join_type: str  # inner/left/right/semi/anti/cross
    on: Optional[Expr] = None


@dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None
    star: bool = False


@dataclass
class OrderItem:
    expr: Expr
    desc: bool = False
    # None = pg default (NULLS LAST asc / NULLS FIRST desc)
    nulls_first: Optional[bool] = None


@dataclass
class Select(Statement):
    items: list[SelectItem] = field(default_factory=list)
    table: Optional[TableRef] = None
    joins: list[JoinClause] = field(default_factory=list)
    where: Optional[Expr] = None
    group_by: list[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    distinct: bool = False
    # WITH name [(col,...)] AS (SELECT ...) — non-recursive CTEs,
    # materialized in order before the main query
    ctes: list[tuple] = field(default_factory=list)  # (name, cols|None, Select)
    # AS OF SYSTEM TIME <expr>: historical read timestamp (CRDB's
    # time-travel queries; served by MVCC visibility at that ts)
    as_of: Optional[Expr] = None


@dataclass
class SetOp(Statement):
    """UNION / INTERSECT / EXCEPT [ALL]; ORDER BY/LIMIT hoisted from
    the last branch apply to the combined result (pg grammar)."""
    op: str  # union | intersect | except
    all: bool
    left: Statement  # Select or SetOp
    right: Statement
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    ctes: list[tuple] = field(default_factory=list)  # WITH over a set op


@dataclass
class ColumnDef:
    name: str
    type: SQLType
    nullable: bool = True
    primary: bool = False
    unique: bool = False  # column UNIQUE -> auto unique index
    default: object = None  # DEFAULT expr (unbound AST)


@dataclass
class CreateTable(Statement):
    name: str
    columns: list[ColumnDef]
    primary_key: list[str]
    if_not_exists: bool = False
    # CHECK constraints: (name, bound-later Expr, source sql text)
    checks: list = field(default_factory=list)
    # FOREIGN KEYs (RESTRICT semantics):
    # (name, [cols], ref_table, [ref_cols])
    foreign_keys: list = field(default_factory=list)
    # table-level UNIQUE (cols) -> auto unique index
    uniques: list = field(default_factory=list)


@dataclass
class DropTable(Statement):
    name: str
    if_exists: bool = False


@dataclass
class CreateIndex(Statement):
    """CREATE [UNIQUE] INDEX <name> ON <table> (cols...). Unique
    indexes write KV entries at /Table/<tid>/<index_id>/<vals> so
    concurrent violations conflict in the KV plane, like the
    reference's index rows (pkg/sql/rowenc/index_encoding.go)."""
    name: str
    table: str
    columns: list[str] = field(default_factory=list)
    unique: bool = False
    if_not_exists: bool = False


@dataclass
class DropIndex(Statement):
    name: str
    if_exists: bool = False


@dataclass
class ShowIndexes(Statement):
    """SHOW INDEXES FROM <table>."""
    table: str


@dataclass
class ShowColumns(Statement):
    """SHOW COLUMNS FROM <table>."""
    table: str


@dataclass
class CreateView(Statement):
    """CREATE VIEW <name> [(cols)] AS <select>. The view body is
    stored as SQL text in the descriptor and re-planned (expanded as a
    derived table) at each use, like the reference's view descriptors
    (pkg/sql/create_view.go)."""
    name: str
    columns: Optional[list] = None
    select: Optional["Statement"] = None  # parsed body (validation)
    sql: str = ""                          # body text (persisted)
    if_not_exists: bool = False


@dataclass
class DropView(Statement):
    name: str
    if_exists: bool = False


@dataclass
class CreateSequence(Statement):
    name: str
    start: int = 1
    increment: int = 1
    if_not_exists: bool = False


@dataclass
class DropSequence(Statement):
    name: str
    if_exists: bool = False


@dataclass
class ShowSequences(Statement):
    pass


@dataclass
class Truncate(Statement):
    """TRUNCATE [TABLE] <t>: clear all rows + index entries, keep the
    schema (pkg/sql/truncate.go swaps in fresh empty indexes)."""
    table: str


@dataclass
class AlterTable(Statement):
    """ALTER TABLE <t> ADD COLUMN <def> [DEFAULT lit] | DROP COLUMN <c>.
    Executed as an online schema change (jobs/schemachange.py)."""
    table: str
    add: Optional[ColumnDef] = None
    default: Optional[Expr] = None
    drop: Optional[str] = None


@dataclass
class ConfigureZone(Statement):
    """ALTER TABLE <t> CONFIGURE ZONE USING k = v, ... — per-table
    config overrides (gc.ttl_seconds, range_max_bytes), the spanconfig
    analogue."""
    table: str
    options: dict = field(default_factory=dict)


@dataclass
class ShowZone(Statement):
    """SHOW ZONE CONFIGURATION FOR TABLE <t>."""
    table: str


@dataclass
class Insert(Statement):
    table: str
    columns: list[str]  # empty = all
    rows: list[list[Expr]] = field(default_factory=list)
    select: Optional[Select] = None
    # UPSERT: a duplicate primary key replaces the row instead of
    # erroring (CRDB's UPSERT whole-row semantics)
    upsert: bool = False


@dataclass
class Update(Statement):
    table: str
    assignments: list[tuple[str, Expr]] = field(default_factory=list)
    where: Optional[Expr] = None


@dataclass
class Delete(Statement):
    table: str
    where: Optional[Expr] = None


@dataclass
class SetVar(Statement):
    name: str
    value: object
    cluster: bool = False  # SET CLUSTER SETTING


@dataclass
class ShowVar(Statement):
    name: str


@dataclass
class ShowTables(Statement):
    pass


@dataclass
class CreateChangefeed(Statement):
    """CREATE CHANGEFEED FOR <table> INTO '<sink-uri>'."""
    table: str
    sink: str


@dataclass
class ShowJobs(Statement):
    pass


@dataclass
class ShowStatements(Statement):
    """SHOW STATEMENTS: per-fingerprint execution stats (sqlstats)."""
    pass


@dataclass
class ShowTrace(Statement):
    """SHOW TRACE FOR SESSION: spans recorded since SET tracing=on."""
    pass


@dataclass
class ShowAll(Statement):
    """SHOW ALL: every session variable and its current value."""
    pass


@dataclass
class ShowCreateTable(Statement):
    """SHOW CREATE TABLE <t>: reconstructed DDL from the descriptor."""
    table: str


@dataclass
class CancelJob(Statement):
    job_id: int


@dataclass
class Backup(Statement):
    """BACKUP TABLE a, b INTO '<dir>' (incremental when the directory
    already holds a backup)."""
    tables: list[str]
    dest: str


@dataclass
class Restore(Statement):
    """RESTORE TABLE a, b FROM '<dir>' (empty tables = all)."""
    tables: list[str]
    src: str


@dataclass
class Explain(Statement):
    stmt: Statement
    analyze: bool = False
    # EXPLAIN ANALYZE (DEBUG): capture a statement diagnostics bundle
    # (plan + operator profile + trace + settings) inline, the
    # reference's stmtdiagnostics bundle path
    debug: bool = False


@dataclass
class Analyze(Statement):
    """ANALYZE <table> — collect table statistics (pkg/sql/stats)."""
    table: str


@dataclass
class BeginTxn(Statement):
    pass


@dataclass
class CommitTxn(Statement):
    pass


@dataclass
class RollbackTxn(Statement):
    pass
