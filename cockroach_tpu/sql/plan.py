"""Logical plan nodes (the analogue of memo relational expressions).

The plan tree the heuristic planner emits and the executor compiles.
Mirrors the reference's planNode/physicalPlan split loosely: this is
the single logical form; the distribution layer decides how a Scan's
spans map onto the device mesh (parallel/partition.py), like
PartitionSpans (distsql_physical_planner.go:1096) decides node
placement.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .bound import BExpr, BoundAgg
from .types import SQLType


class PlanNode:
    pass


@dataclass
class Scan(PlanNode):
    table: str
    alias: str
    # batch column name -> stored column name
    columns: dict[str, str] = field(default_factory=dict)
    # conjuncts pushed down to the scan (evaluated fused with the read)
    filter: Optional[BExpr] = None
    # computed columns added by the planner (e.g. remapped join keys)
    computed: list[tuple[str, BExpr]] = field(default_factory=list)
    # stored columns uploaded to HBM as int32 (engine-proven value
    # range): the scan upcasts them back to int64, so programs see
    # identical semantics while the HBM read moves half the bytes —
    # int64 is software-emulated on TPU, so narrow uploads also shed
    # the emulation's limb ops on the first touch
    narrowed: frozenset = frozenset()


@dataclass
class Filter(PlanNode):
    child: PlanNode
    pred: BExpr = None


@dataclass
class HashJoin(PlanNode):
    left: PlanNode           # probe side
    right: PlanNode          # build side
    left_keys: list[str] = field(default_factory=list)
    right_keys: list[str] = field(default_factory=list)
    payload: list[str] = field(default_factory=list)  # build cols to carry
    join_type: str = "inner"
    # output copies per probe row: 1 for unique build keys; the
    # engine's host-side max-multiplicity probe sets K>1 for
    # duplicate-keyed builds (static expansion bound)
    expand: int = 1
    # direct-address join (the TPU fast path): when the single build
    # key is int-family with a dense value range (dimension pks, dict
    # codes), the engine sets (base, size) and the join becomes one
    # scatter to build + one gather to probe — no hash table, no
    # while_loop. None = open-addressing hash table.
    direct: Optional[tuple] = None  # (base, table_size)
    # payload columns that are dict codes (int32, >= 0): the direct
    # fold packs match/null/value into one table -> one probe gather
    pack_payload: list = field(default_factory=list)


@dataclass
class Compact(PlanNode):
    """Pack selected rows into a smaller batch (blocked top_k over the
    selection mask). Inserted by the engine above low-selectivity
    scans/filters feeding aggregation: every downstream per-row op —
    join probe gathers above all — then runs at ``frac`` of the batch
    instead of full width with masked lanes. The TPU analogue of the
    reference's selection vectors (coldata.Batch sel), which its
    operators consume implicitly; XLA needs the compaction to be an
    explicit op. Per-block capacity overflow raises the
    __compact_overflow sentinel and the engine replans uncompacted."""
    child: PlanNode
    frac: float = 0.125     # per-block capacity fraction
    block: int = 32768


@dataclass
class Project(PlanNode):
    child: PlanNode
    items: list[tuple[str, BExpr]] = field(default_factory=list)


@dataclass
class Aggregate(PlanNode):
    child: PlanNode
    group_by: list[tuple[str, BExpr]] = field(default_factory=list)
    aggs: list[BoundAgg] = field(default_factory=list)
    having: Optional[BExpr] = None  # over BAggRef/group columns
    # output projections over group cols + agg refs
    items: list[tuple[str, BExpr]] = field(default_factory=list)
    max_groups: int = 0  # static bound if known (dict-encoded keys), else 0
    # per-key code-space sizes when max_groups > 0 (dense segment-sum
    # strategy: gid = mixed-radix code over these dims, +1 slot per dim
    # for NULL); empty when the hash-table strategy is required
    group_dims: list[int] = field(default_factory=list)
    # per-dim value offsets: code = value - lo (0 for dict/bool dims;
    # nonzero for small-range INT keys proven dense by stats)
    group_lo: list[int] = field(default_factory=list)
    # static upper bound on rows per group (engine-measured key
    # multiplicity), 0 = unknown. Sizes the i32 limb width of exact
    # int64 group sums (ops/agg.py group_sum): a tight bound means 3
    # fast i32 scatters instead of the software-emulated 64-bit one.
    max_group_rows: int = 0


@dataclass
class Window(PlanNode):
    """Materialize window function results as __win{i} columns on the
    child batch (colexecwindow analogue; one lexsort + scans per spec,
    ops/window.py)."""
    child: PlanNode
    windows: list = field(default_factory=list)  # BoundWindow


@dataclass
class Sort(PlanNode):
    child: PlanNode
    keys: list[tuple[str, bool]] = field(default_factory=list)  # (col, desc)


@dataclass
class Limit(PlanNode):
    child: PlanNode
    limit: Optional[int] = None
    offset: int = 0


@dataclass
class OutputMeta:
    """Result schema: names + types (+ dictionaries for decode)."""
    names: list[str] = field(default_factory=list)
    types: list[SQLType] = field(default_factory=list)
    dictionaries: dict[str, object] = field(default_factory=dict)
    # set when the memoized join-order search ran (sql/memo.py):
    # EXPLAIN surfaces the exploration summary
    memo: object = None
    # normalization rule firings (sql/rules.RuleTrace) — EXPLAIN
    # renders them like the reference's opttester rule output
    rule_trace: object = None
    # alias -> access-path description chosen by the memo's scan
    # costing ("primary eq(l_orderkey) rows≈3" / "full rows≈6001215")
    access_paths: dict = field(default_factory=dict)


def plan_tree_repr(node: PlanNode, indent: int = 0,
                   costs: dict | None = None,
                   actuals: dict | None = None,
                   sources: dict | None = None,
                   profile=None) -> str:
    """Render the plan tree; with ``costs`` (sql/stats.estimate output,
    id(node) -> (est_rows, est_cost)) each line gets the optimizer's
    cardinality/cost annotations, like EXPLAIN's estimated-row counts
    in the reference. EXPLAIN ANALYZE additionally passes ``actuals``
    (id(node) -> measured post-sel rows from the instrumented rerun)
    and ``sources`` (id(scan) -> "analyze"|"sketch"|"default", where
    the scan's cardinalities came from) so est-vs-actual drift — and
    which estimator produced the est — reads off each line. With
    ``profile`` (an exec/profile.ProfileSink from the same rerun) each
    operator additionally shows its measured device-seconds and moved
    bytes — the per-operator attribution the Theseus/Tailwind framing
    asks for."""
    pad = "  " * indent

    def ann() -> str:
        s = ""
        if costs is not None and id(node) in costs:
            rows, cost = costs[id(node)]
            src = ("" if sources is None or id(node) not in sources
                   else f" est={sources[id(node)]}")
            s += f"  (rows≈{rows:.0f} cost≈{cost:.0f}{src})"
        if actuals is not None and id(node) in actuals:
            s += f"  (actual rows={actuals[id(node)]})"
        if profile is not None:
            ent = profile.op_entry(node)
            if ent is not None:
                s += (f"  (device={ent.device_seconds * 1e3:.2f}ms"
                      + (f" bytes={ent.bytes_moved}"
                         if ent.bytes_moved else "") + ")")
        return s

    def child(n, extra_indent: int = 1) -> str:
        return plan_tree_repr(n, indent + extra_indent, costs,
                              actuals, sources, profile)

    if isinstance(node, Scan):
        f = f" filter={node.filter!r}" if node.filter is not None else ""
        return f"{pad}Scan {node.table} as {node.alias}{f}{ann()}\n"
    if isinstance(node, Filter):
        return f"{pad}Filter {node.pred!r}{ann()}\n" + child(node.child)
    if isinstance(node, HashJoin):
        return (f"{pad}HashJoin[{node.join_type}] "
                f"{node.left_keys}={node.right_keys}{ann()}\n"
                + child(node.left) + child(node.right))
    if isinstance(node, Project):
        return (f"{pad}Project {[n for n, _ in node.items]}{ann()}\n"
                + child(node.child))
    if isinstance(node, Aggregate):
        return (f"{pad}Aggregate groups={[n for n, _ in node.group_by]} "
                f"aggs={[a.func for a in node.aggs]}{ann()}\n"
                + child(node.child))
    if isinstance(node, Window):
        return (f"{pad}Window {[w.func for w in node.windows]}{ann()}\n"
                + child(node.child))
    if isinstance(node, Sort):
        return f"{pad}Sort {node.keys}{ann()}\n" + child(node.child)
    if isinstance(node, Limit):
        return (f"{pad}Limit {node.limit} offset {node.offset}{ann()}\n"
                + child(node.child))
    return f"{pad}{node!r}\n"


def prune_scan_columns(root: PlanNode) -> PlanNode:
    root, _ = _prune_impl(root)
    return root


def prune_scan_columns_traced(root: PlanNode):
    """prune_scan_columns, returning [(alias, n_dropped)] for the
    rule trace (sql/rules.py)."""
    _, dropped = _prune_impl(root)
    return dropped


def _prune_impl(root: PlanNode):
    """Projection pruning: shrink every Scan's column map to the batch
    columns the rest of the plan actually references. The engine
    uploads only these to HBM (the reference fetches only needed
    columns per index, colfetcher/cfetcher.go:668; here the win is
    device memory and PCIe, not just decode time).

    Conservative by name: a scan column survives if its batch name
    ("alias.col") appears in ANY expression/key list anywhere in the
    tree, so renames above Projects can never starve a real use.
    """
    from .bound import referenced_columns

    needed: set[str] = set()

    def collect(n: PlanNode):
        if isinstance(n, Scan):
            if n.filter is not None:
                needed.update(referenced_columns(n.filter))
            for _, e in n.computed:
                needed.update(referenced_columns(e))
        elif isinstance(n, Filter):
            needed.update(referenced_columns(n.pred))
        elif isinstance(n, HashJoin):
            needed.update(n.left_keys)
            needed.update(n.right_keys)
            needed.update(n.payload)
        elif isinstance(n, Project):
            for _, e in n.items:
                needed.update(referenced_columns(e))
        elif isinstance(n, Aggregate):
            for _, e in n.group_by:
                needed.update(referenced_columns(e))
            for a in n.aggs:
                if a.arg is not None:
                    needed.update(referenced_columns(a.arg))
            if n.having is not None:
                needed.update(referenced_columns(n.having))
            for _, e in n.items:
                needed.update(referenced_columns(e))
        elif isinstance(n, Window):
            for w in n.windows:
                if w.arg is not None:
                    needed.update(referenced_columns(w.arg))
                for p in w.partition_by:
                    needed.update(referenced_columns(p))
                for o, _ in w.order_by:
                    needed.update(referenced_columns(o))
        elif isinstance(n, Sort):
            needed.update(k[0] for k in n.keys)
        for attr in ("child", "left", "right"):
            c = getattr(n, attr, None)
            if c is not None:
                collect(c)

    collect(root)

    dropped: list[tuple[str, int]] = []

    def prune(n: PlanNode):
        if isinstance(n, Scan):
            kept = {bn: sn for bn, sn in n.columns.items()
                    if bn in needed}
            if not kept and n.columns:
                # count(*)-style plans touch no columns, but a batch
                # needs one to carry its shape
                bn = next(iter(n.columns))
                kept = {bn: n.columns[bn]}
            if len(kept) < len(n.columns):
                dropped.append((n.alias, len(n.columns) - len(kept)))
            n.columns = kept
        for attr in ("child", "left", "right"):
            c = getattr(n, attr, None)
            if c is not None:
                prune(c)

    prune(root)
    return root, dropped
