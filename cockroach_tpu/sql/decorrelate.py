"""EXISTS / NOT EXISTS decorrelation: aggregate-based unnesting.

The reference decorrelates through the optimizer's normalization rules
(pkg/sql/opt/norm/decorrelate.go: hoisting + apply-to-join rewrites).
The TPU engine compiles whole plans to static-shape XLA programs, so
the rewrite happens earlier and simpler — on the AST, before binding:

    ... WHERE EXISTS (SELECT * FROM T t2
                      WHERE t2.k  = outer.k        -- eq correlations
                        AND t2.s <> outer.s        -- <=1 neq correlation
                        AND <uncorrelated preds>)  -- residual

becomes a LEFT JOIN against the grouped inner table

    LEFT JOIN (SELECT k, count(*) AS __c
                    [, min(s) AS __mn, max(s) AS __mx]
               FROM T WHERE <residual> GROUP BY k) AS __existsN
           ON __existsN.k = outer.k

with the EXISTS conjunct replaced by a plain predicate:

    EXISTS          ->  __c >= 1 [AND (__mn <> s OR __mx <> s)]
    NOT EXISTS      ->  coalesce(__c, 0) = 0 [OR (__mn = s AND __mx = s)]

The min/max trick handles the one inequality correlation TPC-H Q21
needs: a row with t2.s <> outer.s exists among the k-group iff the
group's min or max differs from outer.s (works on any equality-
comparable type; we restrict to non-string columns so dictionary code
spaces never mix). The derived table has one row per k, so the LEFT
JOIN never multiplies outer rows. NULL semantics note: correlation
columns must be NOT NULL for the min/max trick (SQL's <> over NULLs
never matches anyway, and TPC-H schemas are NOT NULL throughout).
"""

from __future__ import annotations

import itertools
from dataclasses import replace

from . import ast

_counter = itertools.count()


def _conjuncts(e):
    if isinstance(e, ast.BinOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _and_all(parts):
    out = None
    for p in parts:
        out = p if out is None else ast.BinOp("and", out, p)
    return out


def _refs(e, out):
    """Collect every ColumnRef under e via a generic dataclass walk;
    a None marker means 'opaque' (nested subquery or unknown node) and
    makes the caller bail — misclassifying a hidden outer reference as
    inner would hoist it out of scope."""
    import dataclasses
    if isinstance(e, ast.ColumnRef):
        out.append(e)
        return out
    if isinstance(e, (ast.Exists, ast.Subquery, ast.InSubquery)):
        out.append(None)
        return out
    if isinstance(e, (list, tuple)):
        for v in e:
            _refs(v, out)
        return out
    if dataclasses.is_dataclass(e) and not isinstance(e, type):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, (ast.Expr, list, tuple)):
                _refs(v, out)
        return out
    return out


def _side(e, inner_aliases, inner_cols: set, outer_aliases: set):
    """'inner' / 'outer' / None (mixed or unresolvable).
    inner_aliases: a str (one table) or a set of aliases."""
    if isinstance(inner_aliases, str):
        inner_aliases = {inner_aliases}
    refs = _refs(e, [])
    if any(r is None for r in refs):
        return None
    sides = set()
    for r in refs:
        if r.table in inner_aliases or (r.table is None
                                        and r.name in inner_cols):
            sides.add("inner")
        elif r.table in outer_aliases or r.table is None:
            sides.add("outer")
        else:
            return None
    if not sides:
        return "outer"   # constant expression: evaluable outside
    return sides.pop() if len(sides) == 1 else None


_AGG_FNS = {"sum", "avg", "min", "max", "count"}


def _agg_only(e) -> str | None:
    """Classify a select-item expression that must collapse to one row
    per group: every ColumnRef sits under an aggregate FuncCall and at
    least one aggregate exists. Returns "count" when the expression is
    exactly count(...) (whose empty-group value is 0, not NULL),
    "agg" for other aggregate-only shapes, None when not aggregate-only."""
    import dataclasses
    if isinstance(e, ast.FuncCall) and e.name in _AGG_FNS:
        return "count" if e.name == "count" else "agg"
    if isinstance(e, ast.ColumnRef):
        return None
    if isinstance(e, (ast.Exists, ast.Subquery, ast.InSubquery)):
        return None
    kinds = []
    if dataclasses.is_dataclass(e) and not isinstance(e, type):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            vs = v if isinstance(v, (list, tuple)) else [v]
            for x in vs:
                if isinstance(x, ast.Expr):
                    k = _agg_only(x)
                    if k is None and _refs(x, []):
                        return None  # bare column ref outside an agg
                    if k is not None:
                        kinds.append(k)
    if not kinds:
        return None
    if "count" in kinds:
        # arithmetic over count (e.g. count(*) + 1) would need the
        # empty group to evaluate the expression at count = 0, but the
        # LEFT JOIN yields NULL — not rewritable
        return None
    return "agg"


def _walk_subqueries(e, visit):
    """Depth-first over an expr/statement tree, calling visit(node,
    setter) for every ast.Subquery; setter(replacement) swaps it out
    in place. Mutates e (callers pass a private copy)."""
    import dataclasses
    if not (dataclasses.is_dataclass(e) and not isinstance(e, type)):
        return
    if isinstance(e, (ast.Exists, ast.InSubquery)):
        return  # handled by the EXISTS/IN paths; do not descend
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, ast.Subquery):
            def setter(repl, _e=e, _n=f.name):
                setattr(_e, _n, repl)
            visit(v, setter)
        elif isinstance(v, ast.Expr):
            _walk_subqueries(v, visit)
        elif isinstance(v, (list, tuple)):
            for i, x in enumerate(v):
                if isinstance(x, ast.Subquery):
                    def setter(repl, _v=v, _i=i):
                        _v[_i] = repl
                    visit(x, setter)
                elif isinstance(x, ast.Expr):
                    _walk_subqueries(x, visit)


def decorrelate_scalar(sel: ast.Select, columns_of) -> ast.Select:
    """Rewrite correlated scalar subqueries in sel's SELECT items and
    WHERE into grouped LEFT JOINs (TPC-H q2/q17/q20/q22 shapes):

        x < (SELECT agg(e) FROM T WHERE T.k = outer.k AND <residual>)

    becomes LEFT JOIN (SELECT k AS __k0, agg(e) AS __v FROM T WHERE
    <residual> GROUP BY k) AS __scN ON __scN.__k0 = outer.k, with the
    subquery replaced by __scN.__v. Missing groups join as NULL —
    exactly the empty scalar subquery's value — except count(...),
    which yields 0 and gets a coalesce. Non-rewritable subqueries are
    left untouched (uncorrelated ones bind as constants; genuinely
    unsupported ones keep the clear bind error)."""
    import copy
    outer_aliases = set()
    if sel.table is not None:
        outer_aliases.add(sel.table.alias or sel.table.name)
    for j in sel.joins:
        outer_aliases.add(j.table.alias or j.table.name)
    if not outer_aliases:
        return sel

    # the deepcopy below is ~25% of a point-lookup's latency; skip it
    # (and the walks) when no scalar subquery exists at all
    found = []
    for item in sel.items:
        _walk_subqueries(item, lambda s, _set: found.append(s))
    if sel.where is not None:
        _walk_subqueries(sel.where, lambda s, _set: found.append(s))
    if not found:
        return sel

    sel = copy.deepcopy(sel)
    new_joins = []

    def visit(sub, setter):
        out = _rewrite_scalar(sub.select, outer_aliases, columns_of)
        if out is None:
            return
        join, repl = out
        new_joins.append(join)
        setter(repl)

    for item in sel.items:
        _walk_subqueries(item, visit)
    if sel.where is not None:
        _walk_subqueries(sel.where, visit)
    if not new_joins:
        return sel
    sel.joins = list(sel.joins) + new_joins
    return sel


def _rewrite_scalar(sub: ast.Select, outer_aliases: set, columns_of):
    """One correlated scalar subquery -> (JoinClause, replacement
    expr), or None. The subquery may itself join several tables
    (TPC-H q2's min-supplycost over partsupp x supplier x nation x
    region) as long as every join is inner/comma with inner-only ON
    conditions — the whole inner FROM moves into the derived table."""
    if sub is None or sub.table is None or \
            sub.table.subquery is not None or \
            sub.group_by or sub.having or sub.ctes or sub.distinct or \
            sub.limit is not None or sub.where is None or \
            len(sub.items) != 1:
        return None
    kind = _agg_only(sub.items[0].expr)
    if kind is None:
        return None
    inner_aliases = {sub.table.alias or sub.table.name}
    inner_cols = columns_of(sub.table.name)
    if inner_cols is None:
        return None
    inner_cols = set(inner_cols)
    for j in sub.joins:
        if j.join_type not in ("inner", "cross") or \
                j.table.subquery is not None:
            return None
        cols = columns_of(j.table.name)
        if cols is None:
            return None
        inner_aliases.add(j.table.alias or j.table.name)
        inner_cols |= cols
    if inner_aliases & outer_aliases:
        return None
    for j in sub.joins:
        if j.on is not None and _side(j.on, inner_aliases, inner_cols,
                                      outer_aliases) != "inner":
            return None

    eq_corr = []
    residual = []
    for p in _conjuncts(sub.where):
        s = _side(p, inner_aliases, inner_cols, outer_aliases)
        if s == "inner":
            residual.append(p)
            continue
        if isinstance(p, ast.BinOp) and p.op == "=":
            ls = _side(p.left, inner_aliases, inner_cols, outer_aliases)
            rs = _side(p.right, inner_aliases, inner_cols,
                       outer_aliases)
            pair = None
            if ls == "inner" and rs == "outer" and \
                    isinstance(p.left, ast.ColumnRef):
                pair = (p.left, p.right)
            elif rs == "inner" and ls == "outer" and \
                    isinstance(p.right, ast.ColumnRef):
                pair = (p.right, p.left)
            if pair is not None:
                eq_corr.append(pair)
                continue
        return None
    if not eq_corr:
        return None  # uncorrelated: the binder inlines it already

    dn = f"__sc{next(_counter)}"
    items = []
    group_by = []
    on_parts = []
    for i, (icol, oexpr) in enumerate(eq_corr):
        inner = ast.ColumnRef(icol.name, icol.table)
        items.append(ast.SelectItem(inner, alias=f"__k{i}"))
        group_by.append(inner)
        on_parts.append(ast.BinOp("=", ast.ColumnRef(f"__k{i}", dn),
                                  oexpr))
    items.append(ast.SelectItem(sub.items[0].expr, alias="__v"))
    derived = ast.Select(
        items=items,
        table=sub.table,
        joins=list(sub.joins),
        where=_and_all(residual),
        group_by=group_by)
    join = ast.JoinClause(
        table=ast.TableRef(dn, alias=dn, subquery=derived),
        join_type="left", on=_and_all(on_parts))
    repl: ast.Expr = ast.ColumnRef("__v", dn)
    if kind == "count":
        repl = ast.FuncCall("coalesce", [repl, ast.Literal(0)])
    return join, repl


def _match_exists(c):
    """(exists_node, negated) or (None, False)."""
    if isinstance(c, ast.Exists):
        return c, False
    if isinstance(c, ast.UnaryOp) and c.op == "not" and \
            isinstance(c.operand, ast.Exists):
        return c.operand, True
    return None, False


def decorrelate_exists(sel: ast.Select, columns_of,
                       is_string_col=None) -> ast.Select:
    """Rewrite rewritable (NOT) EXISTS conjuncts of sel.where;
    non-rewritable ones are left alone (and fail later with the
    existing 'correlated subqueries not supported' error).

    columns_of(table_name) -> set of column names, or None if the
    table is unknown (view, CTE - we skip those).
    is_string_col(table, col) -> bool: the neq (min/max) trick is
    refused for string columns (dictionary code spaces must not mix
    across tables)."""
    if sel.where is None or sel.table is None:
        return sel
    outer_aliases = set()
    if sel.table is not None:
        outer_aliases.add(sel.table.alias or sel.table.name)
    for j in sel.joins:
        outer_aliases.add(j.table.alias or j.table.name)

    new_conjs = []
    new_joins = []
    changed = False
    for c in _conjuncts(sel.where):
        ex, negated = _match_exists(c)
        rewritten = None
        if ex is not None and ex.select is not None:
            rewritten = _rewrite_one(ex.select, negated, outer_aliases,
                                     columns_of, is_string_col)
        if rewritten is None:
            new_conjs.append(c)
            continue
        join, pred = rewritten
        new_joins.append(join)
        new_conjs.append(pred)
        changed = True
    if not changed:
        return sel
    return replace(sel, where=_and_all(new_conjs),
                   joins=list(sel.joins) + new_joins)


def _rewrite_one(sub: ast.Select, negated: bool, outer_aliases: set,
                 columns_of, is_string_col=None):
    """One EXISTS subquery -> (JoinClause, replacement predicate),
    or None if the shape is not rewritable."""
    if sub.table is None or sub.table.subquery is not None or \
            sub.joins or sub.group_by or sub.having or sub.ctes or \
            sub.distinct or sub.limit is not None or sub.where is None:
        return None
    inner_alias = sub.table.alias or sub.table.name
    inner_cols = columns_of(sub.table.name)
    if inner_cols is None or inner_alias in outer_aliases:
        return None

    eq_corr = []    # (inner ColumnRef, outer expr)
    neq_corr = []   # (inner ColumnRef, outer expr)
    residual = []
    for p in _conjuncts(sub.where):
        s = _side(p, inner_alias, inner_cols, outer_aliases)
        if s == "inner":
            residual.append(p)
            continue
        if isinstance(p, ast.BinOp) and p.op in ("=", "<>", "!="):
            ls = _side(p.left, inner_alias, inner_cols, outer_aliases)
            rs = _side(p.right, inner_alias, inner_cols, outer_aliases)
            pair = None
            if ls == "inner" and rs == "outer" and \
                    isinstance(p.left, ast.ColumnRef):
                pair = (p.left, p.right)
            elif rs == "inner" and ls == "outer" and \
                    isinstance(p.right, ast.ColumnRef):
                pair = (p.right, p.left)
            if pair is not None:
                (eq_corr if p.op == "=" else neq_corr).append(pair)
                continue
        return None   # unsupported correlated shape
    if not eq_corr or len(neq_corr) > 1:
        return None
    if neq_corr and is_string_col is not None and \
            is_string_col(sub.table.name, neq_corr[0][0].name):
        return None

    dn = f"__exists{next(_counter)}"
    items = []
    group_by = []
    on_parts = []
    for i, (icol, oexpr) in enumerate(eq_corr):
        # keep the subquery's own alias inside the derived select so
        # residual predicates (which carry it as qualifier) still bind
        inner = ast.ColumnRef(icol.name, inner_alias)
        items.append(ast.SelectItem(inner, alias=f"__k{i}"))
        group_by.append(inner)
        on_parts.append(ast.BinOp("=", ast.ColumnRef(f"__k{i}", dn),
                                  oexpr))
    items.append(ast.SelectItem(
        ast.FuncCall("count", [], star=True), alias="__c"))
    if neq_corr:
        s_in = ast.ColumnRef(neq_corr[0][0].name, inner_alias)
        items.append(ast.SelectItem(ast.FuncCall("min", [s_in]),
                                    alias="__mn"))
        items.append(ast.SelectItem(ast.FuncCall("max", [s_in]),
                                    alias="__mx"))
    derived = ast.Select(
        items=items,
        table=ast.TableRef(sub.table.name, alias=inner_alias),
        where=_and_all(residual),
        group_by=group_by)
    join = ast.JoinClause(
        table=ast.TableRef(dn, alias=dn, subquery=derived),
        join_type="left", on=_and_all(on_parts))

    c_col = ast.ColumnRef("__c", dn)
    if not negated:
        pred = ast.BinOp(">=", c_col, ast.Literal(1))
        if neq_corr:
            s_out = neq_corr[0][1]
            mn = ast.ColumnRef("__mn", dn)
            mx = ast.ColumnRef("__mx", dn)
            diff = ast.BinOp("or", ast.BinOp("<>", mn, s_out),
                             ast.BinOp("<>", mx, s_out))
            pred = ast.BinOp("and", pred, diff)
        return join, pred
    # NOT EXISTS: true when no k-match at all, or (with the neq
    # correlation) when every inner row's s equals outer's s
    no_match = ast.BinOp("=", ast.FuncCall(
        "coalesce", [c_col, ast.Literal(0)]), ast.Literal(0))
    if not neq_corr:
        return join, no_match
    s_out = neq_corr[0][1]
    mn = ast.ColumnRef("__mn", dn)
    mx = ast.ColumnRef("__mx", dn)
    all_same = ast.BinOp("and", ast.BinOp("=", mn, s_out),
                         ast.BinOp("=", mx, s_out))
    return join, ast.BinOp("or", no_match, all_same)
