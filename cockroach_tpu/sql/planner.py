"""Heuristic logical planner: Select AST -> plan tree.

The reference runs a full cost-based optimizer (pkg/sql/opt: memo +
norm/xform rules); per SURVEY.md §7 step 7 we start heuristic:

- scans for each FROM table, filters split into conjuncts;
- equality conjuncts between two tables become hash joins (left-deep,
  in FROM order; the syntactically-later / ON-right table is the build
  side, so dimension tables join PK-side as in TPC-H/SSB);
- single-table conjuncts push down into the scan (fused with the MVCC
  visibility mask on device);
- aggregates extracted from SELECT/HAVING into an Aggregate node with
  post-projection expressions (BAggRef), mirroring how the reference's
  DistAggregationTable renders final AVG as SUM/COUNT;
- ORDER BY/LIMIT on top.
"""

from __future__ import annotations

from dataclasses import dataclass

from . import ast, plan
from .binder import Binder, BindError, ColumnBinding, Scope
from .bound import (BAggRef, BBin, BCol, BConst, BDictRemap, BExpr,
                    referenced_columns, walk)
from .types import Family, TableSchema


class PlanError(Exception):
    pass


class CatalogView:
    """What the planner needs from the catalog: schema + dictionaries
    + table statistics (exact row counts; ANALYZE-computed distincts
    when available — sql/stats.py). ``key_distinct_fn(table, cols) ->
    (distinct, nonnull_rows)`` is the engine's exact uniqueness probe
    (cached per generation); None when no store is attached."""

    def __init__(self, schemas, dictionaries, stats=None,
                 key_distinct_fn=None, int_range_fn=None,
                 keys_unique_fn=None, indexes=None):
        self.schemas = schemas
        self.dictionaries = dictionaries
        self.stats = stats or {}
        # table -> [(index_name, (cols...), unique)] of PUBLIC
        # secondary indexes: access-path candidates for the memo's
        # scan costing (planner._choose_access_paths)
        self.indexes = indexes or {}
        self.key_distinct_fn = key_distinct_fn
        # keys_unique_fn(table, cols) -> bool: SNAPSHOT-AWARE
        # uniqueness at the statement's read timestamp — required for
        # correctness-bearing rewrites (FD group-key reduction), where
        # the live-rows distinct probe could disagree with an AS OF
        # read's visible rows
        self.keys_unique_fn = keys_unique_fn
        # int_range_fn(table, col) -> (lo, hi, count) | None: exact
        # all-versions value range of an int column (generation-
        # cached). Lets GROUP BY over small-range int keys (years,
        # status codes) take the dense segment-sum strategy instead of
        # the while-loop hash table. The engine withholds it for
        # txn-overlay reads (uncommitted rows could exceed the range).
        self.int_range_fn = int_range_fn

    def schema(self, name: str) -> TableSchema:
        s = self.schemas.get(name)
        if s is None:
            raise PlanError(f"table {name!r} does not exist")
        return s

    def row_count(self, name: str) -> float:
        st = self.stats.get(name)
        return float(st.row_count) if st is not None else 1000.0


def split_conjuncts(e: BExpr) -> list[BExpr]:
    if isinstance(e, BBin) and e.op == "and":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def and_all(conjuncts: list[BExpr]) -> BExpr:
    out = conjuncts[0]
    from .types import BOOL
    for c in conjuncts[1:]:
        out = BBin("and", out, c, BOOL)
    return out


class Planner:
    # tables beyond this use the greedy orderer (2^n memo groups)
    MEMO_MAX_TABLES = 12

    def __init__(self, catalog: CatalogView, subquery_eval=None,
                 now_micros=None, sequence_ops=None,
                 use_memo: bool = True, volatile_fold_ok: bool = True,
                 dict_folds: bool = True, rules: bool = True,
                 trace=None):
        self.catalog = catalog
        # False: dictionary-content-dependent constant folds disabled
        # so plan structure is shard-independent (distsql/shuffle.py)
        self.dict_folds = dict_folds
        # the normalization rule plane (sql/rules.py); the engine maps
        # SET optimizer_rules = 'off' here
        self.rules_on = rules
        # caller-provided RuleTrace so AST-layer firings (view
        # expansion, decorrelation — recorded by the engine) and
        # plan-layer firings land in one report
        self._trace = trace
        # alias -> chosen access path line (memo scan costing)
        self.access_paths: dict = {}
        # engine-supplied hooks: subquery execution + statement
        # timestamp for now()/current_date + sequence builtins
        # (binder.py)
        self.subquery_eval = subquery_eval
        self.now_micros = now_micros
        self.sequence_ops = sequence_ops
        self.use_memo = use_memo
        self.volatile_fold_ok = volatile_fold_ok
        self.last_memo = None  # sql/memo.MemoResult of the last plan

    def _keys_unique(self, cand_alias: str, cand_table: str, pool,
                     other_side: set, _key_side, scans) -> bool:
        """Would ``cand_alias`` have unique join keys as a build side?
        Collect its side of the equality conjuncts against
        ``other_side`` and run the catalog's exact distinct probe.
        Conservative: unknown/computed keys or no probe -> False."""
        fn = self.catalog.key_distinct_fn
        if fn is None:
            return False
        stored = []
        colmap = scans[cand_alias].columns
        for c in pool:
            if not (isinstance(c, BBin) and c.op == "="):
                continue
            ta, na, ea = _key_side(c.left)
            tb, nb, eb = _key_side(c.right)
            cand_name = None
            if ta == cand_alias and tb in other_side:
                cand_name, cand_expr = na, ea
            elif tb == cand_alias and ta in other_side:
                cand_name, cand_expr = nb, eb
            else:
                continue
            if cand_name is None:
                # dictionary-remapped key: the remap is injective, so
                # the underlying column's distinctness carries over
                from .stats import _underlying_col
                inner = _underlying_col(cand_expr)
                cand_name = getattr(inner, "name", None)
            sname = colmap.get(cand_name) if cand_name else None
            if sname is None:
                return False
            stored.append(sname)
        if not stored:
            return False
        distinct, nonnull = fn(cand_table, tuple(stored))
        return distinct == nonnull

    def _choose_access_paths(self, tables, conjuncts,
                             tables_of) -> None:
        """Cost every table's access paths — full scan vs each index
        whose columns are fully bound by constant-equality conjuncts —
        and record the winner (idxconstraint + the memo's scan costing
        in one place; surfaced by EXPLAIN as 'access:' lines, fed to
        memo.search as scan_cost)."""
        from .bound import BConst
        for alias, tname in tables:
            rc = max(self.catalog.row_count(tname), 1.0)
            st = self.catalog.stats.get(tname)
            eq_cols: set[str] = set()
            for c in conjuncts:
                if isinstance(c, BBin) and c.op == "=" \
                        and tables_of(c) == {alias}:
                    for a, b in ((c.left, c.right),
                                 (c.right, c.left)):
                        if isinstance(a, BCol) and \
                                isinstance(b, BConst):
                            eq_cols.add(a.name.split(".", 1)[-1])
            cands = []
            try:
                pk = tuple(self.catalog.schema(tname).primary_key)
                if pk:
                    cands.append(("primary", pk, True))
            except PlanError:
                pass
            for nm, cols, uniq in self.catalog.indexes.get(tname, []):
                cands.append((nm, tuple(cols), uniq))
            best = ("full", rc, rc)
            for label, cols, uniq in cands:
                if not cols or not all(cn in eq_cols for cn in cols):
                    continue
                if uniq:
                    est = 1.0
                else:
                    est = rc
                    for cn in cols:
                        d = (st.distinct.get(cn)
                             if st is not None and st.distinct
                             else None)
                        est /= max(float(d) if d else rc ** 0.5, 1.0)
                    est = max(est, 1.0)
                cost = est + 2.0   # probe overhead
                if cost < best[2]:
                    best = (f"{label} eq({','.join(cols)})", est, cost)
            self.access_paths[alias] = best

    def _memo_order(self, tables, ordered, conjuncts, alias_table,
                    tables_of, _key_side):
        """Run the memoized join-order search over this query's join
        graph; None = not applicable (disconnected, or no orderable
        shape) — caller falls back to the greedy orderer."""
        from . import memo as memomod
        from .stats import _pred_selectivity
        aliases = [tables[0][0]] + [e[0] for e in ordered]
        if len(set(aliases)) != len(aliases):
            return None  # self-join aliasing handled by greedy path
        pool_all = (list(conjuncts)
                    + [c for _, _, oc in ordered for c in oc])
        stats_map = self.catalog.stats
        # cost-based search engages only when column statistics exist
        # for every table (ANALYZE); without distinct counts the
        # multiplicity/selectivity estimates are guesses and the
        # greedy smallest-build heuristic is safer (the reference
        # likewise falls back without table_statistics)
        for a in aliases:
            st = stats_map.get(alias_table[a])
            if st is None or not st.distinct:
                return None

        def scan_rows(alias: str) -> float:
            st = stats_map.get(alias_table[alias])
            rc = max(self.catalog.row_count(alias_table[alias]), 1.0)
            sel = 1.0
            for c in pool_all:
                if tables_of(c) == {alias}:
                    sel *= _pred_selectivity(c, st)
            return rc * sel

        def _distinct(al: str, cn) -> float | None:
            st = stats_map.get(alias_table[al])
            if st is None or cn is None:
                return None
            dd = st.distinct.get(cn.split(".", 1)[-1])
            return float(dd) if dd else None

        # resolve each equality conjunct's sides ONCE — join_info runs
        # per memo extension (O(2^n * n) calls), so per-call conjunct
        # rescans would dominate planning at the table cap
        edges = []
        for c in pool_all:
            if not (isinstance(c, BBin) and c.op == "="):
                continue
            ta, na, _ea = _key_side(c.left)
            tb, nb, _eb = _key_side(c.right)
            if ta is not None and tb is not None:
                edges.append((ta, na, tb, nb))

        int_range = self.catalog.int_range_fn

        def _direct_eligible(alias: str, key_cols: list) -> bool:
            """Mirror engine._maybe_direct_join's span caps: can a
            build on these key columns take the direct-address table?
            A unique build that can't still pays the while-loop hash
            path, so the memo must charge it accordingly."""
            if int_range is None or not key_cols:
                return False
            t = alias_table[alias]
            spans = []
            n_all = 0
            for qc in key_cols:
                col = qc.split(".", 1)[-1]
                try:
                    r = int_range(t, col)
                except (KeyError, TypeError, ValueError):
                    return False
                if r is None:
                    return False
                lo, hi, n_all = r
                spans.append(hi - lo + 1)
            if len(spans) == 1:
                return (spans[0] <= max(256 * n_all, 4096)
                        and spans[0] + 1 <= (1 << 22))
            total = 1
            for span in spans:
                total *= span
                if total > (1 << 27):
                    return False
            return total <= max(2048 * n_all, 4096)

        kd_fn = self.catalog.key_distinct_fn

        def _exact_distinct(alias: str, cols: tuple) -> float | None:
            """EXACT combined-key distinct via the store (generation-
            cached lexsort). Per-column independence MULTIPLIES
            distincts for composite keys, wildly overestimating when
            the columns are correlated (q9: lineitem (l_suppkey,
            l_partkey) -> 61M 'independent' pairs vs ~800K real; the
            resulting build_mult=1.0 + selectivity 1/61M made a 1M-row
            hash build of lineitem look free)."""
            if kd_fn is None:
                return None
            try:
                d, _nn = kd_fn(alias_table[alias],
                               tuple(c.split(".", 1)[-1]
                                     for c in cols))
            except (KeyError, TypeError):
                return None
            return float(d) if d else None

        def join_info(left_set, right):
            sel = None
            build_key_distinct = 1.0
            build_known = True
            build_cols = []
            probe_sides = []
            for ta, na, tb, nb in edges:
                if ta in left_set and tb == right:
                    sides = ((ta, na), (tb, nb))
                elif tb in left_set and ta == right:
                    sides = ((tb, nb), (ta, na))
                else:
                    continue
                # independence estimate: 1/max(distinct_l, distinct_r)
                d = 1.0
                for al, cn in sides:
                    dd = _distinct(al, cn)
                    if dd:
                        d = max(d, dd)
                if d <= 1.0:
                    d = max(*(self.catalog.row_count(alias_table[al])
                              for al, _ in sides), 1.0)
                s = 1.0 / d
                sel = s if sel is None else sel * s
                bd = _distinct(sides[1][0], sides[1][1])
                if bd:
                    build_key_distinct *= bd
                else:
                    build_known = False
                if sides[1][1] is not None:
                    build_cols.append(sides[1][1])
                probe_sides.append(sides[0])
            if sel is None:
                return None
            if len(build_cols) > 1:
                # composite key: replace the independence products
                # with exact combined distincts on both sides
                bd_exact = _exact_distinct(right, tuple(build_cols))
                if bd_exact is not None:
                    build_key_distinct = bd_exact
                    build_known = True
                    p_alias = {al for al, _ in probe_sides}
                    pd_exact = (_exact_distinct(
                        next(iter(p_alias)),
                        tuple(cn for _, cn in probe_sides
                              if cn is not None))
                        if len(p_alias) == 1
                        and all(cn is not None
                                for _, cn in probe_sides) else None)
                    sel = 1.0 / max(bd_exact, pd_exact or 1.0)
            # duplicate rows per key on the build side: the device
            # join expands these, capped by the engine — estimate
            # from the UNFILTERED base rows (pushdown filters do not
            # reduce per-key multiplicity reliably)
            base = max(self.catalog.row_count(alias_table[right]), 1.0)
            mult = (base / max(build_key_distinct, 1.0)
                    if build_known else 1.0)
            return sel, mult, _direct_eligible(right, build_cols)

        def scan_cost(alias: str) -> float:
            # access-path-aware: an index lookup costs its matched
            # rows; otherwise the post-filter scan estimate
            ap = self.access_paths.get(alias)
            rows = scan_rows(alias)
            if ap is not None and not ap[0].startswith("full"):
                return min(rows, ap[2])
            return rows

        return memomod.search(aliases, scan_rows, join_info,
                              scan_cost=scan_cost)

    def plan_select(self, sel: ast.Select) -> tuple[plan.PlanNode, plan.OutputMeta]:
        if sel.table is None:
            raise PlanError("SELECT without FROM not supported")
        if any(j.join_type == "right" for j in sel.joins):
            # a RIGHT JOIN b == b LEFT JOIN a: rewrite when it is the
            # sole join (the general interior-right case needs full
            # join reassociation — memo/xform territory)
            if len(sel.joins) != 1:
                raise PlanError(
                    "RIGHT JOIN supported only as the sole join")
            import copy
            sel = copy.copy(sel)
            j = sel.joins[0]
            sel.table, sel.joins = j.table, [
                ast.JoinClause(sel.table, "left", j.on)]

        # ---- scopes & scans -------------------------------------------------
        scope = Scope()
        tables: list[tuple[str, str]] = []  # (alias, table_name)
        scans: dict[str, plan.Scan] = {}
        join_specs: list[ast.JoinClause] = list(sel.joins)

        def add_table(tref: ast.TableRef):
            alias = tref.alias or tref.name
            schema = self.catalog.schema(tref.name)
            dicts = self.catalog.dictionaries.get(tref.name, {})
            cols = {}
            colmap = {}
            for c in schema.columns:
                bname = f"{alias}.{c.name}"
                cols[c.name] = ColumnBinding(bname, c.type, dicts.get(c.name))
                colmap[bname] = c.name
            scope.add_table(alias, cols)
            tables.append((alias, tref.name))
            scans[alias] = plan.Scan(tref.name, alias, colmap)

        add_table(sel.table)
        for j in join_specs:
            add_table(j.table)

        binder = Binder(scope, subquery_eval=self.subquery_eval,
                        now_micros=self.now_micros,
                        sequence_ops=self.sequence_ops,
                        volatile_fold_ok=self.volatile_fold_ok,
                        dict_folds=self.dict_folds)

        # ---- gather predicates ---------------------------------------------
        conjuncts: list[BExpr] = []
        explicit_joins: list[tuple[str, str, BExpr]] = []  # (alias, type, on)
        for j in join_specs:
            alias = j.table.alias or j.table.name
            if j.on is not None:
                explicit_joins.append((alias, j.join_type, binder.bind(j.on)))
            else:
                explicit_joins.append((alias, j.join_type, None))
        if sel.where is not None:
            conjuncts.extend(split_conjuncts(binder.bind(sel.where)))

        alias_of_col: dict[str, str] = {}
        for alias, _ in tables:
            for b in scope.tables[alias].values():
                alias_of_col[b.batch_name] = alias

        def tables_of(e: BExpr) -> set[str]:
            return {alias_of_col[c] for c in referenced_columns(e)}

        # ---- assemble join tree --------------------------------------------
        # Left-deep: first table is the running probe side; each joined
        # table is a build side with equality keys from ON + WHERE.
        joined = {tables[0][0]}
        node: plan.PlanNode = scans[tables[0][0]]
        probe_root = tables[0][0]  # updated if the build-side swap fires
        remaining_conjuncts = list(conjuncts)
        self._choose_access_paths(tables, conjuncts, tables_of)

        jk_counter = [0]

        def _key_side(e: BExpr):
            """(alias, batch column name or None-if-computed, expr)."""
            if isinstance(e, BCol):
                return alias_of_col[e.name], e.name, None
            if isinstance(e, BDictRemap) and isinstance(e.expr, BCol):
                return alias_of_col[e.expr.name], None, e
            return None, None, None

        def _key_name(alias: str, name, expr) -> str:
            if name is not None:
                return name
            # computed join key (e.g. dictionary-code remap): evaluate it
            # in the owning scan
            kname = f"__jk{jk_counter[0]}"
            jk_counter[0] += 1
            scans[alias].computed.append((kname, expr))
            return kname

        def extract_equi_keys(pool: list[BExpr], left_tables: set[str],
                              right: str):
            lk, rk, used = [], [], []
            for c in pool:
                if not (isinstance(c, BBin) and c.op == "="):
                    continue
                ta, na, ea = _key_side(c.left)
                tb, nb, eb = _key_side(c.right)
                if ta is None or tb is None:
                    continue
                if ta in left_tables and tb == right:
                    lk.append(_key_name(ta, na, ea))
                    rk.append(_key_name(tb, nb, eb))
                    used.append(c)
                elif tb in left_tables and ta == right:
                    lk.append(_key_name(tb, nb, eb))
                    rk.append(_key_name(ta, na, ea))
                    used.append(c)
            return lk, rk, used

        ordered = []  # (alias, join_type, on_conjuncts)
        for alias, jt, on in explicit_joins:
            ordered.append((alias, jt, split_conjuncts(on) if on is not None else []))

        def _has_equi_keys(pool, left_tables: set, right: str) -> bool:
            """Dry-run of extract_equi_keys (no computed-key naming)."""
            for c in pool:
                if not (isinstance(c, BBin) and c.op == "="):
                    continue
                ta, _, _ = _key_side(c.left)
                tb, _, _ = _key_side(c.right)
                if ta is None or tb is None:
                    continue
                if ((ta in left_tables and tb == right)
                        or (tb in left_tables and ta == right)):
                    return True
            return False

        alias_table = dict(tables)

        def _rc(alias: str) -> float:
            return self.catalog.row_count(alias_table[alias])

        # LEFT JOINs whose ON references only the inner tables (the
        # decorrelated __exists/__sc derived joins, and plain
        # fact LEFT dim) pin to the TAIL, freeing the inner prefix
        # for cost-based reordering — without this, one decorrelated
        # subquery would force the whole FROM list into syntax order
        # (q2's five-table outer join graph is unorderable that way)
        pinned_lefts = []
        if ordered and not all(jt in ("inner", "cross")
                               for _, jt, _ in ordered):
            inners = [e for e in ordered if e[1] in ("inner", "cross")]
            lefts = [e for e in ordered if e[1] == "left"]
            if len(inners) + len(lefts) == len(ordered) and lefts:
                inner_aliases = {tables[0][0]} | {e[0] for e in inners}
                left_aliases = {e[0] for e in lefts}
                ok = True
                for la, _, lon in lefts:
                    for c in lon:
                        if not tables_of(c) <= inner_aliases | {la}:
                            ok = False  # left ON sees another left
                for _, _, oc in inners:
                    for c in oc:
                        if tables_of(c) & left_aliases:
                            ok = False  # inner keyed on a left output
                if ok:
                    # every inner must stay equi-reachable WITHOUT the
                    # left aliases: a WHERE key routed through a left
                    # table (FROM a LEFT b, c WHERE c.x = b.y) would
                    # otherwise strand the inner once lefts move to
                    # the tail
                    pool_noleft = [
                        c for c in conjuncts
                        if not (tables_of(c) & left_aliases)]
                    for _, _, oc in inners:
                        pool_noleft += oc
                    sim = {tables[0][0]}
                    rem = [e[0] for e in inners]
                    while rem and ok:
                        nxt = next((a for a in rem if _has_equi_keys(
                            pool_noleft, sim, a)), None)
                        if nxt is None:
                            ok = False
                        else:
                            sim.add(nxt)
                            rem.remove(nxt)
                if ok:
                    pinned_lefts = lefts
                    ordered = inners

        # Join ordering. Preferred: the memoized cost-based search
        # (sql/memo.py — the compact analogue of opt/xform's
        # exploration + costing), which chooses BOTH the probe root
        # and the build order over all connected left-deep plans.
        # Fallback: the greedy smallest-next heuristic.
        memo_done = False
        if ordered and self.use_memo \
                and len(tables) <= self.MEMO_MAX_TABLES \
                and all(jt in ("inner", "cross")
                        for _, jt, _ in ordered):
            res = self._memo_order(tables, ordered, conjuncts,
                                   alias_table, tables_of, _key_side)
            if res is not None:
                self.last_memo = res
                pool_all = [c for _, _, oc in ordered for c in oc]
                node = scans[res.root]
                probe_root = res.root
                joined = {res.root}
                # inner-join ON conditions pool with WHERE (identical
                # semantics); each reordered step draws its keys there
                remaining_conjuncts = list(conjuncts) + pool_all
                ordered = [(a, "inner", []) for a in res.order]
                memo_done = True
        if ordered and not memo_done and all(
                jt in ("inner", "cross") for _, jt, _ in ordered):
            remaining = list(ordered)
            reordered = []
            sim_joined = set(joined)
            pool_all = list(conjuncts)
            ok = True
            while remaining:
                joinable = [
                    e for e in remaining
                    if _has_equi_keys(e[2] + pool_all, sim_joined, e[0])]
                if not joinable:
                    ok = False  # fall back to syntax order
                    break
                pick = min(joinable, key=lambda e: _rc(e[0]))
                reordered.append(pick)
                remaining.remove(pick)
                sim_joined.add(pick[0])
            if ok:
                ordered = reordered
            # Build-side selection for the FIRST join: hash joins want
            # the SMALL side as the build, but a build's keys must be
            # unique (ops/join.py) — so only swap when the smaller
            # side's keys are verified unique via the store's exact
            # probe. If the syntax probe (root) is the smaller side,
            # swap roles.
            if ordered:
                first_alias, first_jt, first_on = ordered[0]
                root = tables[0][0]
                # a zero row count means "no local data here" (e.g. a
                # DistSQL gateway whose rows live on data nodes), not
                # "empty table" — no signal, keep syntax order
                if (first_jt in ("inner", "cross")
                        and 0 < _rc(root) < _rc(first_alias)
                        and self._keys_unique(
                            root, alias_table[root],
                            first_on + conjuncts, {first_alias},
                            _key_side, scans)):
                    node = scans[first_alias]
                    joined = {first_alias}
                    ordered[0] = (root, first_jt, first_on)
                    probe_root = first_alias

        ordered = ordered + pinned_lefts
        for alias, jt, on_conj in ordered:
            # LEFT JOIN must not consume WHERE conjuncts as join keys —
            # ON and WHERE have different outer-join semantics
            pool = on_conj + (remaining_conjuncts if jt != "left" else [])
            lk, rk, used = extract_equi_keys(pool, joined, alias)
            if lk and jt == "cross":
                # comma-join with equality predicates in WHERE -> hash join
                jt = "inner"
            if not lk:
                raise PlanError(
                    f"no equality join condition for {alias} "
                    "(cartesian products unsupported)")
            for u in used:
                if u in remaining_conjuncts:
                    remaining_conjuncts.remove(u)
            residual = [c for c in on_conj if c not in used]
            build = scans[alias]
            build_local = []
            if jt == "left":
                # residual ON conjuncts on the build side filter which
                # rows can MATCH (NULL-extension still happens) — push
                # into the build scan; cross-side residuals would need
                # per-pair evaluation inside the join
                both_sided = [c for c in residual if tables_of(c) != {alias}]
                if both_sided:
                    raise PlanError(
                        "LEFT JOIN ON conditions across both sides "
                        "(beyond equality keys) not supported yet")
                build_local = residual
                residual = []
            # build-side single-table WHERE conjuncts push into the build
            # scan (for LEFT joins, WHERE stays above the join: filtering
            # the build scan would wrongly null-extend filtered matches)
            if jt != "left":
                wl = [c for c in remaining_conjuncts
                      if tables_of(c) == {alias}]
                for c in wl:
                    remaining_conjuncts.remove(c)
                build_local += wl
            if build_local:
                build.filter = and_all(
                    ([build.filter] if build.filter is not None else [])
                    + build_local)
            payload = [b.batch_name for b in scope.tables[alias].values()]
            pack = [b.batch_name for b in scope.tables[alias].values()
                    if b.dictionary is not None]
            node = plan.HashJoin(node, build, lk, rk, payload, jt,
                                 pack_payload=pack)
            joined.add(alias)
            # residual ON conjuncts of inner joins are plain filters
            remaining_conjuncts.extend(residual)

        # remaining single-table conjuncts on the probe root push into scan
        root_alias = probe_root
        root_local = [c for c in remaining_conjuncts
                      if tables_of(c) <= {root_alias}]
        for c in root_local:
            remaining_conjuncts.remove(c)
        if root_local:
            scans[root_alias].filter = and_all(
                ([scans[root_alias].filter] if scans[root_alias].filter
                 is not None else []) + root_local)
        if remaining_conjuncts:
            node = plan.Filter(node, and_all(remaining_conjuncts))

        # ---- SELECT items & aggregation ------------------------------------
        has_group = bool(sel.group_by)
        # expand stars; disambiguate duplicate output names (the batch is
        # name-keyed, so two items named "sum" would silently collapse)
        items: list[tuple[str, ast.Expr]] = []
        seen_names: dict[str, int] = {}

        def uniq(name: str) -> str:
            k = seen_names.get(name, 0)
            seen_names[name] = k + 1
            return name if k == 0 else f"{name}_{k}"

        for it in sel.items:
            if it.star:
                for alias, _ in tables:
                    for colname, b in scope.tables[alias].items():
                        items.append((uniq(colname),
                                      ast.ColumnRef(colname, alias)))
            else:
                name = it.alias or _default_name(it.expr)
                items.append((uniq(name), it.expr))

        group_exprs: list[tuple[str, BExpr]] = []
        if has_group:
            item_by_name = {n: e for n, e in items}
            for i, g in enumerate(sel.group_by):
                # allow GROUP BY <position> and GROUP BY <alias>
                if isinstance(g, ast.Literal) and isinstance(g.value, int):
                    name, expr = items[g.value - 1]
                    bexpr = binder.bind(expr)
                elif isinstance(g, ast.ColumnRef) and g.table is None:
                    try:
                        bexpr = binder.bind(g)  # real columns win
                        name = _default_name(g)
                    except BindError:
                        if g.name not in item_by_name:
                            raise
                        bexpr = binder.bind(item_by_name[g.name])
                        name = g.name
                else:
                    bexpr = binder.bind(g)
                    name = _default_name(g)
                group_exprs.append((f"g{i}:{name}", bexpr))

        bound_items: list[tuple[str, BExpr]] = []
        any_agg = False
        binder._collect_windows = not has_group  # windows over raw rows
        try:
            for name, expr in items:
                b = binder.bind_with_aggs(expr)
                b = _encode_const_string_item(b)
                bound_items.append((name, b))
                if any(isinstance(n, BAggRef) for n in walk(b)):
                    any_agg = True
        finally:
            binder._collect_windows = False
        if binder.windows and (has_group or binder.aggs):
            raise PlanError(
                "window functions over grouped queries not supported yet "
                "(wrap the GROUP BY in a subquery)")

        having_b = None
        if sel.having is not None:
            having_b = binder.bind_with_aggs(sel.having)

        meta = plan.OutputMeta()

        if has_group or binder.aggs:
            # FD reduction: engage only when it unlocks the dense
            # segment-sum strategy the hash path couldn't use — the
            # hash path handles multi-key groups fine as-is
            fd_repl = []
            if len(group_exprs) >= 2 and self._static_group_bound(
                    group_exprs, scope, tables)[0] == 0:
                n_aggs = len(binder.aggs)
                reduced, repl = self._reduce_fd_group_keys(
                    group_exprs, node, tables, binder)
                if repl and self._static_group_bound(
                        reduced, scope, tables)[0] > 0:
                    group_exprs, fd_repl = reduced, repl
                else:
                    del binder.aggs[n_aggs:]  # undo speculative aggs
            # rewrite grouped output exprs: replace group-expr occurrences
            # with group column refs
            rewritten = []
            for name, b in bound_items:
                b2 = _replace_group_refs(b, group_exprs)
                if fd_repl:
                    b2 = _substitute(b2, fd_repl)
                rewritten.append((name, b2))
            if having_b is not None:
                having_b = _replace_group_refs(having_b, group_exprs)
                if fd_repl:
                    having_b = _substitute(having_b, fd_repl)
            for name, b in rewritten:
                _check_agg_valid(b, group_exprs)
            max_groups, dims, glos = self._static_group_bound(
                group_exprs, scope, tables)
            node = plan.Aggregate(node, group_exprs, binder.aggs,
                                  having_b, rewritten, max_groups, dims,
                                  group_lo=glos)
            out_names = [n for n, _ in rewritten]
            out_types = [b.type for _, b in rewritten]
        elif sel.distinct:
            node = plan.Project(node, bound_items)
            group_exprs = [(n, BCol(n, b.type)) for n, b in bound_items]
            dmax, ddims, dlos = self._static_group_bound(
                group_exprs, scope, tables)
            node = plan.Aggregate(node, group_exprs, [], None,
                                  [(n, BCol(g, b.type))
                                   for (n, b), (g, _) in
                                   zip(bound_items, group_exprs)],
                                  dmax, ddims, group_lo=dlos)
            out_names = [n for n, _ in bound_items]
            out_types = [b.type for _, b in bound_items]
        else:
            if binder.windows:
                node = plan.Window(node, binder.windows)
            node = plan.Project(node, bound_items)
            out_names = [n for n, _ in bound_items]
            out_types = [b.type for _, b in bound_items]

        # ---- ORDER BY / LIMIT ----------------------------------------------
        if sel.order_by:
            keys = []
            grouped = has_group or bool(binder.aggs)
            for i, ob in enumerate(sel.order_by):
                if isinstance(ob.expr, ast.Literal) and isinstance(ob.expr.value, int):
                    keys.append((out_names[ob.expr.value - 1], ob.desc,
                                 ob.nulls_first))
                elif isinstance(ob.expr, ast.ColumnRef) \
                        and ob.expr.name in out_names:
                    keys.append((ob.expr.name, ob.desc,
                                 ob.nulls_first))
                elif not grouped and not sel.distinct \
                        and isinstance(node, plan.Project):
                    # hidden sort column (ordering by a non-output expr)
                    b = binder.bind(ob.expr)
                    if not b.type.is_orderable:
                        # same guard as the visible-key check below: a
                        # hidden datum key would silently sort by
                        # dictionary insertion code
                        raise PlanError(
                            f"ORDER BY on {b.type} is not supported")
                    hname = f"__ord{i}"
                    node.items.append((hname, b))
                    keys.append((hname, ob.desc, ob.nulls_first))
                    # a hidden dict-encoded string key must still sort
                    # by value rank, not code (sort_batch consults
                    # meta.dictionaries by key name)
                    if b.type.family == Family.STRING:
                        d = self._find_dict_for_output(
                            hname, node.items, [], scope, node)
                        if d is not None:
                            meta.dictionaries[hname] = d
                else:
                    raise PlanError("ORDER BY must reference output columns")
            for key in keys:
                kname = key[0]
                if kname in out_names:
                    kty = out_types[out_names.index(kname)]
                    if not kty.is_orderable:
                        # codes rank by dictionary insertion (and text
                        # rank diverges from pg's elementwise array
                        # order: text says {9} > {10}) — reject rather
                        # than silently misorder
                        raise PlanError(
                            f"ORDER BY on {kty} is not supported")
            node = plan.Sort(node, keys)
        if sel.limit is not None or sel.offset is not None:
            node = plan.Limit(node, sel.limit, sel.offset or 0)

        meta.names = out_names
        meta.types = out_types
        # attach dictionaries for string outputs
        for name, ty in zip(out_names, out_types):
            if ty.uses_dictionary:
                d = self._find_dict_for_output(name, bound_items, group_exprs,
                                               scope, node)
                if d is not None:
                    meta.dictionaries[name] = d
        from .rules import RuleTrace
        from .rules import normalize as normalize_rules
        trace = self._trace if self._trace is not None else RuleTrace()
        if self.rules_on:
            node = normalize_rules(node, trace)
        else:
            # rule plane off (SET optimizer_rules = 'off'): the two
            # load-bearing passes still run, untraced
            from .pushdown import push_build_exprs
            push_build_exprs(node)
            plan.prune_scan_columns(node)
        meta.rule_trace = trace
        meta.access_paths = dict(self.access_paths)
        meta.memo = self.last_memo
        return node, meta

    MAX_INT_GROUP_SPAN = 1 << 12
    # a SINGLE int key may span much further: one dense scatter-add
    # buffer per agg at 2M slots is ~16MB HBM and runs in ~1ms on a
    # v5e, where the while-loop hash build takes seconds (q3's
    # 262K-group GROUP BY l_orderkey: measured 0.1-3.5ms dense vs
    # ~11s hashed, with compile 1s vs 385s)
    MAX_INT_GROUP_SPAN_SINGLE = 1 << 21

    def _reduce_fd_group_keys(self, group_exprs, node, tables, binder):
        """Functional-dependency reduction of GROUP BY keys (the one
        FD the reference's optimizer derives that dominates star
        queries, pkg/sql/opt/props/func_dep.go): a group key that is a
        column of a table equi-joined on its single-column PRIMARY KEY
        to another group key is constant within every group of that
        other key — drop it from the keys and carry its value as a
        max() aggregate instead. TPC-H q3's GROUP BY l_orderkey,
        o_orderdate, o_shippriority (orders PK-joined on o_orderkey =
        l_orderkey) collapses to the ONE dense int key l_orderkey.

        Returns (reduced_group_exprs, [(orig_expr, BAggRef), ...]);
        the second list is empty when nothing reduced."""
        from .bound import BAggRef, BoundAgg
        if len(group_exprs) < 2:
            return group_exprs, []
        alias_to_table = dict(tables or [])

        # directed equi-join derivations from the planned FROM tree:
        # (mine, other) means "if `other`'s value is fixed per group
        # and `mine` is unique in its table, `mine`'s whole row is
        # fixed". Inner joins derive both ways; LEFT joins only pin
        # the BUILD (right) side — an unmatched probe row carries NULL
        # build values, so the probe cannot be inferred from them.
        derivs = []

        def _collect(n):
            if isinstance(n, plan.HashJoin):
                if n.join_type == "inner":
                    for lk, rk in zip(n.left_keys, n.right_keys):
                        derivs.append((lk, rk))
                        derivs.append((rk, lk))
                elif n.join_type == "left":
                    for lk, rk in zip(n.left_keys, n.right_keys):
                        derivs.append((rk, lk))
                _collect(n.left)
                _collect(n.right)
            elif hasattr(n, "child"):
                _collect(n.child)
        _collect(node)
        if not derivs:
            return group_exprs, []

        def _is_unique(alias, qual_col):
            """qual_col ("alias.col") is unique within its table:
            single-column PK, or the SNAPSHOT-AWARE uniqueness probe
            (TPC-H schemas declare no PKs; o_orderkey is unique by
            data). The live-rows distinct probe is NOT enough here:
            an AS OF read could see rows the current generation
            deleted, merging distinct groups."""
            t = alias_to_table.get(alias)
            if t is None:
                return False
            sch = self.catalog.schemas.get(t)
            col = qual_col.split(".", 1)[1]
            if sch is not None and sch.primary_key == [col]:
                return True
            fn = self.catalog.keys_unique_fn
            if fn is None:
                return False
            try:
                return bool(fn(t, (col,)))
            except KeyError:
                return False

        def _alias(q):
            return q.split(".", 1)[0]

        def _pinned(keys: set) -> set:
            """Aliases whose row is constant within each group of
            `keys` — the TRANSITIVE closure of the reference's
            func_dep derivation (q18: o_orderkey pins orders, orders'
            o_custkey pins customer through c_custkey, so c_name and
            c_custkey both drop). A column's value is fixed when it
            is a group key or any column of a pinned alias."""
            pinned = set()
            for kc in keys:
                if _is_unique(_alias(kc), kc):
                    pinned.add(_alias(kc))
            changed = True
            while changed:
                changed = False
                for mine, other in derivs:
                    al = _alias(mine)
                    if al in pinned:
                        continue
                    if (other in keys or _alias(other) in pinned) \
                            and _is_unique(al, mine):
                        pinned.add(al)
                        changed = True
            return pinned

        names = [ge.name if isinstance(ge, BCol) and "." in ge.name
                 else None for _, ge in group_exprs]
        kept_flag = [True] * len(group_exprs)
        # try dropping dictionary-coded keys first (they block the
        # dense strategy hardest), then the rest in order; a key drops
        # only if the keys REMAINING afterwards still pin its alias
        order = sorted(range(len(group_exprs)),
                       key=lambda i: (0 if names[i] is not None and
                                      group_exprs[i][1].type
                                      .uses_dictionary else 1, i))
        for i in order:
            nm = names[i]
            if nm is None:
                continue
            remaining = {names[j] for j in range(len(group_exprs))
                         if kept_flag[j] and j != i
                         and names[j] is not None}
            if remaining and _alias(nm) in _pinned(remaining):
                kept_flag[i] = False
        kept = []
        repl = []
        for flag, (gname, ge) in zip(kept_flag, group_exprs):
            if flag:
                kept.append((gname, ge))
            else:
                # "any": per-group-constant by construction — the
                # scatter-SET kernel, not the (64-bit-emulated, ~12x
                # slower) scatter-max (ops/agg.py group_any)
                binder.aggs.append(BoundAgg("any", ge, type=ge.type))
                repl.append((ge, BAggRef(len(binder.aggs) - 1,
                                         ge.type)))
        if not repl or not kept:
            return group_exprs, []
        return kept, repl

    def _static_group_bound(self, group_exprs, scope: Scope,
                            tables=None):
        """If every group key is a dict-encoded column, bool, or an int
        column with a small PROVEN value range, the group count is
        bounded by the product of code-space sizes — the planner then
        uses dense codes + segment_sum with a static size (TPC-H Q1: 4;
        SSB's GROUP BY d_year) instead of the while-loop hash table.
        Returns (bound, dims, los); bound 0 when unbounded. Each dim
        gets one extra NULL slot at compile time; los are per-dim value
        offsets (code = value - lo)."""
        alias_to_table = dict(tables or [])
        bound = 1
        dims = []
        los = []
        for _, e in group_exprs:
            if isinstance(e, BCol) and e.type.uses_dictionary:
                d = self._dict_by_batch_name(e.name, scope)
                if d is None:
                    return 0, [], []
                dims.append(max(len(d), 1))
                los.append(0)
            elif isinstance(e, BCol) and e.type.family == Family.BOOL:
                dims.append(2)
                los.append(0)
            else:
                if isinstance(e, BCol) and e.type.family == Family.INT \
                        and self.catalog.int_range_fn is not None \
                        and "." in e.name:
                    alias, col = e.name.split(".", 1)
                    tname = alias_to_table.get(alias)
                    try:
                        r = (self.catalog.int_range_fn(tname, col)
                             if tname else None)
                    except KeyError:  # renamed/computed: not stored
                        r = None
                    if r is None:
                        return 0, [], []
                    lo, hi, _n = r
                else:
                    # GROUP BY extract(year FROM datecol): the stored
                    # column's value range bounds the year span
                    # (TPC-H q7/q8/q9's o_year — 7 years, not a hash
                    # table)
                    yr = self._year_extract_range(e, alias_to_table)
                    if yr is None:
                        return 0, [], []
                    lo, hi = yr
                span = hi - lo + 1
                span_cap = (self.MAX_INT_GROUP_SPAN_SINGLE
                            if len(group_exprs) == 1
                            else self.MAX_INT_GROUP_SPAN)
                if span > span_cap:
                    return 0, [], []
                dims.append(int(span))
                los.append(int(lo))
            bound *= dims[-1] + 1
            if bound > ((1 << 21) + 2 if len(group_exprs) == 1
                        else 1 << 16):
                return 0, [], []
        return bound, dims, los

    def _year_extract_range(self, e, alias_to_table):
        """(lo_year, hi_year) when e is extract(year FROM <stored
        date/timestamp column>) and the column's value range is
        provable, else None."""
        from .bound import BExtract
        if not (isinstance(e, BExtract) and e.part == "year"
                and isinstance(e.expr, BCol)
                and e.expr.type.family in (Family.DATE,
                                           Family.TIMESTAMP)
                and self.catalog.int_range_fn is not None
                and "." in e.expr.name):
            return None
        alias, col = e.expr.name.split(".", 1)
        tname = alias_to_table.get(alias)
        if tname is None:
            return None
        try:
            r = self.catalog.int_range_fn(tname, col)
        except KeyError:
            return None
        if r is None:
            return None
        lo, hi, _n = r
        if e.expr.type.family == Family.TIMESTAMP:
            lo, hi = lo // 86_400_000_000, hi // 86_400_000_000
        import datetime as _dt
        epoch = _dt.date(1970, 1, 1)
        return ((epoch + _dt.timedelta(days=int(lo))).year,
                (epoch + _dt.timedelta(days=int(hi))).year)

    def _dict_by_batch_name(self, name, scope: Scope):
        for t in scope.tables.values():
            for b in t.values():
                if b.batch_name == name:
                    return b.dictionary
        return None

    def _find_dict_for_output(self, name, bound_items, group_exprs, scope, node):
        for n, b in bound_items:
            if n != name:
                continue
            d = getattr(b, "dictionary", None)  # ad-hoc (CASE constants)
            if d is not None:
                return d
            if isinstance(b, BCol):
                d = self._dict_by_batch_name(b.name, scope)
                if d is not None:
                    return d
                # grouped output referencing a group column
                for gn, ge in group_exprs:
                    if b.name != gn:
                        continue
                    gd = getattr(ge, "dictionary", None)
                    if gd is not None:
                        return gd  # string-builtin transform output
                    if isinstance(ge, BCol):
                        return self._dict_by_batch_name(ge.name, scope)
        return None


def _encode_const_string_item(b: BExpr) -> BExpr:
    """A constant-string output item (SELECT 'lit' FROM t, or a folded
    string builtin like trim(' x ')) compiles to dictionary code 0 +
    an ad-hoc one-entry output dictionary — the same representation
    CASE gives its constant string branches (binder.bind_case)."""
    if isinstance(b, BConst) and b.type.uses_dictionary \
            and isinstance(b.value, str) \
            and getattr(b, "dictionary", None) is None:
        from ..storage.columnstore import Dictionary
        d = Dictionary()
        out = BConst(d.encode(b.value), b.type)
        out.dictionary = d
        return out
    return b


def _default_name(e: ast.Expr) -> str:
    if isinstance(e, ast.ColumnRef):
        return e.name
    if isinstance(e, ast.FuncCall):
        return e.name
    return "column"


def _replace_group_refs(e: BExpr, group_exprs) -> BExpr:
    """Replace occurrences of a group expression with a ref to the group
    output column (so post-agg projection sees [G]-shaped arrays)."""
    return _substitute(e, [(gexpr, BCol(gname, gexpr.type))
                           for gname, gexpr in group_exprs])


def _substitute(e: BExpr, pairs) -> BExpr:
    """Replace repr-equal occurrences of each (expr, replacement)."""
    for orig, repl in pairs:
        if repr(e) == repr(orig):
            return repl
    # recurse
    import copy
    e2 = copy.copy(e)
    from .bound import (BBetween, BCase, BCast, BCoalesce, BDictLookup,
                        BExtract, BInList, BIsNull, BUnary)
    if isinstance(e2, BBin):
        e2.left = _substitute(e2.left, pairs)
        e2.right = _substitute(e2.right, pairs)
    elif isinstance(e2, BUnary):
        e2.operand = _substitute(e2.operand, pairs)
    elif isinstance(e2, BBetween):
        e2.expr = _substitute(e2.expr, pairs)
        e2.lo = _substitute(e2.lo, pairs)
        e2.hi = _substitute(e2.hi, pairs)
    elif isinstance(e2, (BInList, BIsNull, BCast, BDictLookup, BDictRemap)):
        e2.expr = _substitute(e2.expr, pairs)
    elif isinstance(e2, BExtract):
        e2.expr = _substitute(e2.expr, pairs)
    elif isinstance(e2, BCase):
        e2.whens = [(_substitute(c, pairs), _substitute(v, pairs))
                    for c, v in e2.whens]
        if e2.else_ is not None:
            e2.else_ = _substitute(e2.else_, pairs)
    elif isinstance(e2, BCoalesce):
        e2.args = [_substitute(a, pairs) for a in e2.args]
    return e2


def _check_agg_valid(e: BExpr, group_exprs) -> None:
    """Every column in a grouped output must be a group col or inside an
    aggregate (the binder already folded aggregates into BAggRef)."""
    gnames = {n for n, _ in group_exprs}
    for n in walk(e):
        if isinstance(n, BCol) and n.name not in gnames:
            raise PlanError(
                f"column {n.name!r} must appear in GROUP BY or an aggregate")
