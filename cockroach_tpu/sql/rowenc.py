"""Row <-> KV encoding: SQL rows mapped onto the transactional KV plane.

The analogue of the reference's ``pkg/sql/rowenc`` (index key encoding,
``EncodeIndexKey``) and the value side of ``pkg/sql/row`` writers. Every
table row has exactly one KV pair on primary index 1:

    key   = /Table/<id>/1/<pk cols...>      (order-preserving, keys.py)
    value = null-bitmap + packed non-pk column values

Tables with no declared PRIMARY KEY get a hidden ``rowid`` key column
(the reference synthesizes a ``rowid INT DEFAULT unique_rowid()``
column the same way, pkg/sql/catalog/tabledesc). Rowids are allocated
by the storage layer (storage/columnstore.py) and threaded through
here as ``row["__rowid__"]``.

Values are "storage-logical": STRING columns travel as UTF-8 strings
(dictionary codes are store-local and must not leak into the
replicated KV plane); DECIMAL/DATE/TIMESTAMP are their physical int
forms (scaled int, epoch days, epoch micros) exactly as the column
store holds them.
"""

from __future__ import annotations

import struct

from ..storage import keys
from .types import Family, TableSchema

ROWID = "__rowid__"


class RowCodec:
    """Encode/decode rows of one table schema to KV pairs."""

    def __init__(self, schema: TableSchema):
        self.schema = schema
        self.table_id = schema.table_id
        self.pk_cols = list(schema.primary_key)
        self.synthetic_pk = not self.pk_cols
        # value columns: everything not in the pk (pk is recoverable
        # from the key; the reference likewise omits key cols from the
        # value, rowenc/valueside)
        self.value_cols = [c for c in schema.columns
                           if c.name not in self.pk_cols]

    # -- spans -------------------------------------------------------------
    def span(self) -> tuple[bytes, bytes]:
        p = keys.table_prefix(self.table_id)
        return p, keys.prefix_end(p)

    # -- keys --------------------------------------------------------------
    def pk_values(self, row: dict) -> tuple:
        if self.synthetic_pk:
            return (int(row[ROWID]),)
        return tuple(row[c] for c in self.pk_cols)

    def key(self, row: dict) -> bytes:
        return keys.table_key(self.table_id, self.pk_values(row))

    def key_from_pk(self, pk_vals: tuple) -> bytes:
        return keys.table_key(self.table_id, pk_vals)

    # -- values ------------------------------------------------------------
    def encode_value(self, row: dict) -> bytes:
        cols = self.value_cols
        nulls = 0
        buf = bytearray()
        for i, c in enumerate(cols):
            v = row.get(c.name)
            if v is None:
                nulls |= 1 << i
                continue
            f = c.type.family
            if f == Family.BOOL:
                buf += struct.pack(">B", 1 if v else 0)
            elif f == Family.FLOAT:
                buf += struct.pack(">d", float(v))
            elif f in (Family.STRING, Family.BYTES):
                raw = v.encode("utf-8") if isinstance(v, str) else bytes(v)
                buf += struct.pack(">I", len(raw)) + raw
            else:  # INT / DECIMAL / DATE / TIMESTAMP / INTERVAL: int64
                buf += struct.pack(">q", int(v))
        nb = (len(cols) + 7) // 8
        return nulls.to_bytes(nb, "little") + bytes(buf)

    def decode_value(self, b: bytes) -> dict:
        cols = self.value_cols
        nb = (len(cols) + 7) // 8
        nulls = int.from_bytes(b[:nb], "little")
        off = nb
        row: dict = {}
        for i, c in enumerate(cols):
            if nulls & (1 << i):
                row[c.name] = None
                continue
            f = c.type.family
            if f == Family.BOOL:
                row[c.name] = bool(b[off])
                off += 1
            elif f == Family.FLOAT:
                (row[c.name],) = struct.unpack_from(">d", b, off)
                off += 8
            elif f in (Family.STRING, Family.BYTES):
                (ln,) = struct.unpack_from(">I", b, off)
                off += 4
                raw = b[off:off + ln]
                off += ln
                row[c.name] = raw.decode("utf-8") if f == Family.STRING \
                    else raw
            else:
                (row[c.name],) = struct.unpack_from(">q", b, off)
                off += 8
        return row

    def decode_key(self, key: bytes) -> tuple:
        """Recover pk values from an encoded table key."""
        prefix = keys.table_prefix(self.table_id)
        if not key.startswith(prefix):
            raise ValueError(f"key {key!r} not in table {self.table_id}")
        off = len(prefix)
        out = []
        cols = ([None] if self.synthetic_pk
                else [self.schema.column(c) for c in self.pk_cols])
        for c in cols:
            fam = Family.INT if c is None else c.type.family
            if fam in (Family.STRING, Family.BYTES):
                v, off = keys.decode_bytes(key, off)
                out.append(v.decode("utf-8") if fam == Family.STRING else v)
            elif fam == Family.FLOAT:
                v, off = keys.decode_float(key, off)
                out.append(v)
            else:
                v, off = keys.decode_int(key, off)
                out.append(v)
        return tuple(out)

    def decode_row(self, key: bytes, value: bytes) -> dict:
        """Full row from a KV pair (pk cols from the key, rest from the
        value) — the cFetcher decode contract, colfetcher/cfetcher.go:668."""
        row = self.decode_value(value)
        pk = self.decode_key(key)
        if self.synthetic_pk:
            row[ROWID] = pk[0]
        else:
            for name, v in zip(self.pk_cols, pk):
                row[name] = v
        return row
