"""Row <-> KV encoding: SQL rows mapped onto the transactional KV plane.

The analogue of the reference's ``pkg/sql/rowenc`` (index key encoding,
``EncodeIndexKey``) and the value side of ``pkg/sql/row`` writers. Every
table row has exactly one KV pair on primary index 1:

    key   = /Table/<id>/1/<pk cols...>      (order-preserving, keys.py)
    value = null-bitmap + packed non-pk column values

Tables with no declared PRIMARY KEY get a hidden ``rowid`` key column
(the reference synthesizes a ``rowid INT DEFAULT unique_rowid()``
column the same way, pkg/sql/catalog/tabledesc). Rowids are allocated
by the storage layer (storage/columnstore.py) and threaded through
here as ``row["__rowid__"]``.

Values are "storage-logical": STRING columns travel as UTF-8 strings
(dictionary codes are store-local and must not leak into the
replicated KV plane); DECIMAL/DATE/TIMESTAMP are their physical int
forms (scaled int, epoch days, epoch micros) exactly as the column
store holds them.
"""

from __future__ import annotations

import struct

from ..storage import keys
from .types import Family, TableSchema

ROWID = "__rowid__"


class RowCodec:
    """Encode/decode rows of one table schema to KV pairs."""

    def __init__(self, schema: TableSchema):
        self.schema = schema
        self.table_id = schema.table_id
        self.pk_cols = list(schema.primary_key)
        self.synthetic_pk = not self.pk_cols
        # value columns: everything not in the pk (pk is recoverable
        # from the key; the reference likewise omits key cols from the
        # value, rowenc/valueside)
        self.value_cols = [c for c in schema.columns
                           if c.name not in self.pk_cols]
        # precomputed wire tags (decode_value is the per-row hot path)
        self._tag_of = {
            c.name: ((b"#%d" % c.cid) if getattr(c, "cid", 0)
                     else c.name.encode("utf-8"))
            for c in self.value_cols}
        self._col_by_tag = {t.decode("utf-8"): self.schema.column(n)
                            for n, t in self._tag_of.items()}

    # -- spans -------------------------------------------------------------
    def span(self) -> tuple[bytes, bytes]:
        p = keys.table_prefix(self.table_id)
        return p, keys.prefix_end(p)

    # -- keys --------------------------------------------------------------
    def pk_values(self, row: dict) -> tuple:
        if self.synthetic_pk:
            return (int(row[ROWID]),)
        return tuple(row[c] for c in self.pk_cols)

    def key(self, row: dict) -> bytes:
        return keys.table_key(self.table_id, self.pk_values(row))

    def key_from_pk(self, pk_vals: tuple) -> bytes:
        return keys.table_key(self.table_id, pk_vals)

    # -- values ------------------------------------------------------------
    # Self-describing tagged encoding: each present (non-null) column
    # is written as [tag_len:u8][tag][payload_len:u32][payload].
    # Absent columns decode as NULL, unknown tags are skipped — so
    # rows written under an older schema version decode correctly
    # after ADD/DROP COLUMN without a KV rewrite, exactly why the
    # reference tags value-side datums with column ids
    # (pkg/sql/rowenc/valueside/encode.go). The tag is the stable
    # catalog column id ("#<cid>") when the schema carries one —
    # immune to DROP + re-ADD of a name with a different type — and
    # the column name for catalog-less schemas (tests, bulk loaders).
    def encode_value(self, row: dict) -> bytes:
        buf = bytearray()
        n = 0
        for c in self.value_cols:
            v = row.get(c.name)
            if v is None:
                continue
            f = c.type.family
            if f == Family.BOOL:
                payload = struct.pack(">B", 1 if v else 0)
            elif f == Family.FLOAT:
                payload = struct.pack(">d", float(v))
            elif f in (Family.STRING, Family.BYTES, Family.ARRAY,
                       Family.JSON):
                # datum families store their canonical text
                payload = v.encode("utf-8") if isinstance(v, str) \
                    else bytes(v)
            else:  # INT / DECIMAL / DATE / TIMESTAMP / INTERVAL: int64
                payload = struct.pack(">q", int(v))
            tag = self._tag_of[c.name]
            buf += struct.pack(">B", len(tag)) + tag
            buf += struct.pack(">I", len(payload)) + payload
            n += 1
        return struct.pack(">H", n) + bytes(buf)

    def decode_value(self, b: bytes) -> dict:
        row: dict = {c.name: None for c in self.value_cols}
        (n,) = struct.unpack_from(">H", b, 0)
        off = 2
        by_tag = self._col_by_tag
        for _ in range(n):
            nl = b[off]
            off += 1
            tag = b[off:off + nl].decode("utf-8")
            off += nl
            (pl,) = struct.unpack_from(">I", b, off)
            off += 4
            payload = b[off:off + pl]
            off += pl
            c = by_tag.get(tag)
            if c is None:
                continue   # column dropped since this row was written
            f = c.type.family
            if f == Family.BOOL:
                row[c.name] = bool(payload[0])
            elif f == Family.FLOAT:
                (row[c.name],) = struct.unpack(">d", payload)
            elif f in (Family.STRING, Family.ARRAY, Family.JSON):
                row[c.name] = payload.decode("utf-8")
            elif f == Family.BYTES:
                row[c.name] = payload
            else:
                (row[c.name],) = struct.unpack(">q", payload)
        return row

    def decode_key(self, key: bytes) -> tuple:
        """Recover pk values from an encoded table key."""
        prefix = keys.table_prefix(self.table_id)
        if not key.startswith(prefix):
            raise ValueError(f"key {key!r} not in table {self.table_id}")
        off = len(prefix)
        out = []
        cols = ([None] if self.synthetic_pk
                else [self.schema.column(c) for c in self.pk_cols])
        for c in cols:
            fam = Family.INT if c is None else c.type.family
            if fam in (Family.STRING, Family.BYTES):
                v, off = keys.decode_bytes(key, off)
                out.append(v.decode("utf-8") if fam == Family.STRING else v)
            elif fam == Family.FLOAT:
                v, off = keys.decode_float(key, off)
                out.append(v)
            else:
                v, off = keys.decode_int(key, off)
                out.append(v)
        return tuple(out)

    def decode_row(self, key: bytes, value: bytes) -> dict:
        """Full row from a KV pair (pk cols from the key, rest from the
        value) — the cFetcher decode contract, colfetcher/cfetcher.go:668."""
        row = self.decode_value(value)
        pk = self.decode_key(key)
        if self.synthetic_pk:
            row[ROWID] = pk[0]
        else:
            for name, v in zip(self.pk_cols, pk):
                row[name] = v
        return row
