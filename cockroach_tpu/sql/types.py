"""SQL type system, mapped to TPU-friendly physical representations.

The reference models SQL types in ``pkg/sql/types`` (oid-compatible
``types.T``) and stores columnar data in per-type Go slices
(``pkg/col/coldata/native_types.go``). TPUs have no decimal or string
units, so every SQL type here is lowered to a fixed-width numeric
*physical* representation that XLA can tile onto the VPU/MXU:

  BOOL       -> bool_
  INT2/4/8   -> int32 / int64
  FLOAT8     -> float64 (float32 on request)
  DECIMAL    -> scaled int64 fixed-point (value * 10**scale); the
                reference stores apd.Decimal structs per element and
                monomorphizes decimal kernels (coldata/native_types.go:33);
                we instead pick a scale at ingest and do integer math.
  DATE       -> int32 days since unix epoch
  TIMESTAMP  -> int64 microseconds since unix epoch
  STRING     -> int32 dictionary code (dictionary lives host-side) for
                low-cardinality columns; general strings use a flat
                (offsets:int32, data:uint8) arena like coldata.Bytes
                (pkg/col/coldata/bytes.go).
  INTERVAL   -> int64 microseconds
  ARRAY/JSON -> int32 dictionary code over the value's CANONICAL text
                serialization (pg array literal text / sorted-key
                JSON). The reference keeps these as datum-backed
                vectors even in its vectorized engine
                (coldata/datum_vec.go) — per-element host objects.
                Canonical text instead makes value equality equal
                CODE equality, so GROUP BY/DISTINCT/joins on arrays
                and jsonb compile to the same int32 device programs
                as dictionary strings, and per-row operators
                (j->>'k', arr[i], @>) become host-precomputed LUTs
                over the small dictionary — one gather (or one-hot
                MXU matmul) on device instead of per-row host calls.

NULLs are carried as a separate validity bitmap per column (True=valid),
matching coldata's Nulls (pkg/col/coldata/nulls.go) and Arrow.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

import numpy as np


class Family(enum.Enum):
    BOOL = "bool"
    INT = "int"
    FLOAT = "float"
    DECIMAL = "decimal"
    DATE = "date"
    TIMESTAMP = "timestamp"
    INTERVAL = "interval"
    STRING = "string"
    BYTES = "bytes"
    ARRAY = "array"
    JSON = "json"
    UNKNOWN = "unknown"  # NULL literal before type inference


@dataclass(frozen=True)
class SQLType:
    family: Family
    width: int = 64  # bits for INT/FLOAT
    precision: int = 0  # DECIMAL precision
    scale: int = 0  # DECIMAL scale (digits after point)
    elem: Optional["SQLType"] = None  # ARRAY element type

    # -- constructors ------------------------------------------------------
    @staticmethod
    def bool_() -> "SQLType":
        return SQLType(Family.BOOL)

    @staticmethod
    def int_(width: int = 64) -> "SQLType":
        return SQLType(Family.INT, width=width)

    @staticmethod
    def float_(width: int = 64) -> "SQLType":
        return SQLType(Family.FLOAT, width=width)

    @staticmethod
    def decimal(precision: int = 19, scale: int = 2) -> "SQLType":
        return SQLType(Family.DECIMAL, precision=precision, scale=scale)

    @staticmethod
    def date() -> "SQLType":
        return SQLType(Family.DATE, width=32)

    @staticmethod
    def timestamp() -> "SQLType":
        return SQLType(Family.TIMESTAMP)

    @staticmethod
    def interval() -> "SQLType":
        return SQLType(Family.INTERVAL)

    @staticmethod
    def string() -> "SQLType":
        return SQLType(Family.STRING, width=32)

    @staticmethod
    def bytes_() -> "SQLType":
        return SQLType(Family.BYTES)

    @staticmethod
    def array(elem: "SQLType") -> "SQLType":
        return SQLType(Family.ARRAY, width=32, elem=elem)

    @staticmethod
    def json() -> "SQLType":
        return SQLType(Family.JSON, width=32)

    @staticmethod
    def unknown() -> "SQLType":
        return SQLType(Family.UNKNOWN)

    # -- physical lowering -------------------------------------------------
    @property
    def np_dtype(self) -> np.dtype:
        f = self.family
        if f == Family.BOOL:
            return np.dtype(np.bool_)
        if f == Family.INT:
            return np.dtype(np.int32) if self.width <= 32 else np.dtype(np.int64)
        if f == Family.FLOAT:
            return np.dtype(np.float32) if self.width <= 32 else np.dtype(np.float64)
        if f == Family.DECIMAL:
            return np.dtype(np.int64)
        if f == Family.DATE:
            return np.dtype(np.int32)
        if f in (Family.TIMESTAMP, Family.INTERVAL):
            return np.dtype(np.int64)
        if f == Family.STRING:
            return np.dtype(np.int32)  # dictionary code
        if f in (Family.ARRAY, Family.JSON):
            return np.dtype(np.int32)  # canonical-text dictionary code
        if f == Family.BYTES:
            return np.dtype(np.uint8)  # arena bytes
        if f == Family.UNKNOWN:
            return np.dtype(np.int32)
        raise TypeError(f"no physical dtype for {self}")

    @property
    def is_numeric(self) -> bool:
        return self.family in (Family.INT, Family.FLOAT, Family.DECIMAL)

    @property
    def is_orderable(self) -> bool:
        # pg defines elementwise array / jsonb ordering; our codes
        # order by insertion, so comparisons beyond =/!= are rejected
        # cleanly at bind time rather than silently misordered
        return self.family not in (Family.BYTES, Family.ARRAY,
                                   Family.JSON)

    @property
    def uses_dictionary(self) -> bool:
        """Physical column is an int32 code into a host dictionary
        (STRING: the text itself; ARRAY/JSON: canonical text)."""
        return self.family in (Family.STRING, Family.ARRAY, Family.JSON)

    def __str__(self) -> str:
        f = self.family
        if f == Family.INT:
            return f"INT{self.width // 8}"
        if f == Family.FLOAT:
            return "FLOAT4" if self.width <= 32 else "FLOAT8"
        if f == Family.DECIMAL:
            return f"DECIMAL({self.precision},{self.scale})"
        if f == Family.ARRAY:
            return f"{self.elem}[]"
        if f == Family.JSON:
            return "JSONB"
        return f.name


# Canonical instances
BOOL = SQLType.bool_()
INT2 = SQLType.int_(16)
INT4 = SQLType.int_(32)
INT8 = SQLType.int_(64)
FLOAT4 = SQLType.float_(32)
FLOAT8 = SQLType.float_(64)
DATE = SQLType.date()
TIMESTAMP = SQLType.timestamp()
INTERVAL = SQLType.interval()
STRING = SQLType.string()
BYTES = SQLType.bytes_()
JSONB = SQLType.json()
UNKNOWN = SQLType.unknown()


def common_numeric_type(a: SQLType, b: SQLType) -> SQLType:
    """Binary-op result-type resolution (a tiny version of the reference's
    cast matrix in pkg/sql/sem/cast)."""
    if a.family == Family.UNKNOWN:
        return b
    if b.family == Family.UNKNOWN:
        return a
    fams = {a.family, b.family}
    if Family.FLOAT in fams:
        return FLOAT8
    if Family.DECIMAL in fams:
        scale = max(a.scale if a.family == Family.DECIMAL else 0,
                    b.scale if b.family == Family.DECIMAL else 0)
        return SQLType.decimal(scale=scale)
    if fams == {Family.INT}:
        return SQLType.int_(max(a.width, b.width))
    if Family.DATE in fams and Family.INT in fams:
        return DATE  # date +/- int days
    if Family.TIMESTAMP in fams and Family.INTERVAL in fams:
        return TIMESTAMP
    if fams == {Family.DATE, Family.TIMESTAMP}:
        return TIMESTAMP  # date promotes (pg: date is midnight ts)
    if len(fams) == 1:
        return a
    raise TypeError(f"incompatible types {a} and {b}")


@dataclass
class ColumnSchema:
    name: str
    type: SQLType
    nullable: bool = True
    # For STRING columns: dictionary values (host-side); code i -> dictionary[i].
    dictionary: Optional[list] = None
    # Schema-change visibility: a column being added (catalog state
    # WRITE_ONLY) exists physically — DML writes it — but planners and
    # SELECT * must not see it until the descriptor goes PUBLIC.
    hidden: bool = False
    # stable catalog column id (ColumnDescriptor.col_id); 0 = unknown
    # (schemas built outside the catalog). Tags value-side KV payloads.
    cid: int = 0
    # DEFAULT: physical constant, or {"__seq__": name} for
    # DEFAULT nextval('name') (evaluated per inserted row)
    default: object = None


@dataclass
class TableSchema:
    name: str
    columns: list[ColumnSchema] = field(default_factory=list)
    primary_key: list[str] = field(default_factory=list)
    table_id: int = 0

    def column(self, name: str) -> ColumnSchema:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(f"column {name!r} not in table {self.name!r}")

    def column_index(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(f"column {name!r} not in table {self.name!r}")

    @property
    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]
