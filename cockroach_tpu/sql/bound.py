"""Typed (bound) expression tree — the output of semantic analysis.

The reference separates AST (sem/tree) from the typed/normalized memo
expressions the optimizer works on (pkg/sql/opt/memo). Our bound tree
is the physical lowering: every node carries an SQLType whose physical
dtype the executor compiles against, decimals are already scaled ints,
date literals are already day numbers, and string literals against
dictionary-encoded columns are already dictionary codes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .types import SQLType


class BExpr:
    type: SQLType


@dataclass
class BConst(BExpr):
    value: object  # physical scalar (int/float/bool) or None for NULL
    type: SQLType = None


@dataclass
class BParam(BExpr):
    """Runtime statement parameter i — a literal the statement-shape
    plan cache (exec/planparam.py) stripped out of the plan so
    literal-varying statements share one compiled entry. Compiles to a
    broadcast of ``ctx.params[index]`` (exec/expr.py); the value rides
    the dispatch as a replicated runtime scalar instead of baking into
    the trace. ``repr`` deliberately shows index+type only, so the
    parameterized plan's fingerprint is literal-independent."""
    index: int
    type: SQLType = None


@dataclass
class BCol(BExpr):
    name: str  # unique batch column name ("alias.col")
    type: SQLType = None


@dataclass
class BBin(BExpr):
    op: str
    left: BExpr
    right: BExpr
    type: SQLType = None


@dataclass
class BUnary(BExpr):
    op: str  # "-" | "not"
    operand: BExpr
    type: SQLType = None


@dataclass
class BBetween(BExpr):
    expr: BExpr
    lo: BExpr
    hi: BExpr
    negated: bool = False
    type: SQLType = None


@dataclass
class BInList(BExpr):
    expr: BExpr
    values: list  # physical constants
    negated: bool = False
    type: SQLType = None


@dataclass
class BIsNull(BExpr):
    expr: BExpr
    negated: bool = False
    type: SQLType = None


@dataclass
class BCase(BExpr):
    whens: list[tuple[BExpr, BExpr]] = field(default_factory=list)
    else_: Optional[BExpr] = None
    type: SQLType = None


@dataclass
class BCast(BExpr):
    expr: BExpr
    type: SQLType = None


@dataclass
class BCoalesce(BExpr):
    args: list[BExpr] = field(default_factory=list)
    type: SQLType = None


@dataclass
class BExtract(BExpr):
    part: str
    expr: BExpr
    type: SQLType = None


@dataclass
class BDictLookup(BExpr):
    """mask_table[codes] — a predicate over a dictionary-encoded string
    column, pre-evaluated against the dictionary on the host (binder.py);
    on device it is a single gather."""
    expr: BExpr
    table: object = None  # np.ndarray bool[len(dictionary)]
    type: SQLType = None


@dataclass
class BDictRemap(BExpr):
    """remap_table[codes] — translate one string column's dictionary
    codes into another column's code space (for cross-table string
    equality, e.g. join keys); absent values map to -1 (never match).
    ``null_table`` (optional bool[len(dict)], True=non-null) marks
    entries whose RESULT is SQL NULL — json/array operators like
    ``j->'missing'`` yield NULL per dictionary entry; it ANDs into the
    output validity on device."""
    expr: BExpr
    table: object = None  # np.ndarray int32[len(src dictionary)]
    type: SQLType = None
    null_table: object = None  # np.ndarray bool[len(src dictionary)]


@dataclass
class BFunc(BExpr):
    """N-ary elementwise builtin on device (pow, atan2, greatest, ...).
    The kernel table lives in exec/expr.py; the binder (sql/builtins.py)
    has already coerced arguments to the kernel's expected families."""
    name: str
    args: list[BExpr] = field(default_factory=list)
    type: SQLType = None


@dataclass
class BDictGather(BExpr):
    """value_table[codes] — a scalar function of a dictionary-encoded
    string column, pre-evaluated against the dictionary on the host
    (sql/builtins.py); on device it is one typed gather. Generalizes
    BDictLookup (bool tables) to arbitrary result types: length() is an
    int64 table, upper() is a code table into a NEW output dictionary
    (carried in .dictionary). ``null_table`` as in BDictRemap: entries
    whose result is SQL NULL (e.g. arr[i] past the end)."""
    expr: BExpr
    table: object = None  # np.ndarray[len(dictionary)] of type's dtype
    type: SQLType = None
    null_table: object = None  # np.ndarray bool[len(dictionary)]
    # output Dictionary for string results. repr=False: two binds of
    # the same expression build distinct Dictionary objects, and the
    # planner matches group exprs structurally by repr
    dictionary: object = field(default=None, repr=False)


@dataclass
class BAggRef(BExpr):
    """Placeholder for aggregate i's result in a post-aggregation
    expression (the reference's execbuilder renders final-stage AVG as
    SUM/COUNT the same way, physicalplan/aggregator_funcs.go)."""
    index: int
    type: SQLType = None


@dataclass
class BWinRef(BExpr):
    """Placeholder for window function i's result column (the Window
    plan node materializes it as batch column __win{i})."""
    index: int
    type: SQLType = None


@dataclass
class BoundWindow:
    """One window function instance: func(arg) OVER (partition, order).
    Offset carries the lag/lead distance."""
    func: str  # row_number|rank|dense_rank|lag|lead|first_value|
    #            last_value|sum|sum_int|count|count_rows|min|max|avg
    arg: Optional[BExpr]
    partition_by: list[BExpr] = field(default_factory=list)
    order_by: list[tuple[BExpr, bool]] = field(default_factory=list)
    offset: int = 1  # lag/lead distance
    type: SQLType = None


@dataclass
class BoundAgg:
    """One aggregate instance: func(arg) [distinct]."""
    func: str  # sum | count | count_rows | min | max | avg | sum_int
    arg: Optional[BExpr]
    type: SQLType = None
    distinct: bool = False
    # engine-measured bound on |arg| over the scanned table (0 =
    # unknown), valid only with arg_nonneg; lets an exact int64 group
    # SUM of a narrow column (quantities, scaled prices) ride ONE i32
    # scatter instead of 3 (ops/agg.py _group_sum_i64_limbs)
    arg_max_abs: int = 0
    arg_nonneg: bool = False


def walk(e: BExpr):
    yield e
    for child in _children(e):
        yield from walk(child)


def _children(e: BExpr):
    if isinstance(e, BBin):
        return [e.left, e.right]
    if isinstance(e, BUnary):
        return [e.operand]
    if isinstance(e, BBetween):
        return [e.expr, e.lo, e.hi]
    if isinstance(e, (BInList, BIsNull, BDictLookup, BDictRemap,
                      BDictGather)):
        return [e.expr]
    if isinstance(e, BFunc):
        return list(e.args)
    if isinstance(e, BCase):
        out = []
        for c, v in e.whens:
            out += [c, v]
        if e.else_ is not None:
            out.append(e.else_)
        return out
    if isinstance(e, BCast):
        return [e.expr]
    if isinstance(e, BCoalesce):
        return list(e.args)
    if isinstance(e, BExtract):
        return [e.expr]
    return []


def referenced_columns(e: BExpr) -> set[str]:
    return {n.name for n in walk(e) if isinstance(n, BCol)}
