"""Canonical text codec for datum-backed types (ARRAY, JSONB).

The reference's vectorized engine carries arrays and JSON as
datum-backed vectors of host objects (``pkg/col/coldata/datum_vec.go``,
``pkg/util/json``); every operator call crosses into per-element
tree.Datum code. On a TPU there is no per-element host call — instead
each distinct value is interned once into the column's dictionary
under a CANONICAL serialization, so:

- value equality  == code equality (GROUP BY / DISTINCT / joins on
  arrays and jsonb run as int32 device programs, nothing host-side),
- per-row operators (``j->>'k'``, ``arr[i]``, ``@>``) precompute one
  result per DICTIONARY ENTRY on the host and ride the existing
  BDictLookup/BDictRemap/BDictGather LUT nodes (exec/expr.py) — one
  gather or one-hot MXU matmul per batch.

Canonical forms:
- ARRAY: pg array literal text with no spaces — ``{1,2,3}``,
  ``{a,"b c",NULL}``. Strings are quoted only when needed, matching
  pg's array_out so the text round-trips through real clients.
- JSONB: ``json.dumps(..., sort_keys=True, separators=(",", ":"))``.
  Sorted keys give jsonb's object semantics (key order insensitive,
  duplicate keys keep the last) a unique text.
"""

from __future__ import annotations

import json
from typing import Optional

from .types import Family, SQLType

# characters that force quoting inside a pg array literal element
_NEEDS_QUOTE = set(',{}"\\ \t\n')


class DatumError(ValueError):
    pass


# -- JSONB ----------------------------------------------------------------

def canon_json(value) -> str:
    """Canonical jsonb text for an already-parsed JSON value."""
    return json.dumps(value, sort_keys=True, separators=(",", ":"))


def parse_json(text: str) -> object:
    try:
        return json.loads(text)
    except json.JSONDecodeError as e:
        raise DatumError(f"invalid JSON: {e}") from None


def canon_json_text(text: str) -> str:
    return canon_json(parse_json(text))


# -- ARRAY ----------------------------------------------------------------

def _elem_out(v, elem: SQLType) -> str:
    if v is None:
        return "NULL"
    f = elem.family
    if f == Family.BOOL:
        return "t" if v else "f"
    if f == Family.STRING:
        s = str(v)
        if s == "" or s.upper() == "NULL" or any(c in _NEEDS_QUOTE
                                                 for c in s):
            return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'
        return s
    if f == Family.FLOAT:
        return repr(float(v))
    if f == Family.DECIMAL:
        return f"{v:.{elem.scale}f}" if elem.scale else str(int(v))
    return str(int(v))


def canon_array(values: list, elem: SQLType) -> str:
    """Canonical pg-style array text from a list of python values."""
    return "{" + ",".join(_elem_out(v, elem) for v in values) + "}"


def _elem_in(tok: Optional[str], quoted: bool, elem: SQLType):
    if tok is None:
        return None
    if not quoted and tok.upper() == "NULL":
        return None
    f = elem.family
    try:
        if f == Family.BOOL:
            return tok.lower() in ("t", "true", "1")
        if f == Family.STRING:
            return tok
        if f == Family.FLOAT:
            return float(tok)
        if f == Family.DECIMAL:
            return float(tok)
        return int(tok)
    except ValueError:
        raise DatumError(
            f"invalid array element {tok!r} for {elem}") from None


def parse_array(text: str, elem: SQLType) -> list:
    """Parse a pg array literal ``{...}`` into python values."""
    s = text.strip()
    if not (s.startswith("{") and s.endswith("}")):
        raise DatumError(f"malformed array literal {text!r}")
    body = s[1:-1]
    out: list = []
    if body == "":
        return out
    i, n = 0, len(body)
    while i <= n:
        # one element: quoted or bare, ending at , or end
        if i < n and body[i] == '"':
            i += 1
            buf = []
            while i < n:
                c = body[i]
                if c == "\\" and i + 1 < n:
                    buf.append(body[i + 1])
                    i += 2
                    continue
                if c == '"':
                    i += 1
                    break
                buf.append(c)
                i += 1
            out.append(_elem_in("".join(buf), True, elem))
            if i < n and body[i] == ",":
                i += 1
            elif i >= n:
                break
        else:
            j = body.find(",", i)
            if j == -1:
                j = n
            tok = body[i:j].strip()
            if tok.startswith("{"):
                raise DatumError("nested arrays not supported")
            out.append(_elem_in(tok, False, elem) if tok else None)
            i = j + 1
            if j == n:
                break
    return out


def canon_array_text(text: str, elem: SQLType) -> str:
    return canon_array(parse_array(text, elem), elem)


# -- generic entry points -------------------------------------------------

def canon_text(text: str, ty: SQLType) -> str:
    """Canonicalize a literal's text for dictionary interning."""
    if ty.family == Family.JSON:
        return canon_json_text(text)
    if ty.family == Family.ARRAY:
        return canon_array_text(text, ty.elem)
    raise DatumError(f"{ty} is not a datum type")


def decode_text(text: str, ty: SQLType):
    """Stored canonical text -> python value for result rows."""
    if ty.family == Family.JSON:
        return parse_json(text)
    if ty.family == Family.ARRAY:
        return parse_array(text, ty.elem)
    raise DatumError(f"{ty} is not a datum type")
