"""SQL lexer.

The reference generates its scanner/grammar with goyacc
(pkg/sql/parser/sql.y, pkg/sql/scanner); a hand-rolled scanner + Pratt
parser covers our SQL subset without a generator toolchain
(SURVEY.md §7 step 2).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class Tok(enum.Enum):
    IDENT = "ident"
    NUMBER = "number"
    STRING = "string"
    OP = "op"
    KEYWORD = "keyword"
    EOF = "eof"


KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "between", "like", "ilike",
    "is", "null", "true", "false", "case", "when", "then", "else", "end",
    "cast", "join", "inner", "left", "right", "full", "outer", "cross",
    "on", "using", "asc", "desc", "distinct", "create", "table", "primary",
    "key", "insert", "into", "values", "update", "set", "delete", "drop",
    "interval", "date", "timestamp", "exists", "union", "all", "show",
    "explain", "begin", "commit", "rollback", "transaction", "index",
    "analyze", "if", "coalesce", "nulls", "first", "last", "default",
    "cluster", "setting", "extract", "substring", "backup", "restore",
    "to", "with", "over", "partition", "recursive", "rows", "range",
    "groups", "alter", "add", "column", "for", "intersect", "except",
    "upsert",
}

# longest first: the scanner takes the first startswith match
MULTICHAR_OPS = ["->>", "->", "@>", "<@", "?|", "?&",
                 "<=", ">=", "<>", "!=", "||", "::"]
SINGLE_OPS = "+-*/%(),.<>=;^[]?"


@dataclass
class Token:
    kind: Tok
    text: str
    pos: int

    def is_kw(self, *kws: str) -> bool:
        return self.kind == Tok.KEYWORD and self.text in kws

    def __repr__(self):
        return f"{self.kind.name}:{self.text!r}"


class LexError(Exception):
    pass


def lex(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if sql.startswith("--", i):  # line comment
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i)
            if j < 0:
                raise LexError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":  # escaped ''
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            if j >= n:
                raise LexError(f"unterminated string at {i}")
            toks.append(Token(Tok.STRING, "".join(buf), i))
            i = j + 1
            continue
        if c == '"':  # quoted identifier
            j = sql.find('"', i + 1)
            if j < 0:
                raise LexError(f"unterminated identifier at {i}")
            toks.append(Token(Tok.IDENT, sql[i + 1:j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j > i:
                    seen_exp = True
                    j += 1
                    if j < n and sql[j] in "+-":
                        j += 1
                else:
                    break
            toks.append(Token(Tok.NUMBER, sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            lw = word.lower()
            if lw in KEYWORDS:
                toks.append(Token(Tok.KEYWORD, lw, i))
            else:
                toks.append(Token(Tok.IDENT, lw, i))
            i = j
            continue
        matched = False
        for op in MULTICHAR_OPS:
            if sql.startswith(op, i):
                toks.append(Token(Tok.OP, op, i))
                i += len(op)
                matched = True
                break
        if matched:
            continue
        if c in SINGLE_OPS:
            toks.append(Token(Tok.OP, c, i))
            i += 1
            continue
        raise LexError(f"unexpected character {c!r} at {i}")
    toks.append(Token(Tok.EOF, "", n))
    return toks
