"""Device mesh management: the cluster topology of the TPU engine.

The reference partitions work by range leaseholder across nodes
(PartitionSpans, pkg/sql/distsql_physical_planner.go:1096) and moves
data over gRPC streams. Here the "nodes" of a co-scheduled flow are
mesh devices: scan spans shard across the `shards` axis, partial
aggregates merge over ICI collectives inside shard_map
(parallel/distagg.py), and only host<->host edges fall back to the
wire (server/, round 2+).

One axis suffices for the DistSQL-style data parallelism; joins use
broadcast (replicated build side). Multi-axis meshes (e.g. separate
axes for scan-parallel x partition-parallel shuffles) layer on later.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

SHARD_AXIS = "shards"


def pod_mesh() -> Mesh:
    """Mesh over THIS host's slice of the pod (round 15).

    On a real multi-host pod the rendezvous (parallel/multihost.py)
    is live and ``global_mesh()`` returns the hybrid ICI+DCN device
    order from ``mesh_utils.create_hybrid_device_mesh`` — collectives
    inside one slice ride ICI, the slice boundary rides DCN. On the
    CPU backend (tier-1) cross-process XLA computations don't exist,
    so this degrades to the host-local mesh: device collectives stay
    inside the host and the host tree (distsql merge_to/merge_children
    flows) carries the cross-host merge instead."""
    from cockroach_tpu.parallel import multihost
    return Mesh(np.asarray(multihost.global_mesh()), (SHARD_AXIS,))


def make_mesh(devices=None, n: Optional[int] = None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    if n is not None:
        if len(devs) < n:
            # Truncating silently would "test" an n-way sharding on one
            # device; demand the caller pin the platform first (e.g.
            # --xla_force_host_platform_device_count, tests/conftest.py).
            raise RuntimeError(
                f"make_mesh(n={n}): only {len(devs)} JAX devices available "
                f"on platform {devs[0].platform if devs else '?'}; refusing "
                "to silently truncate the mesh")
        devs = devs[:n]
    return Mesh(np.asarray(devs), (SHARD_AXIS,))


def shard_spec() -> PartitionSpec:
    return PartitionSpec(SHARD_AXIS)


def replicated_spec() -> PartitionSpec:
    return PartitionSpec()


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(SHARD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


class _DomainGate:
    """Two-mode execution window for one domain family (a root mesh
    and its sub-meshes). Entries of the SAME mode run concurrently
    (disjoint sub-meshes, or full-mesh calls serialized by their own
    dispatcher); entries of DIFFERENT modes exclude each other,
    because their device sets overlap: a full-mesh collective and a
    sub-mesh collective in flight at once can either starve the
    host-platform's fixed executor pool mid-rendezvous (each run
    holding some threads while waiting for the rest) or, on real
    chips, enqueue in different per-core orders. A waiting mode also
    blocks NEW entries of the active mode, so a steady sub-mesh
    stream cannot starve a full-mesh statement (and vice versa)."""

    def __init__(self):
        self._cv = threading.Condition()
        self._active = {"root": 0, "sub": 0}
        self._waiting = {"root": 0, "sub": 0}

    @contextlib.contextmanager
    def window(self, mode: str):
        other = "sub" if mode == "root" else "root"
        with self._cv:
            self._waiting[mode] += 1
            while self._active[other] > 0 or (
                    self._waiting[other] > 0 and self._active[mode] > 0):
                self._cv.wait()
            self._waiting[mode] -= 1
            self._active[mode] += 1
        try:
            yield
        finally:
            with self._cv:
                self._active[mode] -= 1
                self._cv.notify_all()


# device-id tuple -> (gate, mode); populated by MeshPool so that
# distagg.queued_collective_call can bracket every collective dispatch
# of a registered family. Meshes outside any pool family dispatch
# ungated (zero overhead until a pool exists).
_DOMAIN_GATES: dict = {}
_DOMAIN_GATES_LOCK = threading.Lock()


def _devkey(mesh) -> tuple:
    # gate families are per rendezvous domain: two host processes of
    # one pod each see local device ids 0..k-1, and a serialized gate
    # registry must never conflate host A's devices with host B's
    from cockroach_tpu.parallel import multihost
    topo = multihost.topology()
    dom = topo.process_id if topo is not None else -1
    return (dom,) + tuple(int(d.id) for d in mesh.devices.flat)


def execution_window(mesh):
    """Context manager bracketing a collective dispatch on ``mesh``
    (enqueue through completion), or None when the mesh belongs to no
    registered domain family."""
    if mesh is None:
        return None
    ent = _DOMAIN_GATES.get(_devkey(mesh))
    if ent is None:
        return None
    gate, mode = ent
    return gate.window(mode)


class MeshPool:
    """Partition a mesh's devices into disjoint sub-meshes per pow2 size.

    The sub-mesh dispatch plane (cf. Tailwind's multiplexing of many
    queries onto one accelerator pool, and the DataParallelPartitioner
    sub-mesh shape): an 8-device mesh yields two 4-device or four
    2-device domains. Disjoint device sets are disjoint rendezvous
    domains — each keeps its own ``_MeshDispatcher``
    (parallel/distagg.py keys by device-id tuple), so distributed
    programs on different sub-meshes execute truly concurrently
    instead of serializing on one dispatch thread.

    ``acquire(size)`` returns the least-loaded sub-mesh of that size
    (in-flight counters, incremented here and decremented by
    ``release``); results are bit-identical across sizes because the
    partial-aggregate merges are exact at any shard count.
    """

    def __init__(self, mesh: Mesh):
        from cockroach_tpu.parallel import multihost
        self.mesh = mesh
        # pod awareness: sub-mesh partitioning never crosses a DCN
        # boundary — the pool splits THIS host's devices, and the
        # cross-host dimension is the distsql merge tree's job
        self.num_hosts = multihost.num_hosts()
        devs = list(mesh.devices.flat)
        self._subs: dict[int, list[Mesh]] = {}
        size = len(devs) // 2
        while size >= 1:
            self._subs[size] = [
                Mesh(np.asarray(devs[i:i + size]), (SHARD_AXIS,))
                for i in range(0, len(devs), size)
            ]
            size //= 2
        self._inflight: dict[int, list[int]] = {
            s: [0] * len(ms) for s, ms in self._subs.items()}
        self._lock = threading.Lock()
        self._rr = 0
        self.dispatches = 0
        # register the domain family: two pools over the same devices
        # (two engines on one mesh) must share ONE gate, exactly as
        # they share one rendezvous domain per device set
        with _DOMAIN_GATES_LOCK:
            ent = _DOMAIN_GATES.get(_devkey(mesh))
            gate = ent[0] if ent is not None else _DomainGate()
            _DOMAIN_GATES[_devkey(mesh)] = (gate, "root")
            for ms in self._subs.values():
                for m in ms:
                    _DOMAIN_GATES[_devkey(m)] = (gate, "sub")

    def sizes(self) -> list[int]:
        return sorted(self._subs, reverse=True)

    def count(self, size: int) -> int:
        return len(self._subs.get(size, ()))

    def submeshes(self, size: int) -> list:
        return list(self._subs.get(size, ()))

    def occupancy(self) -> int:
        with self._lock:
            return sum(sum(v) for v in self._inflight.values())

    def acquire(self, size: int):
        """Least-loaded sub-mesh of ``size``; returns (mesh, token).
        Ties rotate round-robin — dispatch is asynchronous, so
        in-flight counts are often all zero and min() alone would pile
        every dispatch onto sub-mesh 0."""
        with self._lock:
            load = self._inflight[size]
            k = len(load)
            i = min(range(k), key=lambda j: (load[j], (j - self._rr) % k))
            self._rr = (i + 1) % k
            load[i] += 1
            self.dispatches += 1
            return self._subs[size][i], (size, i)

    def release(self, token) -> None:
        size, i = token
        with self._lock:
            self._inflight[size][i] = max(0, self._inflight[size][i] - 1)
