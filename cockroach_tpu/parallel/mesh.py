"""Device mesh management: the cluster topology of the TPU engine.

The reference partitions work by range leaseholder across nodes
(PartitionSpans, pkg/sql/distsql_physical_planner.go:1096) and moves
data over gRPC streams. Here the "nodes" of a co-scheduled flow are
mesh devices: scan spans shard across the `shards` axis, partial
aggregates merge over ICI collectives inside shard_map
(parallel/distagg.py), and only host<->host edges fall back to the
wire (server/, round 2+).

One axis suffices for the DistSQL-style data parallelism; joins use
broadcast (replicated build side). Multi-axis meshes (e.g. separate
axes for scan-parallel x partition-parallel shuffles) layer on later.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

SHARD_AXIS = "shards"


def make_mesh(devices=None, n: Optional[int] = None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    if n is not None:
        if len(devs) < n:
            # Truncating silently would "test" an n-way sharding on one
            # device; demand the caller pin the platform first (e.g.
            # --xla_force_host_platform_device_count, tests/conftest.py).
            raise RuntimeError(
                f"make_mesh(n={n}): only {len(devs)} JAX devices available "
                f"on platform {devs[0].platform if devs else '?'}; refusing "
                "to silently truncate the mesh")
        devs = devs[:n]
    return Mesh(np.asarray(devs), (SHARD_AXIS,))


def shard_spec() -> PartitionSpec:
    return PartitionSpec(SHARD_AXIS)


def replicated_spec() -> PartitionSpec:
    return PartitionSpec()


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(SHARD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
