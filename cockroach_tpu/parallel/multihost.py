"""Multi-host pod runtime: rendezvous, host topology, merge tree.

Round-15 tentpole. Everything through round 14 ran one process; this
module is the sanctioned home for every cross-host rendezvous entry
point (graftlint's collective-discipline rule flags the raw
``jax.distributed`` / ``multihost_utils`` / ``create_hybrid_device_mesh``
calls anywhere else, the same way it pins ``shard_map``/``pmap`` to
parallel/distagg.py).

Division of labor, forced by a backend reality: on the CPU backend
``jax.distributed.initialize`` happily rendezvouses N localhost
processes (shared KV store, barriers, global device view), but
cross-process XLA *computations* raise ``Multiprocess computations
aren't implemented on the CPU backend``. So:

- **control plane** — rendezvous, host identity, address exchange and
  barriers ride the jax.distributed coordinator KV store (works on
  every backend, localhost included);
- **data plane** — cross-host rows ride the repo's framed
  SocketTransport / DistSQL flows (rpc/context.py), with the
  hierarchical partial-agg merge (distsql/physical.py merge_plan)
  reducing bytes up a host tree instead of fanning flat into the
  gateway;
- **device collectives** stay host-local (psum inside the host's own
  mesh, distagg.make_distributed_fn unchanged); on real pods
  ``global_mesh()`` upgrades to ``create_hybrid_device_mesh`` so the
  within-slice axis rides ICI and the cross-slice axis rides DCN.

The per-host dispatcher process entry point is server/hostd.py; the
CPU-backed multi-process pytest harness (tests/test_multihost.py) and
``bench.py multihost_child`` both spawn it.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Callable, Optional

KV_PREFIX = "cockroach_tpu"
DEFAULT_FANOUT = 2
_KV_TIMEOUT_S = 60.0


@dataclass(frozen=True)
class HostTopology:
    """One host's view of the pod: who am I, how many of us, where is
    the coordinator, and the merge-tree shape."""

    process_id: int
    num_processes: int
    coordinator: str = ""
    fanout: int = DEFAULT_FANOUT

    @property
    def is_gateway(self) -> bool:
        return self.process_id == 0

    def parent(self) -> Optional[int]:
        return tree_parent(self.process_id, self.fanout)

    def children(self) -> list:
        return tree_children(self.process_id, self.num_processes,
                             self.fanout)


# module-global runtime state: one topology per process, guarded so
# back-to-back engines (and back-to-back tests in one process) never
# inherit a stale rendezvous — Engine.close tears this down.
_LOCK = threading.RLock()
_TOPOLOGY: Optional[HostTopology] = None
_INITIALIZED_JAX = False      # we own a live jax.distributed client
_LOCAL_KV: dict = {}          # single-process fallback KV store
_TEARDOWNS: list = []         # cross-host dispatcher/pump teardown fns


def topology() -> Optional[HostTopology]:
    return _TOPOLOGY


def is_active() -> bool:
    return _TOPOLOGY is not None


def num_hosts() -> int:
    t = _TOPOLOGY
    return t.num_processes if t is not None else 1


def init_distributed(coordinator: str = "", num_processes: int = 1,
                     process_id: int = 0,
                     fanout: int = DEFAULT_FANOUT) -> HostTopology:
    """Join (or create) the pod rendezvous. Idempotent: re-initializing
    with the same shape returns the live topology; a different shape
    while live is a programming error (stale rendezvous — call
    shutdown_distributed first).

    ``num_processes == 1`` is the degenerate pod: no coordinator is
    contacted and the KV store is an in-process dict, so single-host
    engines can use the same topology/merge-tree code paths with zero
    network dependencies.
    """
    global _TOPOLOGY, _INITIALIZED_JAX
    with _LOCK:
        if _TOPOLOGY is not None:
            if (_TOPOLOGY.num_processes == num_processes
                    and _TOPOLOGY.process_id == process_id):
                return _TOPOLOGY
            raise RuntimeError(
                "multihost already initialized as "
                f"{_TOPOLOGY.process_id}/{_TOPOLOGY.num_processes}; "
                "shutdown_distributed() before re-joining with "
                f"{process_id}/{num_processes}")
        topo = HostTopology(process_id=int(process_id),
                            num_processes=int(num_processes),
                            coordinator=coordinator,
                            fanout=max(1, int(fanout)))
        if topo.num_processes > 1:
            import jax
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=topo.num_processes,
                process_id=topo.process_id)
            _INITIALIZED_JAX = True
        _TOPOLOGY = topo
        return topo


def shutdown_distributed() -> None:
    """Tear down the pod runtime: run registered cross-host teardowns
    (dispatcher pumps, transports), release the jax.distributed client,
    and clear the topology. Idempotent and safe when never initialized,
    so Engine.close can always call it."""
    global _TOPOLOGY, _INITIALIZED_JAX
    with _LOCK:
        teardowns, _TEARDOWNS[:] = list(_TEARDOWNS), []
        for fn in reversed(teardowns):
            try:
                fn()
            except Exception:
                pass  # teardown is best-effort; state reset must win
        if _INITIALIZED_JAX:
            try:
                import jax
                jax.distributed.shutdown()
            except Exception:
                pass
            _INITIALIZED_JAX = False
        _TOPOLOGY = None
        _LOCAL_KV.clear()


def register_teardown(fn: Callable[[], None]) -> None:
    """Register a cross-host resource (flow transport, pump thread)
    for shutdown_distributed to reap — run LIFO, errors swallowed."""
    with _LOCK:
        _TEARDOWNS.append(fn)


# ---------------------------------------------------------------------------
# coordinator KV store: address exchange + barriers
# ---------------------------------------------------------------------------

def _client():
    """The live jax.distributed coordinator client, or None in the
    degenerate single-process pod."""
    if not _INITIALIZED_JAX:
        return None
    from jax._src import distributed as _jdist
    return _jdist.global_state.client


def kv_set(key: str, value: str) -> None:
    c = _client()
    if c is None:
        with _LOCK:
            _LOCAL_KV[f"{KV_PREFIX}/{key}"] = str(value)
        return
    c.key_value_set(f"{KV_PREFIX}/{key}", str(value))


def kv_get(key: str, timeout_s: float = _KV_TIMEOUT_S) -> str:
    c = _client()
    if c is None:
        return _LOCAL_KV[f"{KV_PREFIX}/{key}"]
    return c.blocking_key_value_get(f"{KV_PREFIX}/{key}",
                                    int(timeout_s * 1000))


def barrier(name: str, timeout_s: float = _KV_TIMEOUT_S) -> None:
    c = _client()
    if c is None:
        return
    c.wait_at_barrier(f"{KV_PREFIX}/{name}", int(timeout_s * 1000))


def publish_flow_addr(host: str, port: int) -> None:
    """Announce this host's DistSQL SocketTransport listener."""
    t = _TOPOLOGY
    if t is None:
        raise RuntimeError("multihost not initialized")
    kv_set(f"flowaddr/{t.process_id}", f"{host}:{port}")


def peer_flow_addrs(timeout_s: float = _KV_TIMEOUT_S) -> dict:
    """{process_id: (host, port)} for every host in the pod — blocks
    until each peer has published."""
    t = _TOPOLOGY
    if t is None:
        raise RuntimeError("multihost not initialized")
    out = {}
    for pid in range(t.num_processes):
        raw = kv_get(f"flowaddr/{pid}", timeout_s)
        host, _, port = raw.rpartition(":")
        out[pid] = (host, int(port))
    return out


# ---------------------------------------------------------------------------
# device mesh: hybrid on pods, host-local on the CPU harness
# ---------------------------------------------------------------------------

def global_mesh():
    """Device array for the pod-wide mesh.

    On accelerator backends this is ``create_hybrid_device_mesh`` —
    within-slice axis over ICI, cross-slice axis over DCN (SNIPPETS.md
    [1] pattern). On the CPU backend cross-process XLA computations are
    unimplemented, so each host keeps its local device mesh and the
    cross-host reduction rides the DistSQL merge tree instead; the
    returned devices are the host-local ones.
    """
    import jax
    if jax.default_backend() == "cpu" or num_hosts() <= 1:
        return jax.local_devices()
    import numpy as np
    from jax.experimental import mesh_utils
    local = len(jax.local_devices())
    devs = mesh_utils.create_hybrid_device_mesh(
        (local,), (num_hosts(),), devices=jax.devices())
    return list(np.asarray(devs).ravel())


# ---------------------------------------------------------------------------
# merge tree: deterministic parent/children over host process ids
# ---------------------------------------------------------------------------

def tree_parent(pid: int, fanout: int = DEFAULT_FANOUT) -> Optional[int]:
    """Parent host in the k-ary merge tree (None for the root/gateway).
    Heap layout: parent(i) = (i-1)//fanout."""
    if pid <= 0:
        return None
    return (pid - 1) // max(1, fanout)


def tree_children(pid: int, n: int,
                  fanout: int = DEFAULT_FANOUT) -> list:
    """Child hosts of ``pid`` in an n-host pod (heap layout)."""
    f = max(1, fanout)
    kids = [f * pid + 1 + j for j in range(f)]
    return [k for k in kids if k < n]


def merge_depth(n: int, fanout: int = DEFAULT_FANOUT) -> int:
    """Tree height: DCN hops a partial chunk takes worst-case to reach
    the gateway (1 for flat fan-in of <= fanout hosts)."""
    depth, pid = 0, n - 1
    while pid > 0:
        pid = tree_parent(pid, fanout)
        depth += 1
    return depth


def env_topology() -> Optional[HostTopology]:
    """Topology from COCKROACH_TPU_MULTIHOST_* env vars (hostd's
    children and bench subprocesses pass identity this way), or None
    when unset."""
    n = os.environ.get("COCKROACH_TPU_MULTIHOST_PROCS")
    if n is None:
        return None
    return HostTopology(
        process_id=int(os.environ.get("COCKROACH_TPU_MULTIHOST_ID", "0")),
        num_processes=int(n),
        coordinator=os.environ.get("COCKROACH_TPU_MULTIHOST_COORD", ""),
        fanout=int(os.environ.get("COCKROACH_TPU_MULTIHOST_FANOUT",
                                  str(DEFAULT_FANOUT))))
