"""Multi-host pod runtime: rendezvous, host topology, merge tree.

Round-15 tentpole. Everything through round 14 ran one process; this
module is the sanctioned home for every cross-host rendezvous entry
point (graftlint's collective-discipline rule flags the raw
``jax.distributed`` / ``multihost_utils`` / ``create_hybrid_device_mesh``
calls anywhere else, the same way it pins ``shard_map``/``pmap`` to
parallel/distagg.py).

Division of labor, forced by a backend reality: on the CPU backend
``jax.distributed.initialize`` happily rendezvouses N localhost
processes (shared KV store, barriers, global device view), but
cross-process XLA *computations* raise ``Multiprocess computations
aren't implemented on the CPU backend``. So:

- **control plane** — rendezvous, host identity, address exchange and
  barriers ride the jax.distributed coordinator KV store (works on
  every backend, localhost included);
- **data plane** — cross-host rows ride the repo's framed
  SocketTransport / DistSQL flows (rpc/context.py), with the
  hierarchical partial-agg merge (distsql/physical.py merge_plan)
  reducing bytes up a host tree instead of fanning flat into the
  gateway;
- **device collectives** stay host-local (psum inside the host's own
  mesh, distagg.make_distributed_fn unchanged); on real pods
  ``global_mesh()`` upgrades to ``create_hybrid_device_mesh`` so the
  within-slice axis rides ICI and the cross-slice axis rides DCN.

The per-host dispatcher process entry point is server/hostd.py; the
CPU-backed multi-process pytest harness (tests/test_multihost.py) and
``bench.py multihost_child`` both spawn it.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

KV_PREFIX = "cockroach_tpu"
DEFAULT_FANOUT = 2
_KV_TIMEOUT_S = 60.0


@dataclass(frozen=True)
class HostTopology:
    """One host's view of the pod: who am I, how many of us, where is
    the coordinator, and the merge-tree shape."""

    process_id: int
    num_processes: int
    coordinator: str = ""
    fanout: int = DEFAULT_FANOUT

    @property
    def is_gateway(self) -> bool:
        return self.process_id == 0

    def parent(self) -> Optional[int]:
        return tree_parent(self.process_id, self.fanout)

    def children(self) -> list:
        return tree_children(self.process_id, self.num_processes,
                             self.fanout)


# module-global runtime state: one topology per process, guarded so
# back-to-back engines (and back-to-back tests in one process) never
# inherit a stale rendezvous — Engine.close tears this down.
_LOCK = threading.RLock()
_TOPOLOGY: Optional[HostTopology] = None
_INITIALIZED_JAX = False      # we own a live jax.distributed client
_LOCAL_KV: dict = {}          # single-process fallback KV store
_TEARDOWNS: list = []         # cross-host dispatcher/pump teardown fns
_ELASTIC_CLIENT = None        # _KVClient to the elastic coordinator
_ELASTIC_SERVER = None        # _KVServer when this host coordinates
_MEMBERSHIP = None            # this host's Membership, when elastic
_MEMBERSHIP_FAULTS = None     # installed MembershipFaults (tests)


def topology() -> Optional[HostTopology]:
    return _TOPOLOGY


def is_active() -> bool:
    return _TOPOLOGY is not None


def num_hosts() -> int:
    m = _MEMBERSHIP
    if m is not None:
        try:
            return max(1, len(m.view().live))
        except Exception:
            pass        # KV torn down mid-scrape: fall through
    t = _TOPOLOGY
    return t.num_processes if t is not None else 1


def membership():
    """This host's Membership when the pod is elastic, else None."""
    return _MEMBERSHIP


def membership_faults():
    """The installed MembershipFaults, or None (production path)."""
    return _MEMBERSHIP_FAULTS


def install_membership_faults(faults) -> None:
    """Install (or clear, with None) membership-plane fault injection —
    the parallel/shuffle.install_link_faults idiom for the control
    plane: delayed/dropped heartbeats and stale-epoch lease claims.
    Consulted by Membership heartbeat loops and the shard-lease
    transition path (distsql/leases.py)."""
    global _MEMBERSHIP_FAULTS
    with _LOCK:
        _MEMBERSHIP_FAULTS = faults


def init_distributed(coordinator: str = "", num_processes: int = 1,
                     process_id: int = 0,
                     fanout: int = DEFAULT_FANOUT) -> HostTopology:
    """Join (or create) the pod rendezvous. Idempotent: re-initializing
    with the same shape returns the live topology; a different shape
    while live is a programming error (stale rendezvous — call
    shutdown_distributed first).

    ``num_processes == 1`` is the degenerate pod: no coordinator is
    contacted and the KV store is an in-process dict, so single-host
    engines can use the same topology/merge-tree code paths with zero
    network dependencies.
    """
    global _TOPOLOGY, _INITIALIZED_JAX
    with _LOCK:
        if _TOPOLOGY is not None:
            if (_TOPOLOGY.num_processes == num_processes
                    and _TOPOLOGY.process_id == process_id):
                return _TOPOLOGY
            raise RuntimeError(
                "multihost already initialized as "
                f"{_TOPOLOGY.process_id}/{_TOPOLOGY.num_processes}; "
                "shutdown_distributed() before re-joining with "
                f"{process_id}/{num_processes}")
        topo = HostTopology(process_id=int(process_id),
                            num_processes=int(num_processes),
                            coordinator=coordinator,
                            fanout=max(1, int(fanout)))
        if topo.num_processes > 1:
            import jax
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=topo.num_processes,
                process_id=topo.process_id)
            _INITIALIZED_JAX = True
        _TOPOLOGY = topo
        return topo


def shutdown_distributed() -> None:
    """Tear down the pod runtime: run registered cross-host teardowns
    (dispatcher pumps, transports), release the jax.distributed client,
    and clear the topology. Idempotent and safe when never initialized,
    so Engine.close can always call it."""
    global _TOPOLOGY, _INITIALIZED_JAX, _ELASTIC_CLIENT
    global _ELASTIC_SERVER, _MEMBERSHIP
    with _LOCK:
        teardowns, _TEARDOWNS[:] = list(_TEARDOWNS), []
        for fn in reversed(teardowns):
            try:
                fn()
            except Exception:
                pass  # teardown is best-effort; state reset must win
        if _MEMBERSHIP is not None:
            try:
                _MEMBERSHIP.stop_heartbeat()
            except Exception:
                pass
            _MEMBERSHIP = None
        if _ELASTIC_CLIENT is not None:
            try:
                _ELASTIC_CLIENT.close()
            except Exception:
                pass
            _ELASTIC_CLIENT = None
        if _ELASTIC_SERVER is not None:
            try:
                _ELASTIC_SERVER.close()
            except Exception:
                pass
            _ELASTIC_SERVER = None
        if _INITIALIZED_JAX:
            try:
                import jax
                jax.distributed.shutdown()
            except Exception:
                pass
            _INITIALIZED_JAX = False
        _TOPOLOGY = None
        _LOCAL_KV.clear()


def register_teardown(fn: Callable[[], None]) -> None:
    """Register a cross-host resource (flow transport, pump thread)
    for shutdown_distributed to reap — run LIFO, errors swallowed."""
    with _LOCK:
        _TEARDOWNS.append(fn)


# ---------------------------------------------------------------------------
# coordinator KV store: address exchange + barriers
# ---------------------------------------------------------------------------

def _client():
    """The live jax.distributed coordinator client, or None in the
    degenerate single-process pod."""
    if not _INITIALIZED_JAX:
        return None
    from jax._src import distributed as _jdist
    return _jdist.global_state.client


def kv_set(key: str, value: str) -> None:
    e = _ELASTIC_CLIENT
    if e is not None:
        e.set(f"{KV_PREFIX}/{key}", str(value))
        return
    c = _client()
    if c is None:
        with _LOCK:
            _LOCAL_KV[f"{KV_PREFIX}/{key}"] = str(value)
        return
    c.key_value_set(f"{KV_PREFIX}/{key}", str(value))


def kv_get(key: str, timeout_s: float = _KV_TIMEOUT_S) -> str:
    e = _ELASTIC_CLIENT
    if e is not None:
        deadline = time.monotonic() + timeout_s
        while True:
            v = e.try_get(f"{KV_PREFIX}/{key}")
            if v is not None:
                return v
            if time.monotonic() > deadline:
                raise KeyError(key)
            time.sleep(0.01)
    c = _client()
    if c is None:
        return _LOCAL_KV[f"{KV_PREFIX}/{key}"]
    return c.blocking_key_value_get(f"{KV_PREFIX}/{key}",
                                    int(timeout_s * 1000))


def kv_try_get(key: str) -> Optional[str]:
    """Non-blocking read: the value, or None when unset. Membership
    scans poll with this (a missing heartbeat must read as silence,
    not a 60s stall)."""
    e = _ELASTIC_CLIENT
    if e is not None:
        return e.try_get(f"{KV_PREFIX}/{key}")
    c = _client()
    if c is None:
        with _LOCK:
            return _LOCAL_KV.get(f"{KV_PREFIX}/{key}")
    try:
        return c.blocking_key_value_get(f"{KV_PREFIX}/{key}", 1)
    except Exception:
        return None


def kv_cas(key: str, expect: Optional[str], new: str) -> bool:
    """Atomic compare-and-set: write ``new`` iff the key currently
    holds ``expect`` (None = key absent). The epoch bump primitive —
    membership/lease transitions serialize on it, so a stale-epoch
    claim loses instead of double-owning a shard. Only the local and
    elastic KV backends support it; the jax.distributed store has no
    conditional write (elastic pods run their own coordinator)."""
    e = _ELASTIC_CLIENT
    if e is not None:
        return e.cas(f"{KV_PREFIX}/{key}", expect, new)
    c = _client()
    if c is None:
        with _LOCK:
            cur = _LOCAL_KV.get(f"{KV_PREFIX}/{key}")
            if cur != expect:
                return False
            _LOCAL_KV[f"{KV_PREFIX}/{key}"] = str(new)
            return True
    raise RuntimeError(
        "kv_cas requires the elastic (or in-process) KV backend; the "
        "jax.distributed store has no conditional write")


def kv_list(prefix: str) -> dict:
    """{suffix: value} for every key under ``prefix`` (membership and
    lease-table scans). Local/elastic backends only, like kv_cas."""
    e = _ELASTIC_CLIENT
    if e is not None:
        full = f"{KV_PREFIX}/{prefix}"
        return {k[len(full):]: v
                for k, v in e.list(full).items()}
    c = _client()
    if c is None:
        full = f"{KV_PREFIX}/{prefix}"
        with _LOCK:
            return {k[len(full):]: v for k, v in _LOCAL_KV.items()
                    if k.startswith(full)}
    raise RuntimeError(
        "kv_list requires the elastic (or in-process) KV backend")


def barrier(name: str, timeout_s: float = _KV_TIMEOUT_S) -> None:
    if _ELASTIC_CLIENT is not None:
        return   # elastic pods rendezvous through membership epochs
    c = _client()
    if c is None:
        return
    c.wait_at_barrier(f"{KV_PREFIX}/{name}", int(timeout_s * 1000))


def publish_flow_addr(host: str, port: int) -> None:
    """Announce this host's DistSQL SocketTransport listener."""
    t = _TOPOLOGY
    if t is None:
        raise RuntimeError("multihost not initialized")
    kv_set(f"flowaddr/{t.process_id}", f"{host}:{port}")


def peer_flow_addrs(timeout_s: float = _KV_TIMEOUT_S) -> dict:
    """{process_id: (host, port)} for every host in the pod — blocks
    until each peer has published."""
    t = _TOPOLOGY
    if t is None:
        raise RuntimeError("multihost not initialized")
    out = {}
    for pid in range(t.num_processes):
        raw = kv_get(f"flowaddr/{pid}", timeout_s)
        host, _, port = raw.rpartition(":")
        out[pid] = (host, int(port))
    return out


# ---------------------------------------------------------------------------
# device mesh: hybrid on pods, host-local on the CPU harness
# ---------------------------------------------------------------------------

def global_mesh():
    """Device array for the pod-wide mesh.

    On accelerator backends this is ``create_hybrid_device_mesh`` —
    within-slice axis over ICI, cross-slice axis over DCN (SNIPPETS.md
    [1] pattern). On the CPU backend cross-process XLA computations are
    unimplemented, so each host keeps its local device mesh and the
    cross-host reduction rides the DistSQL merge tree instead; the
    returned devices are the host-local ones.
    """
    import jax
    if jax.default_backend() == "cpu" or num_hosts() <= 1:
        return jax.local_devices()
    import numpy as np
    from jax.experimental import mesh_utils
    local = len(jax.local_devices())
    devs = mesh_utils.create_hybrid_device_mesh(
        (local,), (num_hosts(),), devices=jax.devices())
    return list(np.asarray(devs).ravel())


# ---------------------------------------------------------------------------
# merge tree: deterministic parent/children over host process ids
# ---------------------------------------------------------------------------

def tree_parent(pid: int, fanout: int = DEFAULT_FANOUT) -> Optional[int]:
    """Parent host in the k-ary merge tree (None for the root/gateway).
    Heap layout: parent(i) = (i-1)//fanout."""
    if pid <= 0:
        return None
    return (pid - 1) // max(1, fanout)


def tree_children(pid: int, n: int,
                  fanout: int = DEFAULT_FANOUT) -> list:
    """Child hosts of ``pid`` in an n-host pod (heap layout)."""
    f = max(1, fanout)
    kids = [f * pid + 1 + j for j in range(f)]
    return [k for k in kids if k < n]


def merge_depth(n: int, fanout: int = DEFAULT_FANOUT) -> int:
    """Tree height: DCN hops a partial chunk takes worst-case to reach
    the gateway (1 for flat fan-in of <= fanout hosts)."""
    depth, pid = 0, n - 1
    while pid > 0:
        pid = tree_parent(pid, fanout)
        depth += 1
    return depth


# ---------------------------------------------------------------------------
# elastic pod: socket KV coordinator + dynamic membership (round 16)
# ---------------------------------------------------------------------------
# jax.distributed.initialize pins num_processes at rendezvous, so a
# host can never JOIN a running jax-coordinated pod. Elastic pods
# therefore run their own coordinator: host 0 serves a tiny threaded
# TCP KV store (get/set/cas/list, JSON lines) and every host — founding
# or late-joining — talks to it through the kv_* entry points above.
# The data plane is unchanged (framed SocketTransport flows); only the
# rendezvous moves off jax, which elastic pods never needed anyway
# (device collectives stay host-local on every backend we run).

class _KVServer:
    """Threaded TCP KV coordinator: one JSON request per line, one
    response per line. Linearizable by construction (every op runs
    under one lock), which is what gives kv_cas its meaning."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._data: dict = {}
        self._mu = threading.Lock()
        self._sock = socket.create_server((host, port))
        self.addr = self._sock.getsockname()[:2]
        self._closed = False
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn) -> None:
        f = conn.makefile("rwb")
        try:
            for line in f:
                try:
                    req = json.loads(line)
                except ValueError:
                    break
                resp = self._apply(req)
                f.write(json.dumps(resp).encode() + b"\n")
                f.flush()
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _apply(self, req: dict) -> dict:
        op, k = req.get("op"), req.get("k")
        with self._mu:
            if op == "set":
                self._data[k] = req["v"]
                return {"ok": True}
            if op == "get":
                return {"ok": True, "v": self._data.get(k)}
            if op == "cas":
                cur = self._data.get(k)
                if cur != req.get("expect"):
                    return {"ok": False, "v": cur}
                self._data[k] = req["v"]
                return {"ok": True}
            if op == "list":
                return {"ok": True,
                        "kv": {kk: vv for kk, vv in self._data.items()
                               if kk.startswith(k)}}
        return {"ok": False, "error": f"bad op {op!r}"}

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class _KVClient:
    """One connection to the elastic coordinator; requests serialize
    on a lock (the membership/lease planes are low-rate control
    traffic — simplicity beats pipelining here)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self._mu = threading.Lock()
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        self._f = self._sock.makefile("rwb")

    def _request(self, req: dict) -> dict:
        with self._mu:
            self._f.write(json.dumps(req).encode() + b"\n")
            self._f.flush()
            line = self._f.readline()
        if not line:
            raise ConnectionError("elastic KV coordinator gone")
        return json.loads(line)

    def set(self, k: str, v: str) -> None:
        self._request({"op": "set", "k": k, "v": str(v)})

    def try_get(self, k: str) -> Optional[str]:
        return self._request({"op": "get", "k": k}).get("v")

    def cas(self, k: str, expect: Optional[str], new: str) -> bool:
        return bool(self._request({"op": "cas", "k": k,
                                   "expect": expect,
                                   "v": str(new)}).get("ok"))

    def list(self, prefix: str) -> dict:
        return self._request({"op": "list",
                              "k": prefix}).get("kv", {})

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


@dataclass
class MembershipFaults:
    """Control-plane fault injection (install_membership_faults) —
    the membership analogue of shuffle.install_link_faults. Fields
    apply only to hosts listed in ``hosts`` (empty = all)."""

    heartbeat_delay_s: float = 0.0   # each beat sleeps first
    heartbeat_drop: int = 0          # swallow the next N beats
    stale_epoch_claims: bool = False  # lease transitions bid epoch-1
    hosts: tuple = ()                # affected host ids (() = all)

    def applies(self, host_id: int) -> bool:
        return not self.hosts or host_id in self.hosts


@dataclass(frozen=True)
class MemberView:
    """One epoch's converged member view: every host that reads epoch
    ``e`` resolves the SAME live set, because the view is written to
    the KV *before* the epoch CAS that publishes it."""

    epoch: int
    live: tuple
    members: dict = field(default_factory=dict, compare=False)


class Membership:
    """Join/leave epochs with heartbeat liveness over the pod KV —
    the gossip-style generalization of server/node.py's live_peers
    gate. Every transition (join, drain, leave, expel) writes the
    next epoch's full member view under ``mb/view/<e+1>`` and then
    CASes ``mb/epoch`` from e to e+1; losers of the race recompute
    and retry, so concurrent churn converges without a coordinator
    thread. Heartbeats (``mb/hb/<id>``) are wall-clock-stamped and
    incarnation-tagged: a host that rejoins with the same id bumps
    its incarnation, and beats from the old incarnation are ignored
    (no zombie can keep a dead member alive)."""

    HEARTBEAT_INTERVAL_S = 0.25
    LIVENESS_WINDOW_S = 2.0

    def __init__(self, host_id: int, addr: str = "", metrics=None,
                 heartbeat_interval: Optional[float] = None,
                 liveness_window: Optional[float] = None):
        self.host_id = int(host_id)
        self.addr = addr
        self.interval = float(heartbeat_interval
                              if heartbeat_interval is not None
                              else self.HEARTBEAT_INTERVAL_S)
        self.window = float(liveness_window
                            if liveness_window is not None
                            else self.LIVENESS_WINDOW_S)
        self.incarnation = 0
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._metrics = metrics
        if metrics is not None:
            self.m_epoch = metrics.gauge(
                "cluster.membership.epoch",
                "current pod membership epoch as last observed by "
                "this host's membership plane")
            self.m_live = metrics.gauge(
                "cluster.membership.live",
                "live members in the last observed epoch view")
            self.m_joins = metrics.counter(
                "cluster.membership.joins",
                "membership join transitions this host performed")
            self.m_expels = metrics.counter(
                "cluster.membership.expels",
                "members this host expelled after heartbeat silence")
            self.m_rejoins = metrics.counter(
                "cluster.membership.rejoins",
                "joins that re-used an existing member id (new "
                "incarnation fences the old one's leases)")
            self.m_beats = metrics.counter(
                "cluster.membership.heartbeats",
                "liveness heartbeats this host published")

    # -- KV records -------------------------------------------------
    def _member_key(self, hid: int) -> str:
        return f"mb/member/{hid}"

    def _read_members(self) -> dict:
        out = {}
        for suffix, raw in kv_list("mb/member/").items():
            try:
                out[int(suffix)] = json.loads(raw)
            except (ValueError, TypeError):
                continue
        return out

    def epoch(self) -> int:
        return int(kv_try_get("mb/epoch") or 0)

    def view(self, epoch: Optional[int] = None) -> MemberView:
        """The epoch'd member view. With no argument, the CURRENT
        epoch's; with one, that epoch's (walks to the newest view at
        or below it, since not every epoch rewrites every record)."""
        e = self.epoch() if epoch is None else int(epoch)
        probe = e
        while probe > 0:
            raw = kv_try_get(f"mb/view/{probe}")
            if raw is not None:
                d = json.loads(raw)
                v = MemberView(epoch=e, live=tuple(d["live"]),
                               members=d.get("members", {}))
                self._note_view(v)
                return v
            probe -= 1
        return MemberView(epoch=e, live=())

    def _note_view(self, v: MemberView) -> None:
        if self._metrics is not None:
            self.m_epoch.set(v.epoch)
            self.m_live.set(len(v.live))

    def _transition(self, mutate) -> int:
        """Run one membership transition: mutate the member records,
        publish the resulting view for epoch e+1, CAS the epoch.
        Retries until its CAS wins (concurrent churn converges)."""
        while True:
            e = self.epoch()
            before = self._read_members()
            members = mutate(dict(before))
            # write only the records this mutation changed: a losing
            # racer that rewrote EVERY record would clobber the
            # winner's concurrent transition with its stale read
            for hid, rec in members.items():
                if before.get(hid) != rec:
                    kv_set(self._member_key(hid), json.dumps(rec))
            live = sorted(h for h, r in members.items()
                          if r.get("state") in ("live", "draining"))
            view = {"live": live,
                    "members": {str(h): members[h] for h in live}}
            kv_set(f"mb/view/{e + 1}", json.dumps(view))
            if kv_cas("mb/epoch", str(e) if e else None, str(e + 1)):
                self._note_view(MemberView(e + 1, tuple(live)))
                return e + 1

    # -- lifecycle --------------------------------------------------
    def join(self) -> int:
        """Enter the pod (state=live). Re-using an id that already has
        a member record — a crashed host coming back — bumps the
        incarnation so the old life's heartbeats and lease claims are
        fenced, not merged."""
        raw = kv_try_get(self._member_key(self.host_id))
        prev = json.loads(raw) if raw else None
        rejoin = prev is not None
        self.incarnation = (1 if prev is None
                            else int(prev.get("inc", 0)) + 1)
        self.beat()     # liveness (new incarnation) predates visibility

        def mutate(members: dict) -> dict:
            members[self.host_id] = {"state": "live",
                                     "inc": self.incarnation,
                                     "addr": self.addr}
            return members
        e = self._transition(mutate)
        if self._metrics is not None:
            self.m_joins.inc()
            if rejoin:
                self.m_rejoins.inc()
        return e

    def drain(self) -> int:
        """Announce an orderly exit: still serving (state=draining,
        still in the live view) but planners stop placing NEW shard
        leases here; leave() completes the exit once leases moved."""
        def mutate(members: dict) -> dict:
            rec = dict(members.get(self.host_id)
                       or {"inc": self.incarnation, "addr": self.addr})
            rec["state"] = "draining"
            members[self.host_id] = rec
            return members
        return self._transition(mutate)

    def leave(self) -> int:
        def mutate(members: dict) -> dict:
            rec = dict(members.get(self.host_id)
                       or {"inc": self.incarnation, "addr": self.addr})
            rec["state"] = "left"
            members[self.host_id] = rec
            return members
        e = self._transition(mutate)
        self.stop_heartbeat()
        return e

    def expel(self, hid: int) -> int:
        """Convict a silent member (state=dead): called by the
        failover path after its heartbeat went stale. The epoch bump
        is what fences the dead host's in-flight lease claims."""
        def mutate(members: dict) -> dict:
            rec = dict(members.get(hid) or {"inc": 0, "addr": ""})
            rec["state"] = "dead"
            members[hid] = rec
            return members
        e = self._transition(mutate)
        if self._metrics is not None:
            self.m_expels.inc()
        return e

    # -- liveness ---------------------------------------------------
    def beat(self) -> None:
        """Publish one liveness heartbeat (wall-clock stamped: hosts
        are separate processes, so monotonic clocks don't compare)."""
        f = _MEMBERSHIP_FAULTS
        if f is not None and f.applies(self.host_id):
            if f.heartbeat_drop > 0:
                f.heartbeat_drop -= 1
                return
            if f.heartbeat_delay_s > 0:
                time.sleep(f.heartbeat_delay_s)
        kv_set(f"mb/hb/{self.host_id}",
               json.dumps({"inc": self.incarnation, "t": time.time()}))
        if self._metrics is not None:
            self.m_beats.inc()

    def alive(self, hid: int, now: Optional[float] = None) -> bool:
        """Heartbeat-liveness of one member: fresh beat, matching
        incarnation, and a live/draining record in the current view."""
        v = self.view()
        if hid not in v.live:
            return False
        rec = v.members.get(str(hid), {})
        raw = kv_try_get(f"mb/hb/{hid}")
        if raw is None:
            return False
        hb = json.loads(raw)
        if int(hb.get("inc", -1)) != int(rec.get("inc", -2)):
            return False
        now = time.time() if now is None else now
        return (now - float(hb.get("t", 0.0))) <= self.window

    def suspects(self, hids) -> list:
        """The subset of ``hids`` whose heartbeats have gone stale —
        failover conviction candidates."""
        return [h for h in hids
                if h != self.host_id and not self.alive(h)]

    def expelled(self) -> bool:
        """Has some OTHER host convicted us? (Our record is dead, or
        a rejoin under our id outran us.) A live host that sees this
        must re-join with a fresh incarnation, not keep serving."""
        raw = kv_try_get(self._member_key(self.host_id))
        if raw is None:
            return False
        rec = json.loads(raw)
        return (rec.get("state") == "dead"
                or int(rec.get("inc", 0)) != self.incarnation)

    def start_heartbeat(self) -> None:
        if self._hb_thread is not None:
            return
        self._hb_stop.clear()

        def loop():
            while not self._hb_stop.wait(self.interval):
                try:
                    self.beat()
                except Exception:
                    return      # KV gone: the pod is tearing down
        self._hb_thread = threading.Thread(target=loop, daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        self._hb_stop.set()
        t, self._hb_thread = self._hb_thread, None
        if t is not None:
            t.join(timeout=2.0)


def init_elastic(host_id: int, kv_addr: str = "",
                 serve_kv: bool = False,
                 fanout: int = DEFAULT_FANOUT,
                 metrics=None,
                 heartbeat_interval: Optional[float] = None,
                 liveness_window: Optional[float] = None) -> Membership:
    """Join (or found, with serve_kv) an ELASTIC pod: no
    jax.distributed, no fixed num_processes — the kv_* entry points
    route to the socket coordinator and membership is epoch'd, so
    hosts can join or drain while statements run. Returns this host's
    Membership (not yet joined — callers join once their shards are
    streamed, so a joining host becomes visible only when servable).

    The degenerate in-process form (no kv_addr, no serve_kv) rides the
    _LOCAL_KV dict: N Membership instances in ONE process share it,
    which is exactly what the fast-lane churn tests need."""
    global _ELASTIC_CLIENT, _ELASTIC_SERVER, _MEMBERSHIP, _TOPOLOGY
    server = client = None
    if serve_kv:
        server = _KVServer()
        kv_addr = "%s:%d" % server.addr
    if kv_addr:
        h, _, p = kv_addr.rpartition(":")
        client = _KVClient(h or "127.0.0.1", int(p))
    m = Membership(host_id, metrics=metrics,
                   heartbeat_interval=heartbeat_interval,
                   liveness_window=liveness_window)
    with _LOCK:
        if _ELASTIC_SERVER is None:
            _ELASTIC_SERVER = server
        if client is not None:
            _ELASTIC_CLIENT = client
        _MEMBERSHIP = m
        if _TOPOLOGY is None:
            _TOPOLOGY = HostTopology(process_id=int(host_id),
                                     num_processes=1,
                                     coordinator=kv_addr,
                                     fanout=max(1, int(fanout)))
    return m


def elastic_kv_addr() -> str:
    """host:port of the coordinator this host serves ('' when it
    doesn't) — founding host 0 publishes this for late joiners."""
    s = _ELASTIC_SERVER
    return "%s:%d" % s.addr if s is not None else ""


def env_topology() -> Optional[HostTopology]:
    """Topology from COCKROACH_TPU_MULTIHOST_* env vars (hostd's
    children and bench subprocesses pass identity this way), or None
    when unset."""
    n = os.environ.get("COCKROACH_TPU_MULTIHOST_PROCS")
    if n is None:
        return None
    return HostTopology(
        process_id=int(os.environ.get("COCKROACH_TPU_MULTIHOST_ID", "0")),
        num_processes=int(n),
        coordinator=os.environ.get("COCKROACH_TPU_MULTIHOST_COORD", ""),
        fanout=int(os.environ.get("COCKROACH_TPU_MULTIHOST_FANOUT",
                                  str(DEFAULT_FANOUT))))
